"""Structured span tracing with a bounded ring buffer and Chrome-trace export.

The profiling story so far is per-layer µs tables (``train/profiling.py``)
and per-shard stats dicts (``data/transfer.py``) — numbers with no common
timeline. This tracer gives every subsystem one: a **span** is a named
``[t0, t1)`` interval with attributes, recorded on a **track** (a labeled
row in the viewer — one per pipeline stage, one per transfer thread, one
for the serve queue), and the whole event store exports to

- **JSONL** (one event per line — greppable, streamable), and
- **Chrome ``trace_event`` format** — a single JSON file Perfetto /
  ``chrome://tracing`` loads directly, with ``thread_name`` metadata so
  tracks appear labeled, not as anonymous tids.

Design constraints, in order:

1. **Disabled must be free.** ``get_tracer()`` is called on hot paths
   (per H2D chunk, per serve request, per pipeline microbatch). When
   tracing is off, ``span``/``begin``/``end``/``instant`` are swapped for
   module-level no-op *functions* (not methods — no ``self`` binding, no
   kwargs repack beyond the call itself): < 100 ns per span on a
   current CPython, asserted by ``tests/test_obs.py``.
2. **Bounded memory.** Events land in a ``deque(maxlen=capacity)`` — the
   ring buffer drops the OLDEST events under pressure, so a tracer left
   enabled for a week of serving costs a fixed few MB, never an OOM.
   ``deque.append`` is a single C-level op (GIL-atomic), so recording
   needs no lock and concurrent spans are never lost or torn.
3. **Injectable clock** (the ``ServeMetrics`` rule): tests pass a fake
   clock and assert span timestamps/durations by exact equality.
4. **Cross-thread spans.** The ``span()`` context manager covers the
   begin/end-on-one-thread case; ``begin()``/``end()`` return/consume an
   explicit handle for intervals that OPEN on one thread and CLOSE on
   another (a serve request enqueued by a submitter thread, dispatched by
   the batcher thread). The handle carries its track, so the event lands
   on the row of the *operation*, not whichever thread happened to end it.

Spans record **host-side intervals**. Around an async XLA dispatch a span
measures dispatch wall, not device compute — call sites that fence
(transfer-engine puts, sampled pipeline stages) get device-true spans, the
rest are annotated as dispatch spans in their name/attrs. That is the same
honesty line the rest of the repo draws (core/fence.py).
"""

from __future__ import annotations

import gzip as _gzip
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class _NullSpan:
    """Singleton no-op span/handle: context manager, ``set()`` sink."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


def _null_span(name, **attrs):
    """Disabled-path ``span``/``begin``/``instant``: a plain module-level
    function (the cheapest callable CPython has — no bound-method alloc)
    returning the shared null span."""
    return _NULL_SPAN


def _null_end(handle, **attrs):
    return None


def _null_record_span(name, t0_s, t1_s, *, track=None, **attrs):
    return None


class _Span:
    """Live span: context-manager for same-thread use, explicit handle for
    cross-thread ``begin``/``end``. ``track`` pins the display row; default
    is the recording thread's name."""

    __slots__ = ("_tracer", "name", "track", "attrs", "t0")

    def __init__(self, tracer: "Tracer", name: str, track: Optional[str],
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.track = track
        self.attrs = attrs
        self.t0 = tracer._clock()

    def set(self, **attrs) -> "_Span":
        """Attach attributes mid-span (e.g. bytes known only after the
        gather)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_Span":
        # re-stamp: construction may predate entry (begin() handles are
        # stamped at begin, but `with tracer.span(...)` should measure the
        # block, not the call)
        self.t0 = self._tracer._clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._tracer._record(self)
        return False


class Tracer:
    """Span recorder over a bounded ring buffer.

    ``enabled=False`` (the default for the process-global instance) swaps
    every recording entry point for a no-op function; ``set_enabled(True)``
    swaps the real ones back in. The swap is per-instance attribute
    assignment, so call sites holding the tracer object observe the change
    immediately and pay zero branching when disabled.
    """

    def __init__(self, *, capacity: int = 65536,
                 clock: Callable[[], float] = time.perf_counter,
                 enabled: bool = False):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._clock = clock
        self._epoch = clock()
        self._events: deque = deque(maxlen=capacity)
        self.capacity = capacity
        self.set_enabled(enabled)

    # -- enable/disable ----------------------------------------------------
    def set_enabled(self, on: bool) -> None:
        self.enabled = bool(on)
        if self.enabled:
            self.span = self._span
            self.begin = self._span  # same stamped handle, no CM entry needed
            self.end = self._end
            self.instant = self._instant
            self.record_span = self._record_span
        else:
            self.span = _null_span
            self.begin = _null_span
            self.end = _null_end
            self.instant = _null_span
            self.record_span = _null_record_span

    # -- recording (real implementations) ----------------------------------
    def _span(self, name: str, *, track: Optional[str] = None,
              **attrs) -> _Span:
        return _Span(self, name, track, attrs)

    def _end(self, handle: _Span, **attrs) -> None:
        """Close a ``begin()`` handle (cross-thread safe). Ending the null
        handle (begun while disabled) is a no-op, so an enable/disable flip
        mid-span never raises."""
        if handle is _NULL_SPAN or handle is None:
            return
        if attrs:
            handle.attrs.update(attrs)
        self._record(handle)

    def _record_span(self, name: str, t0_s: float, t1_s: float, *,
                     track: Optional[str] = None, **attrs) -> None:
        """Record an already-measured ``[t0_s, t1_s)`` interval (timestamps
        in this tracer's clock domain — ``time.perf_counter`` for the global
        instance). The replay entry point for intervals measured where the
        tracer can't run: feed-worker processes time their gather/augment/
        pack phases with ``perf_counter`` (CLOCK_MONOTONIC — one clock
        system-wide on Linux, so child stamps land on the parent timeline)
        and the parent replays them onto per-worker tracks."""
        self._events.append(
            (name, t0_s - self._epoch, max(t1_s - t0_s, 0.0),
             track if track is not None else threading.current_thread().name,
             attrs))

    def _instant(self, name: str, *, track: Optional[str] = None, **attrs):
        t = self._clock()
        self._events.append(
            (name, t - self._epoch, None,
             track if track is not None else threading.current_thread().name,
             attrs))
        return _NULL_SPAN

    def _record(self, span: _Span) -> None:
        t1 = self._clock()
        track = (span.track if span.track is not None
                 else threading.current_thread().name)
        # one GIL-atomic append — concurrent recorders never lose or tear
        # an event, and maxlen evicts the oldest under pressure
        self._events.append(
            (span.name, span.t0 - self._epoch, t1 - span.t0, track,
             span.attrs))

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        return len(self._events)

    def _events_list(self) -> list:
        """Reader-side copy of the ring buffer. ``list(deque)`` is one
        C-level call (atomic under the CPython GIL), but that is an
        implementation detail — retry on the 'deque mutated during
        iteration' RuntimeError so a live-recording tracer can always be
        exported mid-run (serving soaks export while request threads
        record)."""
        for _ in range(8):
            try:
                return list(self._events)
            except RuntimeError:  # concurrent append won the race; retry
                continue
        return list(self._events)  # last attempt unguarded: surface the bug

    def events(self) -> List[Dict[str, Any]]:
        """Copy of the buffer as dicts, oldest first. ``ts_s`` is seconds
        since the tracer epoch; ``dur_s`` is None for instant events."""
        return [{"name": n, "ts_s": ts, "dur_s": dur, "track": track,
                 "args": dict(attrs)}
                for (n, ts, dur, track, attrs) in self._events_list()]

    def clear(self) -> None:
        self._events.clear()
        self._epoch = self._clock()

    def span_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for (n, *_rest) in self._events_list():
            counts[n] = counts.get(n, 0) + 1
        return counts

    # -- exporters ---------------------------------------------------------
    def _write_jsonl(self, evs: list, path: str, gzip: bool) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # tmp sibling + os.replace: a crash mid-export must never leave a
        # torn artifact at the published path (flush_jsonl's drop-nothing
        # contract also depends on the failed write being invisible)
        tmp = f"{path}.tmp-{os.getpid()}"
        opener = (lambda p: _gzip.open(p, "wt")) if gzip else \
            (lambda p: open(p, "w"))
        try:
            with opener(tmp) as f:
                for (n, ts, dur, track, attrs) in evs:
                    f.write(json.dumps({"name": n, "ts_s": ts, "dur_s": dur,
                                        "track": track,
                                        "args": dict(attrs)}) + "\n")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def export_jsonl(self, path: str, *, gzip: bool = False) -> str:
        """One JSON object per line per event. ``gzip=True`` writes the
        stream gzip-compressed (span JSONL compresses ~10x — the names and
        tracks repeat every line)."""
        self._write_jsonl(self._events_list(), path, gzip)
        return path

    def flush_jsonl(self, path: str, *, gzip: bool = False) -> str:
        """Export, then drop EXACTLY the exported events — the
        periodic-drain entry point for long soaks: flush the ring to disk
        before eviction loses the oldest events, keep recording.

        Concurrency contract: events recorded while the file is being
        written are NOT lost — only events from the snapshot that reached
        disk are popped (checked by identity, so a saturated ring that
        evicted already-exported events during the write never makes the
        drain over-pop unexported ones), and concurrent appends land on
        the other end, so they ride the next flush. A failed write drops
        nothing. The tracer epoch is untouched, so timestamps stay
        monotone across flushes and spans straddling a flush stay valid
        (``clear()``, by contrast, restarts the timeline)."""
        evs = self._events_list()
        self._write_jsonl(evs, path, gzip)
        exported = set(map(id, evs))  # attrs dicts make tuples unhashable
        for _ in range(len(evs)):
            try:
                head = self._events.popleft()
            except IndexError:  # eviction raced us: already gone
                break
            if id(head) not in exported:
                # eviction consumed the rest of the exported prefix while
                # we drained; this event is newer than the snapshot — put
                # it back and stop (ring just shed one slot, so the
                # appendleft cannot evict)
                self._events.appendleft(head)
                break
        return path

    def export_chrome(self, path: str, *,
                      max_events: Optional[int] = None) -> str:
        """Chrome ``trace_event`` JSON (Perfetto / chrome://tracing).

        Complete spans become ``ph:"X"`` events (µs timestamps); instants
        become ``ph:"i"``. Each distinct track maps to a stable tid
        (first-seen order) with a ``thread_name`` metadata record, so the
        viewer shows labeled rows — "stage0", "h2d-xfer_0", "serve" — not
        anonymous thread ids.

        ``max_events`` caps the exported event count (viewers choke on
        multi-million-event files): the NEWEST ``max_events`` survive and
        the drop is explicit, never silent — a ``tracer.truncated`` instant
        at the head of the trace (on a ``tracer`` track) says exactly how
        many older events were cut, log-truncation style."""
        evs = self._events_list()
        truncated = 0
        if max_events is not None:
            if max_events < 1:
                raise ValueError(
                    f"max_events must be >= 1, got {max_events}")
            if len(evs) > max_events:
                truncated = len(evs) - max_events
                evs = evs[-max_events:]
                # an explicit head-of-trace note, stamped just before the
                # oldest surviving event so it sorts first in the viewer
                evs = [("tracer.truncated", evs[0][1], None, "tracer",
                        {"dropped_older_events": truncated,
                         "note": f"... {truncated} older events truncated "
                                 f"(max_events={max_events})"})] + evs
        tids: Dict[str, int] = {}
        out: List[Dict[str, Any]] = [{
            "ph": "M", "pid": 1, "tid": 0, "name": "process_name",
            "args": {"name": "dcnn_tpu"}}]
        for (_n, _ts, _dur, track, _a) in evs:
            if track not in tids:
                tids[track] = len(tids) + 1
                out.append({"ph": "M", "pid": 1, "tid": tids[track],
                            "name": "thread_name",
                            "args": {"name": track}})
        for (name, ts, dur, track, attrs) in evs:
            ev: Dict[str, Any] = {
                "name": name, "pid": 1, "tid": tids[track],
                "ts": round(ts * 1e6, 3), "cat": name.split(".", 1)[0],
                "args": {k: _json_safe(v) for k, v in attrs.items()},
            }
            if dur is None:
                ev["ph"] = "i"
                ev["s"] = "t"   # thread-scoped instant
            else:
                ev["ph"] = "X"
                ev["dur"] = round(dur * 1e6, 3)
            out.append(ev)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # same commit discipline as _write_jsonl: never a torn trace at the
        # path BENCH_OBS points the viewer at
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path


def _json_safe(v):
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return str(v)


# -- process-global tracer -------------------------------------------------
_GLOBAL_TRACER = Tracer(
    enabled=os.environ.get("DCNN_TRACE", "0") == "1")


def get_tracer() -> Tracer:
    """The process-global tracer every built-in call site records through.
    Disabled by default (no-op entry points, < 100 ns/span); enable with
    :func:`configure` or ``DCNN_TRACE=1``."""
    return _GLOBAL_TRACER


def configure(*, enabled: Optional[bool] = None,
              capacity: Optional[int] = None,
              clock: Optional[Callable[[], float]] = None) -> Tracer:
    """Reconfigure the process-global tracer IN PLACE (object identity is
    preserved — call sites that hoisted ``get_tracer()`` stay wired).
    A ``capacity`` change keeps the newest events that fit; a ``clock``
    change clears the buffer (events from two clock domains on one
    timeline would be garbage)."""
    t = _GLOBAL_TRACER
    if capacity is not None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        t._events = deque(t._events, maxlen=capacity)
        t.capacity = capacity
    if clock is not None:
        t._clock = clock
        t._events.clear()
        t._epoch = clock()
    if enabled is not None:
        t.set_enabled(enabled)
    return t
