"""Unified observability: one metrics registry + one span tracer for the
whole framework.

Before this subsystem the repo had three disconnected measurement surfaces
— ``train/profiling.py`` (per-layer fenced µs tables), ``serve/metrics.py``
(rolling serving percentiles), and the hand-rolled ``streaming_timeline``
stats in ``bench.py`` / ``data/transfer.py`` — each speaking its own
format. ``dcnn_tpu.obs`` is the shared layer they now report through:

- :mod:`~dcnn_tpu.obs.registry` — thread-safe Counter / Gauge / Histogram
  (fixed log-spaced buckets), O(1) recorders, ``snapshot()`` dict export
  and Prometheus text exposition; :func:`get_registry` is the
  process-global instance.
- :mod:`~dcnn_tpu.obs.tracer` — structured span tracing
  (``span("h2d.put", chunk=i)`` context manager, explicit
  ``begin``/``end`` for cross-thread spans), bounded ring buffer,
  exporters to JSONL and Chrome ``trace_event`` JSON (Perfetto-loadable,
  labeled tracks); :func:`get_tracer` is the process-global instance —
  a no-op (< 100 ns/span, asserted in tests) until enabled via
  :func:`configure` or ``DCNN_TRACE=1``.

Instrumented out of the box: ``Trainer`` epochs/steps/eval,
``data/transfer.py`` per-chunk H2D gathers+puts, the host-driven pipeline
(one track per stage) and compiled-pipeline dispatches, and the serving
stack's enqueue → dispatch → infer decomposition. ``BENCH_OBS=1 python
bench.py`` writes the Chrome trace artifact and embeds a telemetry block
in the bench JSON. Workflow guide: ``docs/observability.md``.

Since PR 6 the package also carries the EXPORT half of observability —
the pieces that let the outside world see a process (docs/observability.md
"External scraping"):

- :mod:`~dcnn_tpu.obs.server` — :class:`TelemetryServer`: a stdlib
  threaded HTTP server exposing ``/metrics`` (Prometheus text),
  ``/healthz`` (200/503 liveness + resilience checks) and ``/snapshot``
  (JSON registry + recent spans); wired into ``Trainer``
  (``TrainingConfig.metrics_port``) and ``DynamicBatcher.start_telemetry``
  so a future replica router can scrape every replica.
- :mod:`~dcnn_tpu.obs.exposition` — the ONE Prometheus text renderer
  both ``MetricsRegistry.prometheus`` and ``ServeMetrics.prometheus``
  share.
- :mod:`~dcnn_tpu.obs.xla` — compiled-executable introspection: XLA
  ``cost_analysis`` FLOPs/bytes (analytic MFU + roofline byte/FLOP),
  ``compile_total``/``compile_seconds_total`` counters, HBM watermark
  gauges. (Imports jax lazily — this package stays importable first.)
- :mod:`~dcnn_tpu.obs.regress` — the BENCH_r*.json trajectory regression
  gate behind ``benchmarks/compare.py`` and bench.py's ``regressions``
  block.

PR 12 made the tracer DISTRIBUTED and failures self-documenting:

- every span carries ``trace_id``/``span_id``/``parent_id``;
  ``Tracer.inject``/``Tracer.activate`` are the propagation contract
  every framed hop uses (``parallel/comm.py`` ships the carrier as the
  ``_trace`` meta key), so a router request or an elastic
  reconfiguration is ONE trace across processes;
- :mod:`~dcnn_tpu.obs.trace` — ``python -m dcnn_tpu.obs.trace`` merges
  per-process JSONL shards into one Perfetto-loadable Chrome trace
  (handshake-measured clock offsets) and inspects flight bundles;
- :mod:`~dcnn_tpu.obs.flight` — :class:`FlightRecorder`: atomic keep-K
  postmortem bundles (spans + metrics + healthz reasons + offending
  config) dumped on degradation edges; :func:`get_flight_recorder` is
  the process-global instance, off until ``DCNN_FLIGHT_DIR`` /
  :func:`configure_flight`.

PR 15 grew the MONITORING PLANE on top (docs/observability.md
"Monitoring plane"): retained history, rule evaluation, and fleet-wide
aggregation —

- :mod:`~dcnn_tpu.obs.tsdb` — :class:`TimeSeriesStore`: fixed-memory
  per-series ring buffers + a downsampled coarse tier, a PromQL-style
  over-time query API (``rate``/``delta``/``*_over_time``/
  histogram-quantile), atomic ``history.jsonl`` persistence, and
  :class:`TsdbSampler` (a cadence thread over the registry; sleep-free
  by hand in tests). ``python -m dcnn_tpu.obs.tsdb`` is the postmortem
  CLI (``report``/``export``/ASCII ``plot``).
- :mod:`~dcnn_tpu.obs.rules` — :class:`RuleEngine`: declarative
  recording rules and threshold/rate/absence alert rules with ``for_s``
  hold windows (inactive → pending → firing → resolved); firing edges
  bump ``alerts_fired_total``, export ``alert_state{rule=...}``, dump
  ``alert_firing`` flight bundles with the offending series' window,
  and degrade ``/healthz`` via :func:`rules_check`.
- :mod:`~dcnn_tpu.obs.fleet` — :class:`FleetAggregator`: scrapes N
  telemetry surfaces (HTTP via :class:`HttpScraper` or in-process),
  merges them into labeled fleet series (per-replica + sum/max) in its
  own tsdb, and serves ``/fleet`` + ``/alerts`` + a fleet ``/healthz``
  roll-up; the serving ``Autoscaler`` reads its replica signals through
  one of these.

This package is stdlib-only at import time (no jax import) — safe to
import from any layer, including before backend selection.
"""

from .flight import FlightRecorder, configure_flight, get_flight_recorder
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       get_registry)
from .server import (TelemetryServer, checkpoint_check, elastic_check,
                     pipeline_check, watchdog_check)
from .tracer import Tracer, configure, get_tracer

# monitoring-plane names resolve lazily (PEP 562): tsdb/rules/fleet stay
# runnable as `python -m dcnn_tpu.obs.tsdb` without runpy's
# already-imported warning, and the base import stays lean
_LAZY = {
    "TimeSeriesStore": "tsdb", "TsdbSampler": "tsdb",
    "RuleEngine": "rules", "AlertRule": "rules",
    "RecordingRule": "rules", "rules_check": "rules",
    "FleetAggregator": "fleet", "HttpScraper": "fleet",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)


__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "Tracer", "configure", "get_tracer",
    "TelemetryServer", "watchdog_check", "checkpoint_check",
    "elastic_check", "pipeline_check",
    "FlightRecorder", "get_flight_recorder", "configure_flight",
    "TimeSeriesStore", "TsdbSampler",
    "RuleEngine", "AlertRule", "RecordingRule", "rules_check",
    "FleetAggregator", "HttpScraper",
]
