"""Failure flight recorder: on-degradation postmortem bundles.

The fleet PRs 8–11 built detects failure well — watchdog stalls,
non-finite guards, replica deaths, canary rollbacks, autoscaler SLO
breaches all flip counters and ``/healthz`` — but by the time an operator
looks, the evidence is gone: the tracer ring has rotated past the
interesting spans, the registry shows only cumulative totals, and the
503's reasons were served once to a scraper that kept none of it. A
*flight recorder* (the black-box pattern from production serving systems)
closes that gap: subscribe to **degradation edges** and, at the moment
one fires, atomically dump a bounded bundle of everything a postmortem
wants —

- ``spans.jsonl`` — the newest tracer events (same shard format the
  merge CLI reads, so a bundle's spans drop straight into
  ``python -m dcnn_tpu.obs.trace merge`` next to the live shards);
- ``metrics.json`` — the registry snapshot (the counters AS OF the
  failure, not an hour later);
- ``healthz.json`` — the 503 body with machine-readable reasons, when
  the trigger came from a health transition;
- ``config.json`` — the offending configuration (training config, canary
  version, autoscaler verdict — whatever the trigger site owns);
- ``MANIFEST.json`` — trigger, timestamps, process identity, reasons.

Triggers wired in this repo (docs/observability.md "Flight recorder"):
``healthz_degraded`` (TelemetryServer 200→503 edge), ``watchdog_stall``
(StallWatchdog), ``nonfinite_guard`` (StepGuard bad-step streak start),
``replica_death`` (Router ejection — covers death AND failure-eviction),
``canary_rollback`` (ModelVersionManager), ``autoscale_slo_breach``
(Autoscaler breach-episode start).

Design rules:

- **Never raises.** :meth:`FlightRecorder.record` runs inside dispatch
  callbacks, health scrapes, and the autoscaler's never-raise tick; a
  recorder failure is counted (``flight_record_failures_total``) and
  swallowed — evidence capture must not take down the thing it observes.
- **Atomic + bounded.** Bundles are staged and published with
  ``resilience.atomic`` (``stage_dir`` → per-file ``write_file_atomic``
  → ``commit_dir``): a crash mid-dump can never leave a torn bundle a
  postmortem would half-trust. Keep-K retention (oldest deleted after
  each commit) bounds disk; a per-trigger ``min_interval_s`` cooldown
  bounds dump storms (a guard tripping every step records once per
  window, not once per step).
- **Injectable everything** (the obs rule): clock, wall clock, tracer,
  registry — the trigger-matrix tests run sleep-free against tmp dirs.
- **Off by default.** The process-global recorder
  (:func:`get_flight_recorder`) is disabled until ``DCNN_FLIGHT_DIR`` is
  set or :func:`configure_flight` names a directory, so every trigger
  site can call it unconditionally at zero cost. Each process should
  point at its own directory (bundle staging assumes single-process
  ownership of the dir, like CheckpointManager).

Surfaced on ``/snapshot`` via ``TelemetryServer.attach_flight`` (bundle
list: path, trigger, timestamp) and inspectable with
``python -m dcnn_tpu.obs.trace inspect <bundle>``.
"""

from __future__ import annotations

import json
import os
import shutil
import socket as _socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience.atomic import (
    commit_dir, stage_dir, sweep_stale_tmp, write_file_atomic,
)
from .tracer import _json_safe

#: Bundle directory name prefix — everything else in the flight dir
#: (tmp- staging, stray files) is ignored by listing and GC.
_BUNDLE_PREFIX = "fb-"


def _safe_slug(name: str) -> str:
    out = "".join(c if (c.isalnum() or c in "-_") else "_" for c in name)
    return out[:64] or "trigger"


class FlightRecorder:
    """Atomic keep-K postmortem bundle writer over one flight directory.

    ``directory=None`` disables the recorder: :meth:`record` returns
    ``None`` immediately and :meth:`bundles` returns ``[]`` — the state
    every process starts in unless ``DCNN_FLIGHT_DIR`` is set.
    """

    def __init__(self, directory: Optional[str] = None, *,
                 keep: int = 8, span_limit: int = 2048,
                 min_interval_s: float = 30.0,
                 tracer=None, registry=None, tsdb=None,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if span_limit < 0:
            raise ValueError(
                f"span_limit must be >= 0, got {span_limit}")
        self.directory = directory
        self.keep = keep
        self.span_limit = span_limit
        self.min_interval_s = min_interval_s
        self._tracer = tracer
        self._registry = registry
        self._tsdb = tsdb
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._last: Dict[str, float] = {}   # dcnn: guarded_by=_lock
        self._seq = 0                       # dcnn: guarded_by=_lock
        self._swept = False                 # dcnn: guarded_by=_lock
        # stale tmp- staging dirs from a preempted process are swept
        # lazily at the first record (the dir may not exist yet here)

    # -- wiring ------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def attach_tsdb(self, store) -> "FlightRecorder":
        """Wire a :class:`~dcnn_tpu.obs.tsdb.TimeSeriesStore`: every
        bundle gains ``history.jsonl`` — the store's retained window, so
        a postmortem shows the minutes BEFORE the trigger, not just the
        counters at it. ``None`` detaches (owners detach at shutdown so
        a dead run's store is not dumped into a later bundle)."""
        self._tsdb = store
        return self

    def _default_tracer(self):
        if self._tracer is not None:
            return self._tracer
        from .tracer import get_tracer
        return get_tracer()

    def _default_registry(self):
        if self._registry is not None:
            return self._registry
        from .registry import get_registry
        return get_registry()

    # -- recording ---------------------------------------------------------
    def record(self, trigger: str, *,
               reasons: Optional[List[str]] = None,
               health: Optional[Dict[str, Any]] = None,
               config: Optional[Dict[str, Any]] = None,
               extra: Optional[Dict[str, Any]] = None,
               registry=None, tracer=None) -> Optional[str]:
        """Dump one postmortem bundle for ``trigger``; returns the
        committed bundle path, or ``None`` when disabled, suppressed by
        the per-trigger cooldown, or failed (failures are counted, never
        raised — see the module docstring)."""
        if not self.directory:
            return None
        try:
            return self._record(trigger, reasons, health, config, extra,
                                registry, tracer)
        except Exception:
            try:
                self._default_registry().counter(
                    "flight_record_failures_total",
                    "flight-recorder dumps that failed").inc()
            except Exception:
                pass
            return None

    def _record(self, trigger, reasons, health, config, extra,
                registry, tracer) -> Optional[str]:
        now = self._clock()
        with self._lock:
            last = self._last.get(trigger)
            if last is not None and now - last < self.min_interval_s:
                self._default_registry().counter(
                    "flight_records_suppressed_total",
                    "flight dumps suppressed by the per-trigger "
                    "cooldown").inc()
                return None
            self._last[trigger] = now
            self._seq += 1
            seq = self._seq
            sweep = not self._swept
            self._swept = True
        try:
            return self._dump(trigger, reasons, health, config, extra,
                              registry, tracer, now, seq, sweep)
        except BaseException:
            # the cooldown stamp was a CLAIM, not a record: a failed
            # dump (unwritable dir, ENOSPC) must not suppress the next
            # episode's evidence for min_interval_s — release it so the
            # next edge retries (unless a concurrent success re-stamped)
            with self._lock:
                if self._last.get(trigger) == now:
                    del self._last[trigger]
            raise

    def _dump(self, trigger, reasons, health, config, extra,
              registry, tracer, now, seq, sweep) -> Optional[str]:
        os.makedirs(self.directory, exist_ok=True)
        if sweep:
            sweep_stale_tmp(self.directory)
        trc = tracer if tracer is not None else self._default_tracer()
        reg = registry if registry is not None else self._default_registry()
        t_wall = self._wall()
        spans = trc.events()[-self.span_limit:] if self.span_limit else []
        manifest = {
            "trigger": trigger,
            "t_wall": t_wall,
            "t_mono": now,
            "host": _socket.gethostname(),
            "pid": os.getpid(),
            "process": getattr(trc, "process_name", None),
            "reasons": list(reasons or []),
            "spans": len(spans),
            "tracer_enabled": getattr(trc, "enabled", False),
        }
        name = f"{_BUNDLE_PREFIX}{int(t_wall * 1000):015d}-{seq:04d}-" \
               f"{_safe_slug(trigger)}"
        tsdb = self._tsdb
        if tsdb is not None:
            try:
                history = tsdb.to_jsonl_bytes()
            except Exception:
                history = None  # a broken store must not cost the bundle
            manifest["history_series"] = (len(tsdb.series_names())
                                          if history is not None else None)
        else:
            history = None
        tmp = stage_dir(self.directory)
        try:
            self._stage_json(tmp, "MANIFEST.json", manifest)
            self._stage_spans(tmp, trc, spans)
            if history is not None:
                write_file_atomic(os.path.join(tmp, "history.jsonl"),
                                  history)
            self._stage_json(tmp, "metrics.json", reg.snapshot())
            if health is not None:
                self._stage_json(tmp, "healthz.json", health)
            if config is not None:
                self._stage_json(tmp, "config.json", config)
            if extra is not None:
                self._stage_json(tmp, "extra.json", extra)
            final = os.path.join(self.directory, name)
            commit_dir(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise  # _record's outer handler releases the cooldown stamp
        self._gc()
        reg.counter("flight_records_total",
                    "flight-recorder bundles committed").inc()
        reg.gauge("flight_bundles",
                  "bundles currently retained").set(len(self._list_dirs()))
        return final

    @staticmethod
    def _stage_json(tmp: str, name: str, obj: Any) -> None:
        data = json.dumps(obj, default=str, indent=1).encode("utf-8")
        write_file_atomic(os.path.join(tmp, name), data)

    @staticmethod
    def _stage_spans(tmp: str, trc, spans: List[Dict[str, Any]]) -> None:
        """Bundle spans in the JSONL shard format (header + one event
        per line) so the merge CLI reads a bundle's spans exactly like a
        live shard."""
        lines = [json.dumps({"shard": trc.shard_meta()})] if hasattr(
            trc, "shard_meta") else []
        for ev in spans:
            ev = dict(ev)
            ev["args"] = {k: _json_safe(v)
                          for k, v in dict(ev.get("args") or {}).items()}
            lines.append(json.dumps(ev, default=str))
        write_file_atomic(os.path.join(tmp, "spans.jsonl"),
                          ("\n".join(lines) + "\n").encode("utf-8"))

    # -- retention / listing -----------------------------------------------
    def _list_dirs(self) -> List[str]:
        if not self.directory or not os.path.isdir(self.directory):
            return []
        return sorted(n for n in os.listdir(self.directory)
                      if n.startswith(_BUNDLE_PREFIX))

    def _gc(self) -> None:
        names = self._list_dirs()
        for n in names[:max(len(names) - self.keep, 0)]:
            shutil.rmtree(os.path.join(self.directory, n),
                          ignore_errors=True)

    def bundles(self) -> List[Dict[str, Any]]:
        """Retained bundles, newest first: ``{path, trigger, t_wall,
        reasons}`` — the block ``/snapshot`` lists so an operator finds
        the evidence from the same surface that showed the 503."""
        out: List[Dict[str, Any]] = []
        for n in reversed(self._list_dirs()):
            path = os.path.join(self.directory, n)
            entry: Dict[str, Any] = {"path": path}
            try:
                with open(os.path.join(path, "MANIFEST.json")) as f:
                    md = json.load(f)
                entry.update(trigger=md.get("trigger"),
                             t_wall=md.get("t_wall"),
                             reasons=md.get("reasons", []))
            except (OSError, ValueError):
                # name carries enough to find it; a torn manifest cannot
                # exist (commit is atomic) but a deleted-mid-list one can
                entry["trigger"] = n.rsplit("-", 1)[-1]
            out.append(entry)
        return out


# -- process-global recorder -------------------------------------------------
_GLOBAL_FLIGHT = FlightRecorder(
    os.environ.get("DCNN_FLIGHT_DIR") or None,
    keep=int(os.environ.get("DCNN_FLIGHT_KEEP", "8")))


def get_flight_recorder() -> FlightRecorder:
    """The process-global flight recorder every built-in trigger site
    records through. Disabled (``record`` → None) until
    ``DCNN_FLIGHT_DIR`` is set or :func:`configure_flight` names a
    directory."""
    return _GLOBAL_FLIGHT


def resolve_flight_recorder(flight: Optional[FlightRecorder] = None
                            ) -> FlightRecorder:
    """THE trigger-site fallback: an explicitly injected recorder wins
    (tests, per-component dirs), else the process-global one. Every
    built-in trigger site resolves through here so the lazy-import
    fallback cannot drift between call sites."""
    return flight if flight is not None else _GLOBAL_FLIGHT


def configure_flight(directory: Optional[str] = None, *,
                     keep: Optional[int] = None,
                     span_limit: Optional[int] = None,
                     min_interval_s: Optional[float] = None,
                     tsdb=None) -> FlightRecorder:
    """Reconfigure the process-global recorder IN PLACE (identity
    preserved — trigger sites that hoisted it stay wired). Passing a
    ``directory`` enables it; ``None`` leaves the current one. ``tsdb``
    attaches a history store (see :meth:`FlightRecorder.attach_tsdb`)."""
    r = _GLOBAL_FLIGHT
    if directory is not None:
        r.directory = directory
        with r._lock:
            r._swept = False  # new dir: sweep its stale tmp- on first use
    if tsdb is not None:
        r.attach_tsdb(tsdb)
    if keep is not None:
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        r.keep = keep
    if span_limit is not None:
        r.span_limit = span_limit
    if min_interval_s is not None:
        r.min_interval_s = min_interval_s
    return r
