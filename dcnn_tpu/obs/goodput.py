"""Goodput accounting: attribute every second of wall time to a bucket.

The tracer (PR 3/12) records *what happened*; this module says *where the
time went*. It consumes the span stream and attributes a window of wall
time to exclusive buckets — ``compute``, ``eval``, ``compile``,
``checkpoint``, ``recovery``, ``h2d``, ``feed_stall`` — plus the residual
``unattributed``. Overlap between spans (a worker packing while the
device steps, an H2D put under a dispatch) is resolved with the same
interval-union math as :func:`dcnn_tpu.data.transfer.union_seconds`,
with a fixed claim priority (:data:`CLAIM_ORDER`): compute claims first,
so feed/transfer work that overlaps compute is *hidden* latency and only
the exposed remainder counts as a stall. ``goodput_fraction`` is
``compute / wall`` — the fraction of the window the device spent on the
thing the run exists to do.

Three layers, each usable alone:

- :func:`attribute` / :func:`summarize` — pure functions over an event
  list (``Tracer.events()`` dicts or a replayed JSONL export): the bench
  ``goodput`` block and the BENCH_r05 replay test use these.
- :class:`GoodputLedger` — binds a tracer + registry and publishes the
  window as gauges (``goodput_fraction``, ``goodput_<bucket>_seconds``,
  ``goodput_h2d_gbps`` from per-put ``bytes`` attrs, ``mfu_live`` from
  the ``obs/xla.py`` cost × the measured step rate).
- :class:`BottleneckClassifier` + :class:`GoodputMonitor` — the rolling
  verdict (feed-bound / compute-bound / compile-bound / io-bound /
  healthy) with dwell + exit-margin hysteresis, fed into a
  :class:`~dcnn_tpu.obs.tsdb.TimeSeriesStore` for the shipped
  :func:`~dcnn_tpu.obs.rules.goodput_alert_rules`, plus the ``/goodput``
  endpoint and the hook into :mod:`~dcnn_tpu.obs.anomaly`.

:data:`SPAN_BUCKETS` is the NORMATIVE span→bucket table (mirrored in
docs/observability.md). The GP01 lint (``python -m dcnn_tpu.analysis
--span-coverage``) fails tier-1 when a span recorded anywhere in the
package is missing from it, so new instrumentation cannot silently
become ``unattributed``. A value of ``None`` marks a *structural* span —
a container whose children carry the time (``train.epoch``,
``h2d.shard``, ``pipe.batch``) — deliberately excluded from attribution
so the parent/child double count never happens.

Stdlib-only at import time, like the rest of ``dcnn_tpu.obs``.
"""

from __future__ import annotations

import threading
from typing import (Any, Callable, Dict, List, Mapping, Optional, Sequence,
                    Tuple)

from .registry import MetricsRegistry, get_registry
from .tracer import Tracer, get_tracer

# Attribution buckets, and the order in which they claim wall time.
# Earlier buckets win overlap: compute first (overlapped feed/H2D work is
# hidden, not a stall), feed_stall last (what it keeps is by construction
# *exposed* host-feed time — the true stall).
BUCKETS: Tuple[str, ...] = ("compute", "eval", "compile", "checkpoint",
                            "recovery", "h2d", "feed_stall")
CLAIM_ORDER: Tuple[str, ...] = BUCKETS

# The normative span→bucket map. None = structural/container span whose
# time is carried by its children (excluded from attribution). Keys may
# be globs; the GP01 lint matches recorded span names against them.
SPAN_BUCKETS: Dict[str, Optional[str]] = {
    # training step loop — the device doing the actual work
    "train.step": "compute",
    "train.chunk": "compute",
    "train.resident_epoch": "compute",
    "train.shard_dispatch": "compute",
    "train.eval": "eval",
    "train.epoch": None,
    # elastic data parallelism
    "elastic.step": "compute",
    "elastic.rebuild": "recovery",
    "elastic.reconfigure": "recovery",
    "elastic.restore": "recovery",
    # host-driven / compiled pipeline
    "pipe.fwd": "compute",
    "pipe.bwd": "compute",
    "pipe.commit": "compute",
    "pipe.recover": "recovery",
    "pipe.batch": None,
    "pipe.compiled.step": "compute",
    # host→device transfer plane
    "h2d.put": "h2d",
    "h2d.put_labels": "h2d",
    "h2d.gather": "feed_stall",
    "h2d.shard": None,
    # feed worker pool (replayed via record_span)
    "feed.gather": "feed_stall",
    "feed.augment": "feed_stall",
    "feed.pack": "feed_stall",
    # serving
    "serve.infer": "compute",
    "serve.dispatch": "compute",
    "serve.queue": "feed_stall",
    "serve.compile": "compile",
    "serve.warmup": "compile",
    "serve.request": None,
    "serve.shed": None,
    # continuous-batching decode (serve/decode.py)
    "decode.step": "compute",
    # checkpointing
    "checkpoint.save": "checkpoint",
    "checkpoint.restore": "checkpoint",
    "checkpoint.snapshot": "checkpoint",
    # observability's own artifacts
    "profiler.xprof": None,
    "tracer.truncated": None,
}

# Spans whose `bytes` attr feeds the live H2D bandwidth gauge.
_H2D_BYTE_SPANS = ("h2d.put", "h2d.put_labels")
# Spans that count toward the live step rate (train.chunk carries a
# `steps` attr covering its inner loop).
_STEP_SPANS = ("train.step", "elastic.step")

Interval = Tuple[float, float]


def _merge(intervals: Sequence[Interval]) -> List[Interval]:
    """Sort + coalesce — same union math as ``transfer.union_seconds``."""
    out: List[Interval] = []
    for s, e in sorted(i for i in intervals if i[1] > i[0]):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _subtract(intervals: Sequence[Interval],
              claimed: Sequence[Interval]) -> List[Interval]:
    """``intervals - claimed``; both inputs must be merged/sorted."""
    out: List[Interval] = []
    ci = 0
    for s, e in intervals:
        while ci < len(claimed) and claimed[ci][1] <= s:
            ci += 1
        j = ci
        while s < e and j < len(claimed) and claimed[j][0] < e:
            cs, ce = claimed[j]
            if cs > s:
                out.append((s, cs))
            s = max(s, ce)
            j += 1
        if s < e:
            out.append((s, e))
    return out


def _total(intervals: Sequence[Interval]) -> float:
    return sum(e - s for s, e in intervals)


def bucket_of(name: str,
              mapping: Mapping[str, Optional[str]] = SPAN_BUCKETS
              ) -> Optional[str]:
    """Bucket for a span name, or None (structural or unknown). Exact
    match first, then glob keys — mirrors the GP01 lint's matching."""
    if name in mapping:
        return mapping[name]
    import fnmatch
    for pat, b in mapping.items():
        if "*" in pat and fnmatch.fnmatchcase(name, pat):
            return b
    return None


def attribute(events: Sequence[Mapping[str, Any]], *,
              t0: Optional[float] = None,
              t1: Optional[float] = None) -> Dict[str, Any]:
    """Exclusive wall-time attribution over ``Tracer.events()``-shaped
    dicts. Window defaults to the span extent (min start .. max end of
    non-structural spans); spans are clipped to it. Returns the ledger
    doc: wall/bucket/unattributed seconds and ``goodput_fraction``."""
    spans: List[Tuple[float, float, str]] = []
    for ev in events:
        dur = ev.get("dur_s")
        if dur is None:
            continue
        b = bucket_of(str(ev.get("name", "")))
        if b is None:
            continue
        s = float(ev["ts_s"])
        e = s + float(dur)
        if e > s:
            spans.append((s, e, b))
    if t0 is None:
        t0 = min((s for s, _, _ in spans), default=0.0)
    if t1 is None:
        t1 = max((e for _, e, _ in spans), default=t0)
    wall = max(0.0, float(t1) - float(t0))
    buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
    claimed: List[Interval] = []
    for b in CLAIM_ORDER:
        ivs = _merge([(max(s, t0), min(e, t1))
                      for s, e, bb in spans if bb == b])
        free = _merge(_subtract(ivs, claimed))
        buckets[b] = _total(free)
        claimed = _merge(list(claimed) + free)
    attributed = _total(claimed)
    return {
        "t0_s": float(t0), "t1_s": float(t1), "wall_s": wall,
        "buckets": buckets,
        "attributed_s": attributed,
        "unattributed_s": max(0.0, wall - attributed),
        "goodput_fraction": (buckets["compute"] / wall) if wall > 0 else 0.0,
    }


# Classifier thresholds (fraction of window wall). Entry order is the
# rule order: compile dominates (a recompile storm shows up under every
# other symptom), then exposed feed (feed_stall + h2d — BENCH_r05's
# put-dominated wall IS feed-bound), then checkpoint/recovery, then a
# compute-dominated window is (boringly, correctly) compute-bound.
_STATE_FRACS: Dict[str, Tuple[str, ...]] = {
    "compile_bound": ("compile",),
    "feed_bound": ("feed_stall", "h2d"),
    "io_bound": ("checkpoint", "recovery"),
    "compute_bound": ("compute", "eval"),
}
_ENTER_FRAC: Dict[str, float] = {
    "compile_bound": 0.30,
    "feed_bound": 0.50,
    "io_bound": 0.50,
    "compute_bound": 0.70,
}
STATES: Tuple[str, ...] = ("healthy", "feed_bound", "compute_bound",
                           "compile_bound", "io_bound")
STATE_CODES: Dict[str, int] = {s: i for i, s in enumerate(STATES)}


def classify_window(doc: Mapping[str, Any], *,
                    enter: Optional[Mapping[str, float]] = None) -> str:
    """Raw (memoryless) verdict for one ledger window."""
    wall = float(doc.get("wall_s") or 0.0)
    if wall <= 0:
        return "healthy"
    buckets = doc["buckets"]
    thresholds = dict(_ENTER_FRAC)
    if enter:
        thresholds.update(enter)
    for state in ("compile_bound", "feed_bound", "io_bound",
                  "compute_bound"):
        frac = sum(buckets.get(n, 0.0) for n in _STATE_FRACS[state]) / wall
        if frac >= thresholds[state]:
            return state
    return "healthy"


def summarize(events: Sequence[Mapping[str, Any]], *,
              t0: Optional[float] = None,
              t1: Optional[float] = None) -> Dict[str, Any]:
    """:func:`attribute` + the raw classifier verdict — the one-shot form
    the bench block and timeline replays use."""
    doc = attribute(events, t0=t0, t1=t1)
    doc["verdict"] = classify_window(doc)
    return doc


class BottleneckClassifier:
    """Rolling-window verdict with hysteresis.

    Two anti-flap mechanisms compose: a *dwell* (a new raw verdict must
    repeat for ``confirm_windows`` consecutive windows before the state
    flips) and an *exit margin* (while in a bound state, that state's
    fraction must drop ``margin`` below its entry threshold before any
    other verdict is even considered — boundary noise around the entry
    threshold cannot oscillate the state). Each observation is recorded
    into the tsdb as ``goodput_bottleneck_state`` (the
    :data:`STATE_CODES` code) plus one 0/1 series per state
    (``goodput_bottleneck_<state>``) so ``for_s``-held alert rules can
    express "feed-bound sustained > N windows".
    """

    def __init__(self, *, store: Optional[Any] = None,
                 confirm_windows: int = 2, margin: float = 0.15,
                 enter: Optional[Mapping[str, float]] = None,
                 on_change: Optional[Callable[[str, str], None]] = None):
        self._store = store
        self.confirm_windows = max(1, int(confirm_windows))
        self.margin = float(margin)
        self._enter = dict(_ENTER_FRAC)
        if enter:
            self._enter.update(enter)
        self.on_change = on_change
        self._state = "healthy"
        self._pending: Optional[str] = None
        self._streak = 0
        self._flips = 0

    @property
    def state(self) -> str:
        return self._state

    @property
    def flips(self) -> int:
        return self._flips

    def _fraction(self, doc: Mapping[str, Any], state: str) -> float:
        wall = float(doc.get("wall_s") or 0.0)
        if wall <= 0:
            return 0.0
        b = doc["buckets"]
        return sum(b.get(n, 0.0) for n in _STATE_FRACS[state]) / wall

    def observe(self, doc: Mapping[str, Any]) -> str:
        raw = classify_window(doc, enter=self._enter)
        if self._state != "healthy" and raw != self._state:
            # exit margin: stay put while still inside the hysteresis band
            if (self._fraction(doc, self._state)
                    >= self._enter[self._state] - self.margin):
                raw = self._state
        if raw == self._state:
            self._pending, self._streak = None, 0
        else:
            if raw != self._pending:
                self._pending, self._streak = raw, 0
            self._streak += 1
            if self._streak >= self.confirm_windows:
                old, self._state = self._state, raw
                self._pending, self._streak = None, 0
                self._flips += 1
                if self.on_change is not None:
                    self.on_change(old, raw)
        if self._store is not None:
            self._store.add("goodput_bottleneck_state",
                            float(STATE_CODES[self._state]))
            for s in STATES:
                if s != "healthy":
                    self._store.add(f"goodput_bottleneck_{s}",
                                    1.0 if s == self._state else 0.0)
        return self._state


class GoodputLedger:
    """Tracer-bound ledger that publishes a window as registry gauges.

    ``flops_per_sample`` / ``peak_tflops`` / ``samples_per_step`` are the
    model-cost inputs for ``mfu_live`` (the ``obs/xla.py`` analytic cost
    × the step rate measured from ``train.step``/``train.chunk`` spans);
    when any is missing the gauge is simply not set — absent series, not
    a lying 0.0. Same for ``goodput_h2d_gbps`` when no put carried a
    ``bytes`` attr in the window.
    """

    def __init__(self, *, tracer: Optional[Tracer] = None,
                 registry: Optional[MetricsRegistry] = None,
                 flops_per_sample: Optional[float] = None,
                 peak_tflops: Optional[float] = None,
                 samples_per_step: Optional[float] = None):
        self._tracer = tracer if tracer is not None else get_tracer()
        self._registry = (registry if registry is not None
                          else get_registry())
        self.flops_per_sample = flops_per_sample
        self.peak_tflops = peak_tflops
        self.samples_per_step = samples_per_step

    def set_model_costs(self, *, flops_per_sample: Optional[float] = None,
                        peak_tflops: Optional[float] = None,
                        samples_per_step: Optional[float] = None) -> None:
        if flops_per_sample is not None:
            self.flops_per_sample = float(flops_per_sample)
        if peak_tflops is not None:
            self.peak_tflops = float(peak_tflops)
        if samples_per_step is not None:
            self.samples_per_step = float(samples_per_step)

    def _now_rel(self) -> float:
        tr = self._tracer
        clock = getattr(tr, "_clock", None)
        epoch = getattr(tr, "_epoch", 0.0)
        if clock is None:  # disabled no-op tracer facade
            return 0.0
        return clock() - epoch

    def abs_to_rel(self, t_abs: float) -> float:
        """Convert a stamp from the tracer's clock domain (default
        ``time.perf_counter``) to event-relative time."""
        return float(t_abs) - getattr(self._tracer, "_epoch", 0.0)

    def snapshot(self, *, window_s: Optional[float] = None,
                 t0: Optional[float] = None, t1: Optional[float] = None,
                 t0_abs: Optional[float] = None,
                 publish: bool = False) -> Dict[str, Any]:
        """Ledger doc for a window. Precedence: explicit ``t0``/``t1``
        (event-relative) > ``t0_abs`` (clock-domain, e.g. an epoch-start
        ``perf_counter()``) > trailing ``window_s`` ending now > the
        full span extent of the buffer."""
        events = self._tracer.events()
        if t0 is None and t0_abs is not None:
            t0 = self.abs_to_rel(t0_abs)
            if t1 is None:
                t1 = self._now_rel()
        if t0 is None and window_s is not None:
            if t1 is None:
                t1 = self._now_rel()
            t0 = max(0.0, t1 - float(window_s))
        doc = attribute(events, t0=t0, t1=t1)
        doc["verdict"] = classify_window(doc)
        self._augment(doc, events)
        if publish:
            self.publish(doc)
        return doc

    def _augment(self, doc: Dict[str, Any],
                 events: Sequence[Mapping[str, Any]]) -> None:
        t0, t1 = doc["t0_s"], doc["t1_s"]
        wall = doc["wall_s"]
        h2d_bytes = 0
        h2d_iv: List[Interval] = []
        steps = 0.0
        for ev in events:
            dur = ev.get("dur_s")
            if dur is None:
                continue
            s = float(ev["ts_s"])
            e = s + float(dur)
            if e <= t0 or s >= t1:
                continue
            name = ev.get("name")
            if name in _H2D_BYTE_SPANS:
                h2d_iv.append((max(s, t0), min(e, t1)))
                try:
                    h2d_bytes += int((ev.get("args") or {})
                                     .get("bytes") or 0)
                except (TypeError, ValueError):
                    pass
            elif name in _STEP_SPANS:
                steps += 1.0
            elif name == "train.chunk":
                try:
                    steps += float((ev.get("args") or {})
                                   .get("steps") or 0.0)
                except (TypeError, ValueError):
                    pass
        put_s = _total(_merge(h2d_iv))
        doc["h2d_put_union_s"] = put_s
        doc["h2d_bytes"] = h2d_bytes
        doc["h2d_gbps"] = ((h2d_bytes / put_s) / 1e9
                           if put_s > 0 and h2d_bytes > 0 else None)
        doc["steps"] = steps
        rate = steps / wall if wall > 0 else 0.0
        doc["step_rate"] = rate
        mfu = None
        if (self.samples_per_step and self.flops_per_sample
                and self.peak_tflops and rate > 0):
            from .xla import analytic_mfu
            mfu = analytic_mfu(self.flops_per_sample,
                               rate * self.samples_per_step,
                               self.peak_tflops)
        doc["mfu_live"] = mfu

    def publish(self, doc: Mapping[str, Any]) -> None:
        reg = self._registry
        reg.gauge("goodput_fraction",
                  "fraction of window wall time the compute bucket "
                  "claimed (ledger window)").set(doc["goodput_fraction"])
        reg.gauge("goodput_wall_seconds",
                  "ledger window wall seconds").set(doc["wall_s"])
        reg.gauge("goodput_unattributed_seconds",
                  "window seconds no instrumented span accounts for"
                  ).set(doc["unattributed_s"])
        for b in BUCKETS:
            reg.gauge(f"goodput_{b}_seconds",
                      "window wall seconds attributed to this bucket"
                      ).set(doc["buckets"][b])
        if doc.get("h2d_gbps") is not None:
            reg.gauge("goodput_h2d_gbps",
                      "live H2D bandwidth over the put-span union"
                      ).set(doc["h2d_gbps"])
        if doc.get("mfu_live") is not None:
            reg.gauge("mfu_live",
                      "XLA-cost MFU at the measured live step rate"
                      ).set(doc["mfu_live"])


class GoodputMonitor:
    """The orchestrator the trainer wires up: one :meth:`poll` per tsdb
    sampler pass publishes the trailing-window ledger, runs the
    classifier, and (via :mod:`~dcnn_tpu.obs.anomaly`) turns a verdict
    flip into a bounded capture. :meth:`attach` serves the whole thing
    as the ``/goodput`` endpoint."""

    def __init__(self, *, tracer: Optional[Tracer] = None,
                 registry: Optional[MetricsRegistry] = None,
                 store: Optional[Any] = None,
                 window_s: float = 30.0,
                 ledger: Optional[GoodputLedger] = None,
                 classifier: Optional[BottleneckClassifier] = None,
                 anomaly: Optional[Any] = None,
                 **ledger_kw: Any):
        self.window_s = float(window_s)
        self.ledger = ledger if ledger is not None else GoodputLedger(
            tracer=tracer, registry=registry, **ledger_kw)
        self.anomaly = anomaly
        self.classifier = (classifier if classifier is not None
                           else BottleneckClassifier(store=store))
        user_cb = self.classifier.on_change

        def _flip(old: str, new: str) -> None:
            if user_cb is not None:
                user_cb(old, new)
            if self.anomaly is not None:
                self.anomaly.on_classification_flip(
                    old, new, ledger_doc=self._last)

        self.classifier.on_change = _flip
        self._last: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    def poll(self, _store: Optional[Any] = None) -> Optional[Dict[str, Any]]:
        """One window: snapshot → publish gauges → classify. Signature is
        ``TsdbSampler.add_after_sample``-compatible and it never raises —
        a ledger bug must not kill the sampling cadence."""
        try:
            with self._lock:
                doc = self.ledger.snapshot(window_s=self.window_s,
                                           publish=True)
                state = self.classifier.observe(doc)
                doc["bottleneck"] = state
                self.ledger._registry.gauge(
                    "goodput_bottleneck_state",
                    "classifier state code (0 healthy, 1 feed, 2 compute,"
                    " 3 compile, 4 io)").set(float(STATE_CODES[state]))
                self._last = doc
                return doc
        except Exception:  # pragma: no cover - defensive
            return None

    def observe_step(self, dt_s: float) -> None:
        """Per-step hook from the training loop — feeds the anomaly
        detector's step-time EWMA band."""
        if self.anomaly is not None:
            self.anomaly.observe_step(dt_s, ledger_doc=self._last)

    def doc(self) -> Dict[str, Any]:
        """``/goodput`` body."""
        last = self._last if self._last is not None else self.poll()
        body: Dict[str, Any] = {
            "window_s": self.window_s,
            "ledger": last,
            "bottleneck": {
                "state": self.classifier.state,
                "flips": self.classifier.flips,
                "confirm_windows": self.classifier.confirm_windows,
                "margin": self.classifier.margin,
            },
        }
        if self.anomaly is not None:
            body["anomaly"] = self.anomaly.stats()
        return body

    def attach(self, server: Any) -> "GoodputMonitor":
        """Serve :meth:`doc` as ``GET /goodput`` on a TelemetryServer."""
        server.add_route("/goodput", self.doc)
        return self

    def close(self) -> None:
        if self.anomaly is not None:
            self.anomaly.close()
