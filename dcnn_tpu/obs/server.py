"""HTTP exposition server: ``/metrics``, ``/healthz``, ``/snapshot``.

PR 3 gave every subsystem one in-process registry and tracer; this module
is the half that lets anything OUTSIDE the process see them — a Prometheus
scraper, a load-balancer health probe, or the planned replica router
(ROADMAP item 2), which will route on exactly the per-replica
health/latency these endpoints expose.

Endpoints (all GET; anything else is 404/405):

- ``/metrics`` — Prometheus text exposition (format 0.0.4). Default body
  is ``registry.prometheus()``; a ``metrics_text`` callable overrides it
  (the serve wiring passes ``ServeMetrics.prometheus`` so the exact
  windowed percentile gauges ride along).
- ``/healthz`` — JSON liveness + resilience state. 200 while every
  registered check passes, **503 the moment one fails**, with a
  machine-readable body: ``{"status": "unhealthy", "reasons": [...],
  "checks": {name: {"ok": bool, "reason": ...}}}``. Checks are plain
  callables returning ``None``/``True`` for healthy or a reason string
  for degraded (an exception counts as degraded with the exception as
  the reason — a health check that crashes is not healthy). Adapters for
  the resilience subsystem live here: :func:`watchdog_check`
  (``StallWatchdog`` stall state) and :func:`checkpoint_check`
  (``CheckpointManager.check()`` — failing async saves). The body also
  carries the registry's guard/resilience flags (``train_stalled``,
  ``train_skipped_steps_total``, ``ckpt_*``) so a scraper gets the WHY
  without a second request.
- ``/snapshot`` — JSON debug dump: the full registry ``snapshot()``, the
  newest tracer spans (bounded by ``snapshot_events``), per-name span
  counts, and any extra provider blocks the owner registered (the serve
  wiring adds the live ``ServeMetrics.snapshot()``).

Design rules, inherited from the rest of ``obs``:

- **stdlib only** (``http.server``) — no framework dependency for three
  GET routes; ``ThreadingHTTPServer`` so a slow scraper never blocks a
  health probe.
- **Injectable everything**: registry, tracer, clock, checks. Tests bind
  port 0 (ephemeral), drive stall/corruption with fakes, and never sleep.
- **Read-only**: handlers only ever snapshot/render; no endpoint mutates
  training or serving state.
- **Graceful shutdown**: :meth:`TelemetryServer.stop` shuts the listener
  down, joins the thread, and closes the socket — idempotent, safe from
  ``finally`` blocks.
"""

from __future__ import annotations

import json
import os
import socket as _socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from .exposition import CONTENT_TYPE
from .registry import MetricsRegistry, get_registry
from .tracer import Tracer, _json_safe, get_tracer

# registry series mirrored into the /healthz body when present — the
# resilience flags a router wants alongside the up/down verdict
_HEALTH_FLAGS = (
    "train_stalled", "train_last_progress_age_s", "train_stall_flags_total",
    "train_skipped_steps_total", "train_rollbacks_total",
    "ckpt_last_step", "ckpt_saves_total", "ckpt_restore_skipped_total",
    "elastic_generation", "elastic_world_size", "elastic_reconfiguring",
    "elastic_reconfigures_total", "elastic_peers_lost_total",
    # TCP pipeline (parallel/distributed_pipeline.py): generation + stage
    # count + the recovery counters a prober wants next to the verdict
    "pipeline_generation", "pipeline_stages", "pipeline_recovering",
    "pipeline_stages_lost_total", "pipeline_recoveries_total",
    "pipeline_stage_respawns_total", "pipeline_replayed_batches_total",
    "pipeline_batches_lost_total",
    # router tier (serve/router.py): fleet shape + the counters a prober
    # wants next to the 200/503 verdict
    "serve_router_replicas", "serve_router_replicas_routable",
    "serve_router_canary_replicas", "serve_router_version",
    "serve_router_replica_deaths_total", "serve_router_rejoins_total",
    "serve_router_rollbacks_total", "serve_router_promotions_total",
    # autoscaler (serve/autoscale.py): is the loop in breach, what fleet
    # size is it steering toward, and can it actually grow (lease/HBM
    # pins surface as reasons via autoscale_check; these flags give the
    # prober the numbers next to that verdict)
    "autoscale_breach", "autoscale_replicas_target",
    "autoscale_scale_ups_total", "autoscale_scale_downs_total",
    "autoscale_lease_blocked_total", "autoscale_hbm_blocked_total",
    "autoscale_last_scale_up_reaction_s",
    "serve_router_decommissions_total",
    "serve_router_decommission_sweeps_total",
    "lease_free_devices",
    # goodput plane (obs/goodput.py): where the wall time went and what
    # the classifier currently blames, next to the 200/503 verdict
    "goodput_fraction", "goodput_bottleneck_state",
    "goodput_unattributed_seconds",
    # gray-failure plane (resilience/slowness.py; docs/reliability.md
    # §11): fail-slow verdicts next to the fail-stop ones
    "elastic_stragglers_evicted_total", "elastic_slow_leader_total",
    "pipeline_rebalances_total", "pipeline_stage_imbalance",
    "serve_router_hedges_total", "serve_router_hedge_wins_total",
    "serve_router_probation_replicas", "feed_worker_recycled_total",
)


def watchdog_check(watchdog) -> Callable[[], Optional[str]]:
    """Health check over a :class:`~dcnn_tpu.resilience.guards.StallWatchdog`:
    degraded while the loop it watches has not beaten within its timeout.
    Calls ``check()`` live, so the endpoint sees a stall the moment it is
    scraped — not at the next poll tick."""
    def _check() -> Optional[str]:
        if watchdog.check():
            return (f"stalled: no progress for > "
                    f"{watchdog.timeout_s:g}s")
        return None
    return _check


def elastic_check(controller) -> Callable[[], Optional[str]]:
    """Health check over an elastic controller
    (``parallel/elastic.py``): degraded **while a reconfiguration is in
    flight** — survivors are mid-barrier / restoring a checkpoint and the
    replica is not serving useful steps, so a router or fleet scheduler
    should treat it like a draining replica, not a dead one. Healthy
    again the moment the new generation is established (the ``/healthz``
    body's ``elastic_generation`` / ``elastic_world_size`` flags say what
    it reconfigured *to*)."""
    def _check() -> Optional[str]:
        if getattr(controller, "reconfiguring", False):
            return (f"elastic reconfiguration in flight "
                    f"(generation {getattr(controller, 'generation', '?')}, "
                    f"world {getattr(controller, 'world', '?')})")
        return None
    return _check


def pipeline_check(coordinator) -> Callable[[], Optional[str]]:
    """Health check over a
    :class:`~dcnn_tpu.parallel.distributed_pipeline.DistributedPipelineCoordinator`:
    degraded **while a stage-loss recovery is in flight** — the
    coordinator is mid-sweep / restoring a commit / replaying the batch
    journal and is not making forward progress on new batches, so a fleet
    scheduler should treat the run like a draining replica, not a dead
    one. Healthy again the moment the re-shipped generation is serving
    (the body's ``pipeline_generation`` / ``pipeline_stages`` flags say
    what it recovered *to*)."""
    def _check() -> Optional[str]:
        if getattr(coordinator, "recovering", False):
            return (f"pipeline recovery in flight "
                    f"(generation {getattr(coordinator, 'generation', '?')}, "
                    f"stages {getattr(coordinator, 'num_stages', '?')})")
        return None
    return _check


def checkpoint_check(manager) -> Callable[[], Optional[str]]:
    """Health check over a
    :class:`~dcnn_tpu.resilience.checkpoint.CheckpointManager`: degraded
    once an async save has failed — a run whose checkpoints are rotting
    is not preemption-safe and a router should know before it matters.

    Prefers the manager's NON-consuming, latching ``health()`` probe:
    ``check()`` is a one-shot that drops inspected futures, so a scrape
    calling it would steal the failure from the trainer's own
    per-cadence fail-fast and report healthy again on the next scrape.
    A fake without ``health()`` falls back to ``check()``."""
    def _check() -> Optional[str]:
        probe = getattr(manager, "health", None)
        try:
            exc = probe() if probe is not None else manager.check()
        except Exception as e:
            exc = e
        if exc is not None:
            return f"checkpoint save failing: {type(exc).__name__}: {exc}"
        return None
    return _check


class _Handler(BaseHTTPRequestHandler):
    # the owning TelemetryServer is attached to the server object
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj, default=str).encode("utf-8"),
                   "application/json")

    def do_GET(self):  # noqa: N802 (http.server API)
        owner: "TelemetryServer" = self.server.owner  # type: ignore
        path = self.path.split("?", 1)[0]
        t0 = time.perf_counter()
        endpoint = owner._endpoint_slug(path)
        failed = False
        try:
            if path == "/metrics":
                code, raw, ctype = (200, owner.metrics_body().encode(
                    "utf-8"), CONTENT_TYPE)
            else:
                if path == "/healthz":
                    code, body = owner.health()
                elif path == "/snapshot":
                    code, body = 200, owner.snapshot()
                elif path in owner._routes:
                    code, body = owner.route_body(path)
                else:
                    code, body = 404, {"error": f"no route {path}",
                                       "routes": ["/metrics", "/healthz",
                                                  "/snapshot",
                                                  *sorted(owner._routes)]}
                raw, ctype = (json.dumps(body, default=str).encode("utf-8"),
                              "application/json")
        except Exception as e:  # a broken provider must not kill the server
            failed = True
            code, ctype = 500, "application/json"
            raw = json.dumps({"error": f"{type(e).__name__}: {e}"},
                             default=str).encode("utf-8")
        # scrape self-observability (a monitoring plane that cannot see
        # its own scrapes repeats the PR 11 silent-parse-failure lesson):
        # per-endpoint request/error counters + one shared duration
        # histogram on the SAME registry this surface exposes. Accounted
        # BEFORE the bytes hit the wire: a client that has seen the
        # response must find the scrape already counted — probes and
        # tests legitimately race on exactly that edge.
        try:
            owner._observe_scrape(endpoint, time.perf_counter() - t0,
                                  failed)
        except Exception:
            pass  # self-accounting must never break a scrape
        try:
            self._send(code, raw, ctype)
        except Exception:
            pass  # peer gone mid-write: nothing useful to do


class TelemetryServer:
    """Threaded HTTP exposition server over one registry + tracer.

    ``port=0`` binds an ephemeral port (read it back from :attr:`port`
    after :meth:`start` — the test/e2e pattern); a fixed port is the
    production scrape target. ``metrics_text`` overrides the ``/metrics``
    body provider; ``extra_snapshot`` callables contribute named blocks to
    ``/snapshot``.
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 clock: Callable[[], float] = time.monotonic,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics_text: Optional[Callable[[], str]] = None,
                 snapshot_events: int = 256):
        if snapshot_events < 0:
            raise ValueError(
                f"snapshot_events must be >= 0, got {snapshot_events}")
        self.registry = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self._clock = clock
        self._host = host
        self._port = port
        self.metrics_text = (metrics_text if metrics_text is not None
                             else self.registry.prometheus)
        self._snapshot_events = snapshot_events
        self._checks: List[Tuple[str, Callable[[], Any]]] = []
        self._extra_snapshot: Dict[str, Callable[[], Any]] = {}
        self._routes: Dict[str, Callable[[], Any]] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t0 = clock()
        # trace identity for /snapshot: merged multi-process traces need
        # to attribute each shard (host, pid, rank/component/name —
        # whatever the owner sets via set_identity)
        self._identity: Dict[str, Any] = {}
        # flight recorder + healthz edge detection (attach_flight):
        # handler threads race on the 200→503 transition, so the edge
        # state is lock-guarded
        self._flight = None
        self._edge_lock = threading.Lock()
        self._last_ok = True                    # dcnn: guarded_by=_edge_lock

    # -- wiring ------------------------------------------------------------
    def add_check(self, name: str, fn: Callable[[], Any]
                  ) -> "TelemetryServer":
        """Register a health check: ``fn()`` returns ``None``/``True`` when
        healthy, a reason string when degraded; raising counts as degraded.
        Returns self for chaining."""
        self._checks.append((name, fn))
        return self

    def set_identity(self, **identity: Any) -> "TelemetryServer":
        """Name this process for merged-trace attribution: ``/snapshot``'s
        ``process`` block carries host + pid plus whatever the owner sets
        here (``component="router"``, ``rank=2``, ...). Also stamps the
        tracer's ``process_name`` (JSONL shard headers) when unset."""
        self._identity.update(identity)
        if getattr(self.tracer, "process_name", None) is None:
            name = identity.get("name") or identity.get("component")
            if name is not None:
                self.tracer.process_name = str(name)
        return self

    def attach_flight(self, recorder) -> "TelemetryServer":
        """Wire a :class:`~dcnn_tpu.obs.flight.FlightRecorder` to this
        surface: the ``/healthz`` 200→503 **transition** dumps a
        ``healthz_degraded`` bundle carrying the full 503 body (reasons,
        checks, flags), and ``/snapshot`` gains a ``flight`` block
        listing retained bundles. Edge-triggered: a fleet that stays
        degraded records once per degradation episode, not per scrape."""
        self._flight = recorder
        self.add_snapshot("flight", lambda: {
            "dir": recorder.directory,
            "enabled": recorder.enabled,
            "bundles": recorder.bundles(),
        })
        return self

    def add_snapshot(self, name: str, fn: Callable[[], Any]
                     ) -> "TelemetryServer":
        """Register an extra ``/snapshot`` block (``fn()`` must return a
        JSON-representable value)."""
        self._extra_snapshot[name] = fn
        return self

    def add_route(self, path: str, fn: Callable[[], Any]
                  ) -> "TelemetryServer":
        """Register an extra GET route serving JSON: ``fn()`` returns
        either a JSON-representable body (→ 200) or a ``(status_code,
        body)`` tuple. The built-in three routes cannot be shadowed —
        their contracts are load-bearing (router/probe/scraper). Wire
        routes before :meth:`start` (the handler reads the table from
        its own threads)."""
        if not path.startswith("/"):
            raise ValueError(f"route must start with '/', got {path!r}")
        if path in ("/metrics", "/healthz", "/snapshot"):
            raise ValueError(f"route {path} is built in")
        self._routes[path] = fn
        return self

    # -- endpoint bodies (exercised directly by unit tests) ----------------
    def health(self) -> Tuple[int, Dict[str, Any]]:
        """(status_code, body) for ``/healthz``: 200 iff every check
        passes, else 503 with every failing check's machine-readable
        reason."""
        checks: Dict[str, Any] = {}
        reasons: List[str] = []
        for name, fn in self._checks:
            try:
                res = fn()
            except Exception as e:
                res = f"{type(e).__name__}: {e}"
            if res is None or res is True:
                checks[name] = {"ok": True}
            else:
                reason = res if isinstance(res, str) else repr(res)
                checks[name] = {"ok": False, "reason": reason}
                reasons.append(f"{name}: {reason}")
        snap = self.registry.snapshot()
        flags = {k: snap[k] for k in _HEALTH_FLAGS if k in snap}
        # the stall gauge doubles as a registry-only degradation signal for
        # processes that wired a watchdog to the registry but not to us
        if not any(n == "watchdog" for n, _ in self._checks):
            if flags.get("train_stalled"):
                reasons.append("train_stalled: registry flag set")
        # same contract for the elastic controller: a process that set the
        # reconfiguring flag on the registry degrades even without the
        # explicit elastic_check adapter registered
        if not any(n == "elastic" for n, _ in self._checks):
            if flags.get("elastic_reconfiguring"):
                reasons.append("elastic_reconfiguring: registry flag set")
        ok = not reasons
        body = {
            "status": "ok" if ok else "unhealthy",
            "reasons": reasons,
            "checks": checks,
            "flags": flags,
            "uptime_s": round(max(self._clock() - self._t0, 0.0), 3),
        }
        # flight recorder on the DEGRADATION EDGE: exactly one bundle per
        # 200→503 transition (concurrent scrapes race on the edge, so it
        # is claimed under the lock), carrying this very body — the 503's
        # machine-readable reasons are postmortem evidence, not just a
        # one-shot scrape response
        with self._edge_lock:
            degraded_edge = self._last_ok and not ok
            self._last_ok = ok
        if degraded_edge and self._flight is not None:
            self._flight.record("healthz_degraded", reasons=reasons,
                                health=body, registry=self.registry,
                                tracer=self.tracer)
        return (200 if ok else 503), body

    def route_body(self, path: str) -> Tuple[int, Any]:
        """(status_code, body) for a registered extra route."""
        res = self._routes[path]()
        if isinstance(res, tuple) and len(res) == 2 \
                and isinstance(res[0], int):
            return res
        return 200, res

    # -- scrape self-observability -----------------------------------------
    _KNOWN_ENDPOINTS = ("metrics", "healthz", "snapshot")

    def _endpoint_slug(self, path: str) -> str:
        """Bounded-cardinality endpoint label for a request path. ONLY
        an exactly-matched route earns its own counter — ``/healthz/``
        404s, so counting it as ``healthz`` would mask exactly the
        misconfigured-probe case the counters exist to expose; it and
        every other unmatched path land on ``other``. Route names are
        sanitized to the metric-name grammar (``/my-route`` mints
        ``scrape_requests_my_route_total``, not a ValueError that skips
        the accounting)."""
        name = path.lstrip("/")
        if not (name in self._KNOWN_ENDPOINTS and path == f"/{name}") \
                and path not in self._routes:
            return "other"
        name = "".join(c if (c.isalnum() and c.isascii()) or c == "_"
                       else "_" for c in name.replace("/", "_"))
        if not name or name[0].isdigit():
            name = f"r_{name}"
        return name

    def _observe_scrape(self, endpoint: str, dur_s: float,
                        failed: bool) -> None:
        reg = self.registry
        reg.counter("scrape_requests_total",
                    "telemetry HTTP requests served").inc()
        reg.counter(f"scrape_requests_{endpoint}_total",  # dcnn: metric=scrape_requests_*_total
                    f"telemetry requests served on /{endpoint}").inc()
        if failed:
            reg.counter("scrape_errors_total",
                        "telemetry HTTP requests that failed (500)").inc()
            reg.counter(f"scrape_errors_{endpoint}_total",  # dcnn: metric=scrape_errors_*_total
                        f"failed telemetry requests on /{endpoint}").inc()
        reg.histogram("scrape_duration_seconds",
                      "wall per telemetry HTTP request").observe(dur_s)

    def metrics_body(self) -> str:
        """The ``/metrics`` body: refreshes the tracer's saturation
        series (``trace_events_dropped_total`` + buffer occupancy
        gauges) onto the registry first, so a saturated tracer is
        visible on the scrape that would otherwise miss it."""
        try:
            self.tracer.export_gauges(self.registry)
        except Exception:
            pass  # a broken gauge refresh must not kill the scrape
        return self.metrics_text()

    def snapshot(self) -> Dict[str, Any]:
        """Body for ``/snapshot``: registry dump + newest tracer spans +
        this process's trace identity (merged traces are attributable)."""
        try:
            self.tracer.export_gauges(self.registry)
        except Exception:
            pass
        events = self.tracer.events()[-self._snapshot_events:] \
            if self._snapshot_events else []
        for ev in events:  # tracer attrs may hold arbitrary objects
            ev["args"] = {k: _json_safe(v) for k, v in ev["args"].items()}
        out: Dict[str, Any] = {
            "metrics": self.registry.snapshot(),
            "spans": events,
            "span_counts": self.tracer.span_counts(),
            "tracer_enabled": self.tracer.enabled,
            "process": {
                "host": _socket.gethostname(),
                "pid": os.getpid(),
                "name": getattr(self.tracer, "process_name", None),
                "trace_events_dropped": getattr(self.tracer, "dropped", 0),
                **self._identity,
            },
        }
        for name, fn in self._extra_snapshot.items():
            try:
                out[name] = fn()
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self._host, self._port), _Handler)
        httpd.daemon_threads = True
        httpd.owner = self  # type: ignore[attr-defined]
        self._httpd = httpd
        self._port = httpd.server_address[1]  # resolve an ephemeral bind
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name=f"dcnn-telemetry-{self._port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Graceful, idempotent shutdown: stop serving, join, close."""
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self._port}"

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def __repr__(self) -> str:
        state = "listening" if self._httpd is not None else "stopped"
        return (f"TelemetryServer({self.url}, {state}, "
                f"checks={[n for n, _ in self._checks]})")
