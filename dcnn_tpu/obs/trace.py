"""Multi-process trace merge + flight-bundle inspection CLI.

``obs/tracer.py`` gives every process identity-stamped spans and a JSONL
shard exporter; ``parallel/comm.py`` carries the trace context across
every framed hop. This module is the last mile: merge the per-process
shards into **one** Perfetto-loadable Chrome trace where a request (or a
reconfiguration) reads as a single cross-process timeline —

    python -m dcnn_tpu.obs.trace merge router.jsonl replica-*.jsonl \\
        -o /tmp/fleet_trace.json
    python -m dcnn_tpu.obs.trace inspect /var/flight/fb-...-replica_death

Clock alignment: shard events are relative to each tracer's epoch, and
the shard header (first JSONL line) carries that epoch in the process's
``perf_counter`` domain. On one host ``perf_counter`` is
``CLOCK_MONOTONIC`` — one clock system-wide on Linux — so same-host
shards align **exactly** with no configuration. Across hosts, pass
``--offset <shard-basename>=<seconds>`` per shard; the live system
measures exactly these offsets at handshake time (the serve tier's
ping/pong midpoint estimate — ``TcpReplica.clock_offset_s`` — and the
elastic mesh's HELLO stamps — ``Membership.clock_offsets()``), so the
operator reads them off ``/snapshot``/stats rather than guessing. A
shard may also carry ``clock_offset_s`` in its header (a writer that
knows its own offset), applied automatically when no flag overrides it.

Merged layout: one Chrome **pid** per shard (process_name from the
shard's host/pid/name identity), one **tid** per (shard, track) with
``thread_name`` metadata — the same labeled-rows contract
``Tracer.export_chrome`` established, scaled to N processes. Span args
(including ``trace_id``/``span_id``/``parent_id``) ride through
untouched, so Perfetto's args search finds every span of a trace across
all processes.

Exit codes match the repo's other CLIs: 0 ok, 1 validation/tool failure,
2 usage.
"""

from __future__ import annotations

import argparse
import gzip as _gzip
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


# --------------------------------------------------------------- shard IO

def read_shard(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Parse one JSONL shard (plain or ``.gz``) into ``(meta, events)``.
    The header line is recognized by its ``shard`` key; a headerless file
    (hand-made fixture) yields ``meta == {}``. Malformed lines raise —
    a half-merged timeline is worse than no timeline."""
    opener = _gzip.open if path.endswith(".gz") else open
    meta: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    with opener(path, "rt") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: bad JSONL: {e}") from e
            if "shard" in obj and "name" not in obj:
                meta = dict(obj["shard"])
            elif "name" in obj:
                events.append(obj)
            else:
                raise ValueError(f"{path}:{lineno}: neither a shard "
                                 f"header nor an event: {obj!r}")
    return meta, events


def _shard_label(path: str) -> str:
    return os.path.basename(path)


def _process_name(path: str, meta: Dict[str, Any]) -> str:
    name = meta.get("process")
    host = meta.get("host")
    pid = meta.get("pid")
    base = name if name else _shard_label(path)
    if host and pid:
        return f"{base} ({host}:{pid})"
    return str(base)


# ----------------------------------------------------------------- merge

def merge_shards(paths: List[str], out: str, *,
                 offsets: Optional[Dict[str, float]] = None,
                 max_events: Optional[int] = None) -> Dict[str, Any]:
    """Merge JSONL shards into one Chrome ``trace_event`` file at
    ``out`` (written atomically: tmp sibling + ``os.replace``). Returns
    a summary dict — the block bench embeds under ``telemetry`` and the
    tests assert on: event/span counts, distinct trace ids, per-shard
    identity, and total events the writers reported dropping."""
    if not paths:
        raise ValueError("no shards to merge")
    offsets = dict(offsets or {})
    shards = []
    for p in paths:
        meta, events = read_shard(p)
        off = offsets.get(_shard_label(p),
                          float(meta.get("clock_offset_s") or 0.0))
        shards.append((p, meta, events, float(meta.get("epoch_s") or 0.0),
                       off))

    # absolute timeline: t_abs = epoch + ts - offset (an offset measured
    # as "server_clock - client_clock" maps a server shard BACK onto the
    # reference timeline); normalized to the earliest event so the
    # viewer opens at t=0
    t_min: Optional[float] = None
    for (_p, _m, events, epoch, off) in shards:
        for ev in events:
            t = epoch + float(ev["ts_s"]) - off
            if t_min is None or t < t_min:
                t_min = t
    t_min = t_min or 0.0

    chrome: List[Dict[str, Any]] = []
    tids: Dict[Tuple[int, str], int] = {}
    next_tid = 1
    trace_ids = set()
    total = 0
    dropped = 0
    shard_summaries = []
    for i, (p, meta, events, epoch, off) in enumerate(shards):
        pid = i + 1
        chrome.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": _process_name(p, meta)}})
        dropped += int(meta.get("dropped") or 0)
        for ev in events:
            track = ev.get("track") or "main"
            key = (pid, track)
            if key not in tids:
                tids[key] = next_tid
                chrome.append({"ph": "M", "pid": pid, "tid": next_tid,
                               "name": "thread_name",
                               "args": {"name": track}})
                next_tid += 1
            args = dict(ev.get("args") or {})
            tid_val = args.get("trace_id")
            if tid_val:
                trace_ids.add(tid_val)
            ts_us = round((epoch + float(ev["ts_s"]) - off - t_min) * 1e6,
                          3)
            rec: Dict[str, Any] = {
                "name": ev["name"], "pid": pid, "tid": tids[key],
                "ts": ts_us, "cat": str(ev["name"]).split(".", 1)[0],
                "args": args,
            }
            if ev.get("dur_s") is None:
                rec["ph"] = "i"
                rec["s"] = "t"
            else:
                rec["ph"] = "X"
                rec["dur"] = round(float(ev["dur_s"]) * 1e6, 3)
            chrome.append(rec)
            total += 1
        shard_summaries.append({
            "path": p, "events": len(events), "offset_s": off,
            "process": _process_name(p, meta),
        })

    if max_events is not None and total > max_events:
        # newest-N survive, like Tracer.export_chrome — metadata records
        # (ph M) are kept, the drop is explicit in the summary
        metas = [e for e in chrome if e["ph"] == "M"]
        evs = sorted((e for e in chrome if e["ph"] != "M"),
                     key=lambda e: e["ts"])
        cut = len(evs) - max_events
        chrome = metas + evs[cut:]
        dropped += cut

    # events sorted by timestamp read better in "flow" tooling; Perfetto
    # does not require it but diffable output does
    metas = [e for e in chrome if e["ph"] == "M"]
    evs = sorted((e for e in chrome if e["ph"] != "M"),
                 key=lambda e: e["ts"])
    doc = {"traceEvents": metas + evs, "displayTimeUnit": "ms"}
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    tmp = f"{out}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, out)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return {
        "out": out,
        "shards": shard_summaries,
        "events": len(evs),
        "trace_ids": len(trace_ids),
        "events_dropped_by_writers": dropped,
    }


# ------------------------------------------------------------- validation

#: Chrome trace_event phases this repo emits.
_PHASES = {"X", "i", "M"}


def validate_chrome(path: str) -> List[str]:
    """Schema problems in a Chrome trace file (empty list = loadable by
    Perfetto/chrome://tracing as far as this repo's emitters go). Shared
    by the merge-CLI tests and the acceptance soak."""
    problems: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable: {e}"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(evs):
        ph = ev.get("ph")
        if ph not in _PHASES:
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        for k in ("name", "pid", "tid"):
            if k not in ev:
                problems.append(f"event {i}: missing {k}")
        if ph == "M":
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            problems.append(f"event {i}: non-numeric ts")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            problems.append(f"event {i}: complete span without dur")
        if not isinstance(ev.get("args", {}), dict):
            problems.append(f"event {i}: args not a dict")
    return problems


# ------------------------------------------------------- bundle inspection

def inspect_bundle(path: str) -> Dict[str, Any]:
    """Summarize one flight-recorder bundle directory: manifest, files,
    span/trace counts, healthz reasons — the postmortem's front page."""
    if not os.path.isdir(path):
        raise ValueError(f"not a bundle directory: {path}")
    out: Dict[str, Any] = {"path": path,
                           "files": sorted(os.listdir(path))}
    mpath = os.path.join(path, "MANIFEST.json")
    try:
        with open(mpath) as f:
            out["manifest"] = json.load(f)
    except (OSError, ValueError) as e:
        out["manifest_error"] = str(e)
    spath = os.path.join(path, "spans.jsonl")
    if os.path.isfile(spath):
        _meta, events = read_shard(spath)
        out["spans"] = len(events)
        counts: Dict[str, int] = {}
        traces = set()
        for ev in events:
            counts[ev["name"]] = counts.get(ev["name"], 0) + 1
            t = (ev.get("args") or {}).get("trace_id")
            if t:
                traces.add(t)
        out["span_counts"] = counts
        out["trace_ids"] = len(traces)
    hpath = os.path.join(path, "healthz.json")
    if os.path.isfile(hpath):
        try:
            with open(hpath) as f:
                h = json.load(f)
            out["healthz"] = {"status": h.get("status"),
                              "reasons": h.get("reasons")}
        except (OSError, ValueError) as e:
            out["healthz_error"] = str(e)
    # tsdb history window (obs/tsdb.py): the minutes BEFORE the trigger
    tpath = os.path.join(path, "history.jsonl")
    if os.path.isfile(tpath):
        try:
            from .tsdb import summarize_history
            out["history"] = summarize_history(tpath)
        except (OSError, ValueError) as e:
            out["history_error"] = str(e)
    return out


# -------------------------------------------------------------------- CLI

def _parse_offsets(pairs: List[str]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for p in pairs:
        name, sep, val = p.rpartition("=")
        if not sep:
            raise ValueError(f"--offset wants <shard-basename>=<seconds>, "
                             f"got {p!r}")
        out[name] = float(val)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dcnn_tpu.obs.trace",
        description="Merge per-process trace shards into one "
                    "Perfetto-loadable Chrome trace; inspect flight "
                    "bundles.")
    sub = ap.add_subparsers(dest="cmd")
    mp = sub.add_parser("merge", help="merge JSONL shards → Chrome trace")
    mp.add_argument("shards", nargs="+",
                    help="JSONL shard files (Tracer.export_jsonl / "
                         "flush_jsonl output, .gz ok; a flight bundle's "
                         "spans.jsonl works too)")
    mp.add_argument("-o", "--out", required=True,
                    help="merged Chrome trace path")
    mp.add_argument("--offset", action="append", default=[],
                    metavar="SHARD=SECONDS",
                    help="clock offset for one shard (basename match): "
                         "its events shift by -SECONDS onto the "
                         "reference timeline; measured at handshake "
                         "(TcpReplica.clock_offset_s, "
                         "Membership.clock_offsets)")
    mp.add_argument("--max-events", type=int, default=None,
                    help="keep only the newest N events (viewers choke "
                         "on multi-million-event files)")
    mp.add_argument("--json", action="store_true",
                    help="print the summary as JSON")
    ip = sub.add_parser("inspect", help="summarize a flight bundle")
    ip.add_argument("bundle", help="flight bundle directory (fb-*)")
    args = ap.parse_args(argv)
    if args.cmd is None:
        ap.print_help()
        return 2
    try:
        if args.cmd == "merge":
            summary = merge_shards(
                list(args.shards), args.out,
                offsets=_parse_offsets(list(args.offset)),
                max_events=args.max_events)
            problems = validate_chrome(args.out)
            if problems:
                print("merged trace FAILED schema validation:",
                      file=sys.stderr)
                for p in problems[:20]:
                    print(f"  {p}", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(summary, indent=1))
            else:
                print(f"merged {len(summary['shards'])} shard(s), "
                      f"{summary['events']} events, "
                      f"{summary['trace_ids']} distinct traces "
                      f"-> {summary['out']}")
                for s in summary["shards"]:
                    print(f"  {s['process']}: {s['events']} events "
                          f"(offset {s['offset_s']:+g}s) [{s['path']}]")
                if summary["events_dropped_by_writers"]:
                    print(f"  note: writers reported "
                          f"{summary['events_dropped_by_writers']} "
                          f"events dropped before export "
                          f"(ring saturation / --max-events)")
            return 0
        summary = inspect_bundle(args.bundle)
        print(json.dumps(summary, indent=1, default=str))
        return 0
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
