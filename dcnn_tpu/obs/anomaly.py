"""Anomaly-triggered capture: the run that regresses collects its own
postmortem evidence.

Two trigger classes, one capture path:

- **step-time band breach** — :class:`EwmaBand` keeps an EWMA mean and
  variance of the per-step wall time; a sample above
  ``mean + band·std`` (and above ``mean·(1 + min_rel)``, so a
  microsecond-noise band can't trip) opens a *breach episode*.
- **bottleneck flip** — the :class:`~dcnn_tpu.obs.goodput
  .BottleneckClassifier` changing state (wired through
  :class:`~dcnn_tpu.obs.goodput.GoodputMonitor`).

Each episode fires **exactly one** bounded capture: a flight-recorder
bundle (:mod:`~dcnn_tpu.obs.flight`) tagged with the ledger snapshot,
plus an xprof profile opened through the non-raising
:func:`~dcnn_tpu.train.profiling.try_trace` (so an operator's manual
trace always wins — the anomaly path just counts the miss) and closed
after ``profile_steps`` further steps. The episode ends only after
``recover_samples`` consecutive in-band steps; a permanent regression
therefore captures once, not once per window. Breached samples do not
feed the EWMA — the band must not learn the anomaly.

Expected stalls (an elastic reconfigure re-sharding the world) are
fenced with the process-global :func:`suppress` context manager:
samples observed under it neither feed the band nor open episodes.

Everything is injectable (clock, detector, profiler, flight recorder)
so tier-1 tests run sleep-free and jax-free.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Any, Callable, Dict, Iterator, Optional

from .registry import MetricsRegistry, get_registry

_suppress_lock = threading.Lock()
_suppress_depth = 0


@contextlib.contextmanager
def suppress() -> Iterator[None]:
    """Fence an expected stall (reconfigure, planned checkpoint storm):
    step samples observed inside the block are dropped — they neither
    update the EWMA band nor trigger captures. Re-entrant and
    cross-thread (the depth is process-global: the stall is a property
    of the process, not of the observing thread)."""
    global _suppress_depth
    with _suppress_lock:
        _suppress_depth += 1
    try:
        yield
    finally:
        with _suppress_lock:
            _suppress_depth -= 1


def is_suppressed() -> bool:
    with _suppress_lock:
        return _suppress_depth > 0


class EwmaBand:
    """EWMA mean/std band over a scalar stream.

    :meth:`observe` returns True when the sample breaches the band that
    existed *before* the sample — and only in-band samples update the
    state, so a sustained regression cannot drag the band up and
    silently end its own episode. The first ``warmup`` samples always
    update and never breach."""

    def __init__(self, *, alpha: float = 0.2, band: float = 3.0,
                 min_rel: float = 0.5, warmup: int = 8):
        self.alpha = float(alpha)
        self.band = float(band)
        self.min_rel = float(min_rel)
        self.warmup = int(warmup)
        self._mean: Optional[float] = None
        self._var = 0.0
        self._n = 0

    @property
    def mean(self) -> Optional[float]:
        return self._mean

    @property
    def std(self) -> float:
        return math.sqrt(max(0.0, self._var))

    def threshold(self) -> Optional[float]:
        """Current breach threshold, or None during warmup."""
        if self._mean is None or self._n < self.warmup:
            return None
        return max(self._mean + self.band * self.std,
                   self._mean * (1.0 + self.min_rel))

    def observe(self, x: float) -> bool:
        x = float(x)
        thr = self.threshold()
        breach = thr is not None and x > thr
        if not breach:
            if self._mean is None:
                self._mean = x
            else:
                d = x - self._mean
                self._mean += self.alpha * d
                self._var = (1.0 - self.alpha) * (self._var
                                                  + self.alpha * d * d)
            self._n += 1
        return breach


def _default_profiler(log_dir: Optional[str]):
    """Lazy bridge to ``train.profiling.try_trace`` — imported only when
    a capture actually fires, keeping this module jax-free."""
    from ..train.profiling import try_trace
    return try_trace(log_dir) if log_dir else None


class AnomalyMonitor:
    """Exactly-one-capture-per-episode state machine.

    ``profiler`` is a callable ``(log_dir) -> context manager | None``
    (default: :func:`try_trace`); ``flight`` defaults to the process
    flight recorder. Counters: ``goodput_anomaly_episodes_total`` (one
    per opened episode, labeled by construction via the trigger reason
    inside the bundle), ``goodput_captures_total`` (bundles actually
    written), ``goodput_capture_profile_skipped_total`` (a capture that
    wanted an xprof profile but a trace was already active)."""

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 flight: Optional[Any] = None,
                 detector: Optional[EwmaBand] = None,
                 profiler: Optional[Callable[[Optional[str]], Any]] = None,
                 profile_dir: Optional[str] = None,
                 profile_steps: int = 8,
                 recover_samples: int = 4,
                 flip_captures: bool = True):
        self._registry = (registry if registry is not None
                          else get_registry())
        self._flight = flight
        self.detector = detector if detector is not None else EwmaBand()
        self._profiler = (profiler if profiler is not None
                          else _default_profiler)
        self.profile_dir = profile_dir
        self.profile_steps = max(1, int(profile_steps))
        self.recover_samples = max(1, int(recover_samples))
        self.flip_captures = bool(flip_captures)
        self._lock = threading.Lock()
        self._in_episode = False
        self._ok_streak = 0
        self._episodes = 0
        self._captures = 0
        self._profile_cm: Optional[Any] = None
        self._profile_path: Optional[str] = None
        self._profile_left = 0
        self._last_bundle: Optional[str] = None

    def _resolve_flight(self):
        if self._flight is not None:
            return self._flight
        from .flight import get_flight_recorder
        return get_flight_recorder()

    def observe_step(self, dt_s: float, *,
                     ledger_doc: Optional[Dict[str, Any]] = None) -> bool:
        """Feed one step wall time; returns True when this sample opened
        an episode (and fired its one capture)."""
        if is_suppressed():
            return False
        with self._lock:
            self._tick_profile_locked()
            breach = self.detector.observe(dt_s)
            if breach:
                self._ok_streak = 0
                if self._in_episode:
                    return False
                self._in_episode = True
                self._episodes += 1
                self._registry.counter(
                    "goodput_anomaly_episodes_total",
                    "anomaly episodes opened (band breach or verdict "
                    "flip)").inc()
                self._capture_locked("step_time_breach", ledger_doc,
                                     dt_s=float(dt_s),
                                     threshold=self.detector.threshold())
                return True
            if self._in_episode:
                self._ok_streak += 1
                if self._ok_streak >= self.recover_samples:
                    self._in_episode = False
                    self._ok_streak = 0
            return False

    def on_classification_flip(self, old: str, new: str, *,
                               ledger_doc: Optional[Dict[str, Any]] = None
                               ) -> None:
        """Bottleneck verdict changed — one capture per flip edge (the
        classifier's own hysteresis is the episode boundary here)."""
        if not self.flip_captures or is_suppressed():
            return
        with self._lock:
            self._episodes += 1
            self._registry.counter(
                "goodput_anomaly_episodes_total",
                "anomaly episodes opened (band breach or verdict "
                "flip)").inc()
            self._capture_locked("bottleneck_flip", ledger_doc,
                                 transition=f"{old}->{new}")

    def _capture_locked(self, kind: str,
                        ledger_doc: Optional[Dict[str, Any]],
                        **detail: Any) -> None:
        extra: Dict[str, Any] = {"trigger_kind": kind, "detail": detail}
        if ledger_doc is not None:
            extra["ledger"] = ledger_doc
        try:
            flight = self._resolve_flight()
            path = flight.record("goodput_anomaly",
                                 reasons=[f"goodput anomaly: {kind}"],
                                 extra=extra, registry=self._registry)
        except Exception:  # pragma: no cover - flight never raises, belt
            path = None
        if path is not None:
            self._last_bundle = path
            self._captures += 1
            self._registry.counter(
                "goodput_captures_total",
                "anomaly flight bundles written").inc()
        if self._profile_cm is None:
            cm = None
            try:
                cm = self._profiler(self.profile_dir)
            except Exception:  # pragma: no cover - profiler is best-effort
                cm = None
            if cm is None:
                self._registry.counter(
                    "goodput_capture_profile_skipped_total",
                    "anomaly captures that could not open an xprof "
                    "profile (trace already active or profiling "
                    "unavailable)").inc()
            else:
                try:
                    self._profile_path = cm.__enter__()
                    self._profile_cm = cm
                    self._profile_left = self.profile_steps
                except Exception:  # pragma: no cover
                    self._profile_cm = None

    def _tick_profile_locked(self) -> None:
        if self._profile_cm is None:
            return
        self._profile_left -= 1
        if self._profile_left <= 0:
            cm, self._profile_cm = self._profile_cm, None
            try:
                cm.__exit__(None, None, None)
            except Exception:  # pragma: no cover
                pass

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "in_episode": self._in_episode,
                "episodes": self._episodes,
                "captures": self._captures,
                "profile_open": self._profile_cm is not None,
                "profile_path": self._profile_path,
                "last_bundle": self._last_bundle,
                "band": {
                    "mean": self.detector.mean,
                    "std": self.detector.std,
                    "threshold": self.detector.threshold(),
                },
            }

    def close(self) -> None:
        """Close any open profile (end-of-run teardown)."""
        with self._lock:
            if self._profile_cm is not None:
                cm, self._profile_cm = self._profile_cm, None
                try:
                    cm.__exit__(None, None, None)
                except Exception:  # pragma: no cover
                    pass
