"""Fixed-memory in-process time-series store: the monitoring plane's memory.

Every observability surface this repo built so far is *point-in-time*:
``/metrics`` serves the registry as of the scrape, ``/snapshot`` the
newest ring of spans, flight bundles the counters AS OF the trigger.
Nothing retains history, so "p99 has been over SLO for 30 s" cannot be
evaluated anywhere and a postmortem sees the failure instant but not the
minutes before it. This module is the missing layer — a deliberately
small in-process TSDB in the Prometheus recording-rule tradition:

- :class:`TimeSeriesStore` — per-series **ring buffers** (fine tier, one
  point per sample) plus a **downsampled coarse tier** (min/max/mean over
  ``downsample`` fine points), both fixed-capacity: total memory is
  bounded by ``series x (retention + coarse_retention)`` and independent
  of run length (asserted in tests). Labeled series
  (``name{replica="r0"}``) share the exposition's escape rules.
- :class:`TsdbSampler` — a daemon thread that snapshots a
  :class:`~dcnn_tpu.obs.registry.MetricsRegistry` into the store at a
  cadence. Injectable clock, ``Event.wait``-paced, and **sleep-free in
  tests**: drive :meth:`TsdbSampler.sample_once` by hand. Not starting
  the sampler costs zero threads and zero per-step work.
- A query API in the PromQL-over-time vocabulary: :meth:`range`,
  :meth:`delta`, :meth:`rate`, :meth:`avg_over_time` /
  :meth:`max_over_time` / :meth:`min_over_time`, and
  :meth:`quantile_over_time` (histogram-quantile from bucket-count
  deltas over a window — the honest windowed p99, not the lifetime one).
- **Atomic JSONL persistence** (:meth:`persist` via
  ``resilience.atomic``): flight bundles and bench captures carry
  time-resolved history (``history.jsonl``), not just a final snapshot;
  :func:`load_history` reads it back for the CLI and tests.
- A postmortem CLI: ``python -m dcnn_tpu.obs.tsdb report|export|plot``
  (``plot`` renders an ASCII sparkline — the 2 a.m. terminal view).

Alert/recording rules over this store live in :mod:`~dcnn_tpu.obs.rules`;
the fleet-wide aggregation tier in :mod:`~dcnn_tpu.obs.fleet`. Stdlib
only, like the rest of ``obs``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .exposition import escape_label_value

#: history.jsonl schema version (bumped on incompatible layout changes)
_SCHEMA = 1


def render_series_key(name: str, labels: Optional[Dict[str, str]] = None
                      ) -> str:
    """Canonical series key: ``name`` or ``name{k="v",...}`` with sorted
    keys and exposition-rule escaping — the same spelling a Prometheus
    exposition line would use, so fleet series read naturally."""
    if not labels:
        return name
    body = ",".join(f'{k}="{escape_label_value(str(v))}"'
                    for k, v in sorted(labels.items()))
    return f"{name}{{{body}}}"


class _Ring:
    """Fixed-capacity ring of tuples. Preallocated; append is O(1) and
    allocation-free after the first lap."""

    __slots__ = ("cap", "_buf", "_n", "_i")

    def __init__(self, cap: int):
        self.cap = cap
        self._buf: List[Any] = [None] * cap
        self._n = 0
        self._i = 0

    def append(self, item) -> None:
        self._buf[self._i] = item
        self._i = (self._i + 1) % self.cap
        if self._n < self.cap:
            self._n += 1

    def __len__(self) -> int:
        return self._n

    def items(self) -> List[Any]:
        """Chronological contents (oldest first)."""
        if self._n < self.cap:
            return self._buf[:self._n]
        return self._buf[self._i:] + self._buf[:self._i]


class Series:
    """One series: fine ring of ``(t, v)`` + coarse ring of
    ``(t, min, max, mean, count)`` summarizing ``downsample`` fine points
    each. NOT thread-safe on its own — the owning store's lock guards it."""

    __slots__ = ("key", "name", "labels", "fine", "coarse", "first_t",
                 "_b_t", "_b_min", "_b_max", "_b_sum", "_b_n",
                 "_downsample")

    def __init__(self, key: str, name: str, labels: Dict[str, str], *,
                 retention: int, downsample: int, coarse_retention: int):
        self.key = key
        self.name = name
        self.labels = labels
        self.fine = _Ring(retention)
        self.coarse = _Ring(coarse_retention)
        self.first_t: Optional[float] = None  # first-EVER point (survives
        self._downsample = downsample         # ring eviction)
        self._b_t = 0.0
        self._b_min = float("inf")
        self._b_max = float("-inf")
        self._b_sum = 0.0
        self._b_n = 0

    def add(self, t: float, v: float) -> None:
        if self.first_t is None:
            self.first_t = t
        self.fine.append((t, v))
        self._b_t = t
        if v < self._b_min:
            self._b_min = v
        if v > self._b_max:
            self._b_max = v
        self._b_sum += v
        self._b_n += 1
        if self._b_n >= self._downsample:
            self.coarse.append((self._b_t, self._b_min, self._b_max,
                                self._b_sum / self._b_n, self._b_n))
            self._b_min = float("inf")
            self._b_max = float("-inf")
            self._b_sum = 0.0
            self._b_n = 0


class TimeSeriesStore:
    """Thread-safe fixed-memory store of :class:`Series` ring buffers.

    ``max_series`` bounds cardinality: past it, NEW series are dropped
    (counted on :attr:`dropped_series`) rather than growing without
    bound — a labeled-series explosion must degrade history, not the
    process. All timestamps are in the injected ``clock`` domain
    (monotonic by default); ``wall_clock`` anchors persistence so a
    reader can map them back to wall time.
    """

    def __init__(self, *, retention: int = 600, downsample: int = 10,
                 coarse_retention: int = 360, max_series: int = 4096,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time):
        if retention < 2 or downsample < 1 or coarse_retention < 1:
            raise ValueError(
                f"need retention >= 2, downsample >= 1, coarse_retention "
                f">= 1 (got {retention}, {downsample}, {coarse_retention})")
        if max_series < 1:
            raise ValueError(f"max_series must be >= 1, got {max_series}")
        self.retention = retention
        self.downsample = downsample
        self.coarse_retention = coarse_retention
        self.max_series = max_series
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._series: Dict[str, Series] = {}   # dcnn: guarded_by=_lock
        self._dropped = 0                      # dcnn: guarded_by=_lock
        self._samples = 0                      # dcnn: guarded_by=_lock

    # -- writing -----------------------------------------------------------
    def add(self, name: str, value: float, *, t: Optional[float] = None,
            labels: Optional[Dict[str, str]] = None) -> None:
        """Record one point. ``t`` defaults to the store clock's now."""
        if t is None:
            t = self._clock()
        key = render_series_key(name, labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self._dropped += 1
                    return
                s = Series(key, name, dict(labels or {}),
                           retention=self.retention,
                           downsample=self.downsample,
                           coarse_retention=self.coarse_retention)
                self._series[key] = s
            s.add(t, float(value))

    def sample_registry(self, registry, *, t: Optional[float] = None
                        ) -> int:
        """One sampling pass over a registry: every Counter/Gauge becomes
        a point on its own series; every Histogram becomes ``_sum`` /
        ``_count`` points plus per-bucket **cumulative** counts
        (``name_bucket{le="..."}``, non-empty buckets only) — exactly the
        shape :meth:`quantile_over_time` consumes. Returns the number of
        points written."""
        from .registry import Counter, Gauge, Histogram

        if t is None:
            t = self._clock()
        wrote = 0
        for name, inst in registry.instruments():
            if isinstance(inst, Histogram):
                v = inst.value
                self.add(name + "_sum", v["sum"], t=t)
                self.add(name + "_count", v["count"], t=t)
                wrote += 2
                for bound, cum in inst.cumulative()[:-1]:
                    if cum:
                        self.add(name + "_bucket", cum, t=t,
                                 labels={"le": repr(bound)})
                        wrote += 1
            elif isinstance(inst, (Counter, Gauge)):
                self.add(name, float(inst.value), t=t)
                wrote += 1
        with self._lock:
            self._samples += 1
        return wrote

    def sample_exposition(self, text: str, *, t: Optional[float] = None
                          ) -> int:
        """One sampling pass over Prometheus exposition TEXT (the same
        contract the fleet tier scrapes): scalar families become points,
        histogram families become ``_sum``/``_count`` + cumulative
        bucket points. This is how a surface whose exposition carries
        DERIVED gauges (``ServeMetrics.prometheus`` — windowed p99, shed
        fraction) gets them into history: they exist only in the text,
        never in the registry. Returns points written; malformed text
        raises ``ValueError`` (parse contract)."""
        from .exposition import parse_prometheus_text

        if t is None:
            t = self._clock()
        wrote = 0
        for name, fam in parse_prometheus_text(text).items():
            if fam.get("kind") == "histogram":
                if "sum" in fam:
                    self.add(name + "_sum", fam["sum"], t=t)
                    wrote += 1
                if "count" in fam:
                    self.add(name + "_count", fam["count"], t=t)
                    wrote += 1
                for bound, cum in fam.get("buckets", []):
                    if cum and bound != float("inf"):
                        self.add(name + "_bucket", cum, t=t,
                                 labels={"le": repr(bound)})
                        wrote += 1
            elif "value" in fam:
                self.add(name, float(fam["value"]), t=t)
                wrote += 1
        with self._lock:
            self._samples += 1
        return wrote

    # -- introspection -----------------------------------------------------
    @property
    def dropped_series(self) -> int:
        with self._lock:
            return self._dropped

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def points(self) -> int:
        """Total fine points currently retained (bounded by
        ``series x retention`` — the fixed-memory invariant)."""
        with self._lock:
            return sum(len(s.fine) for s in self._series.values())

    def summary(self) -> Dict[str, Any]:
        """Small JSON block for ``/snapshot``: shape, not data."""
        with self._lock:
            return {"series": len(self._series),
                    "points": sum(len(s.fine) for s in
                                  self._series.values()),
                    "samples": self._samples,
                    "dropped_series": self._dropped,
                    "retention": self.retention,
                    "downsample": self.downsample}

    # -- queries -----------------------------------------------------------
    def _get(self, key: str) -> Optional[Series]:
        return self._series.get(key)

    def range(self, key: str, window_s: Optional[float] = None, *,
              tier: str = "fine") -> List[Tuple[float, ...]]:
        """Chronological points of one series key. ``tier="fine"`` yields
        ``(t, v)``; ``tier="coarse"`` yields ``(t, min, max, mean,
        count)``. ``window_s`` keeps only points newer than ``now -
        window_s``."""
        if tier not in ("fine", "coarse"):
            raise ValueError(f"tier must be fine|coarse, got {tier!r}")
        now = self._clock()
        with self._lock:
            s = self._get(key)
            if s is None:
                return []
            pts = (s.fine if tier == "fine" else s.coarse).items()
        if window_s is not None:
            cut = now - window_s
            pts = [p for p in pts if p[0] >= cut]
        return pts

    def latest(self, key: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            s = self._get(key)
            if s is None or not len(s.fine):
                return None
            pts = s.fine.items()
        return pts[-1]

    def value_at_or_before(self, key: str, t: float,
                           default: Optional[float] = None
                           ) -> Optional[float]:
        """Newest value with timestamp <= ``t`` (cumulative series are
        step functions — between samples the value holds)."""
        with self._lock:
            s = self._get(key)
            pts = s.fine.items() if s is not None else []
        best = default
        for pt, pv in pts:
            if pt <= t:
                best = pv
            else:
                break
        return best

    def delta(self, key: str, window_s: float) -> Optional[float]:
        """last - first over the window (None with < 2 points)."""
        pts = self.range(key, window_s)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def rate(self, key: str, window_s: float) -> Optional[float]:
        """Per-second increase over the window — the counter verb."""
        pts = self.range(key, window_s)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return (pts[-1][1] - pts[0][1]) / dt

    def avg_over_time(self, key: str, window_s: float) -> Optional[float]:
        pts = self.range(key, window_s)
        if not pts:
            return None
        return sum(p[1] for p in pts) / len(pts)

    def max_over_time(self, key: str, window_s: float) -> Optional[float]:
        pts = self.range(key, window_s)
        if not pts:
            return None
        return max(p[1] for p in pts)

    def min_over_time(self, key: str, window_s: float) -> Optional[float]:
        pts = self.range(key, window_s)
        if not pts:
            return None
        return min(p[1] for p in pts)

    def _window_delta(self, key: str, start: float, now: float
                      ) -> Optional[float]:
        """Increase of a cumulative series over ``[start, now]`` with one
        consistent basis for every series of a histogram family: the
        newest value at-or-before ``start`` when retained; the oldest
        retained point when eviction already ate the true basis (the
        closest available approximation — and the SAME one for count and
        buckets, so a quantile never mixes bases); exactly 0 when the
        series was born inside the window (cumulatives start at 0)."""
        with self._lock:
            s = self._get(key)
            if s is None:
                return None
            pts = s.fine.items()
            first_t = s.first_t
        if not pts:
            return None
        end_v = None
        for pt, pv in pts:
            if pt <= now:
                end_v = pv
            else:
                break
        if end_v is None:
            return None
        start_v: Optional[float] = None
        for pt, pv in pts:
            if pt <= start:
                start_v = pv
            else:
                break
        if start_v is None:
            start_v = 0.0 if (first_t is None or first_t > start) \
                else pts[0][1]
        return end_v - start_v

    def quantile_over_time(self, hist_name: str, q: float,
                           window_s: float) -> Optional[float]:
        """Histogram quantile from bucket-count **deltas** over the
        window (the ``histogram_quantile(rate(...))`` shape): linear
        interpolation inside the winning bucket, bounded above by the
        largest finite bucket bound. ``None`` when the window saw no
        observations."""
        if not 0 <= q <= 1:
            raise ValueError(f"q must be in [0, 1], got {q}")
        now = self._clock()
        start = now - window_s
        prefix = hist_name + "_bucket"
        with self._lock:
            buckets = [(float(s.labels["le"]), s.key)
                       for s in self._series.values()
                       if s.name == prefix and "le" in s.labels]
        if not buckets:
            return None
        total = self._window_delta(hist_name + "_count", start, now)
        if total is None or total <= 0:
            return None
        target = q * total
        buckets.sort()
        prev_bound = 0.0
        acc_prev = 0.0
        for bound, key in buckets:
            acc = self._window_delta(key, start, now) or 0.0
            if acc >= target:
                span = acc - acc_prev
                frac = ((target - acc_prev) / span) if span > 0 else 1.0
                return prev_bound + (bound - prev_bound) * frac
            prev_bound, acc_prev = bound, acc
        # target beyond the largest finite bucket: report its bound (the
        # observation landed in the +Inf overflow — no finite estimate)
        return buckets[-1][0]

    # -- persistence -------------------------------------------------------
    def to_jsonl_bytes(self) -> bytes:
        """The ``history.jsonl`` document: a header line with store meta
        (schema, knobs, wall anchor mapping the monotonic domain to wall
        time) then one line per series with fine + coarse points."""
        with self._lock:
            series = list(self._series.values())
            samples = self._samples
        header = {"tsdb": {
            "schema": _SCHEMA,
            "retention": self.retention,
            "downsample": self.downsample,
            "coarse_retention": self.coarse_retention,
            "samples": samples,
            # wall = t + wall_anchor for any point timestamp t
            "wall_anchor": self._wall() - self._clock(),
        }}
        lines = [json.dumps(header)]
        for s in sorted(series, key=lambda s: s.key):
            with self._lock:
                fine = [(round(t, 4), v) for t, v in s.fine.items()]
                coarse = [(round(c[0], 4),) + tuple(c[1:])
                          for c in s.coarse.items()]
            lines.append(json.dumps({
                "series": s.key, "name": s.name, "labels": s.labels,
                "points": fine, "coarse": coarse}))
        return ("\n".join(lines) + "\n").encode("utf-8")

    def persist(self, path: str) -> str:
        """Atomic JSONL dump (tmp sibling + fsync + replace — a
        preempted dump can never publish a torn history file)."""
        from ..resilience.atomic import write_file_atomic

        write_file_atomic(path, self.to_jsonl_bytes())
        return path


def load_history(path: str) -> Tuple[Dict[str, Any],
                                     Dict[str, Dict[str, Any]]]:
    """Read a ``history.jsonl`` back: ``(meta, {series_key: {"name",
    "labels", "points", "coarse"}})``. Malformed lines raise — a
    half-trusted history misleads a postmortem."""
    meta: Dict[str, Any] = {}
    series: Dict[str, Dict[str, Any]] = {}
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: bad JSONL: {e}") from e
            if "tsdb" in obj:
                meta = dict(obj["tsdb"])
            elif "series" in obj:
                series[obj["series"]] = {
                    "name": obj.get("name", obj["series"]),
                    "labels": obj.get("labels", {}),
                    "points": [tuple(p) for p in obj.get("points", [])],
                    "coarse": [tuple(c) for c in obj.get("coarse", [])],
                }
            else:
                raise ValueError(f"{path}:{lineno}: neither header nor "
                                 f"series: {obj!r}")
    return meta, series


def series_stats(points: List[Tuple[float, float]]) -> Dict[str, Any]:
    """min/mean/max/last over ``(t, v)`` points — the compact block
    bench captures and `report` print."""
    if not points:
        return {"points": 0, "min": None, "mean": None, "max": None,
                "last": None}
    vals = [p[1] for p in points]
    return {"points": len(vals), "min": min(vals),
            "mean": sum(vals) / len(vals), "max": max(vals),
            "last": vals[-1]}


def summarize_history(path: str, *, top: int = 8) -> Dict[str, Any]:
    """Front-page summary of a ``history.jsonl`` (``trace.py inspect``
    calls this for bundles): series/point counts, covered time span, and
    stats for the busiest series."""
    meta, series = load_history(path)
    t_lo: Optional[float] = None
    t_hi: Optional[float] = None
    total = 0
    for s in series.values():
        for t, _v in s["points"]:
            t_lo = t if t_lo is None or t < t_lo else t_lo
            t_hi = t if t_hi is None or t > t_hi else t_hi
        total += len(s["points"])
    busiest = sorted(series.items(), key=lambda kv: -len(kv[1]["points"]))
    return {
        "series": len(series),
        "points": total,
        "span_s": (round(t_hi - t_lo, 3)
                   if t_lo is not None and t_hi is not None else None),
        "samples": meta.get("samples"),
        "top": {k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                    for kk, vv in series_stats(v["points"]).items()}
                for k, v in busiest[:top]},
    }


class TsdbSampler:
    """The cadence thread: snapshot ``registry`` into ``store`` every
    ``interval_s``. Daemon + :meth:`stop`-joinable; never started =
    zero threads. ``after_sample`` callbacks run after each pass on the
    sampler thread — the rule engine's evaluation hook. ``text_fn``
    switches the pass to exposition-text sampling
    (:meth:`TimeSeriesStore.sample_exposition`) — the wiring for
    surfaces like ``ServeMetrics`` whose derived windowed gauges exist
    only in their rendered text."""

    def __init__(self, store: TimeSeriesStore, *, registry=None,
                 interval_s: float = 1.0,
                 text_fn: Optional[Callable[[], str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tick_clock: Callable[[], float] = time.perf_counter):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.store = store
        self.text_fn = text_fn
        if registry is None:
            from .registry import get_registry
            registry = get_registry()
        self.registry = registry
        self.interval_s = interval_s
        self._clock = clock
        self._tick_clock = tick_clock
        self._after: List[Callable[[TimeSeriesStore], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._samples = self.registry.counter(
            "tsdb_samples_total", "tsdb sampling passes completed")
        self._errors = self.registry.counter(
            "tsdb_sample_errors_total", "tsdb sampling passes that raised")
        self._tick_hist = self.registry.histogram(
            "tsdb_sample_seconds", "wall per tsdb sampling pass")
        self._series_gauge = self.registry.gauge(
            "tsdb_series", "series currently retained in the tsdb")

    def add_after_sample(self, fn: Callable[[TimeSeriesStore], None]
                         ) -> "TsdbSampler":
        """Register a post-pass hook (rule evaluation). Wire before
        :meth:`start` — the list is read from the sampler thread."""
        self._after.append(fn)
        return self

    def sample_once(self) -> int:
        """One pass: snapshot the registry, refresh the sampler's own
        instruments, run the hooks. Returns points written. Exceptions
        are counted and re-raised — the thread loop swallows them so a
        broken provider cannot kill the cadence, while a by-hand test
        caller still sees the failure."""
        t0 = self._tick_clock()
        try:
            if self.text_fn is not None:
                wrote = self.store.sample_exposition(self.text_fn(),
                                                     t=self._clock())
            else:
                wrote = self.store.sample_registry(self.registry,
                                                   t=self._clock())
            for fn in self._after:
                fn(self.store)
        except Exception:
            self._errors.inc()
            raise
        finally:
            self._tick_hist.observe(self._tick_clock() - t0)
        self._samples.inc()
        self._series_gauge.set(len(self.store.series_names()))
        return wrote

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TsdbSampler":
        """Idempotent; one daemon thread paced by ``Event.wait`` (a
        :meth:`stop` wakes it immediately — no sleep to ride out)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dcnn-tsdb-sampler")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                pass  # counted in sample_once; cadence must survive

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "TsdbSampler":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# -------------------------------------------------------------------- CLI

_SPARK = " .:-=+*#%@"


def sparkline(values: List[float], *, width: int = 60) -> str:
    """ASCII sparkline (pure-ASCII ramp — 2 a.m. terminals over serial
    consoles included). Values are binned to ``width`` columns by mean."""
    if not values:
        return ""
    if len(values) > width:
        binned = []
        step = len(values) / width
        for i in range(width):
            lo, hi = int(i * step), max(int((i + 1) * step), int(i * step) + 1)
            chunk = values[lo:hi]
            binned.append(sum(chunk) / len(chunk))
        values = binned
    lo, hi = min(values), max(values)
    span = hi - lo
    out = []
    for v in values:
        frac = (v - lo) / span if span > 0 else 0.5
        out.append(_SPARK[min(int(frac * (len(_SPARK) - 1) + 0.5),
                              len(_SPARK) - 1)])
    return "".join(out)


def _cli_report(path: str) -> int:
    meta, series = load_history(path)
    print(f"{path}: {len(series)} series, "
          f"{sum(len(s['points']) for s in series.values())} points "
          f"(schema {meta.get('schema')}, {meta.get('samples')} samples)")
    width = max((len(k) for k in series), default=0)
    for key in sorted(series):
        st = series_stats(series[key]["points"])
        if not st["points"]:
            continue
        print(f"  {key:<{width}}  n={st['points']:<5d} "
              f"min={st['min']:<12.6g} mean={st['mean']:<12.6g} "
              f"max={st['max']:<12.6g} last={st['last']:.6g}")
    return 0


def _cli_export(path: str, out: Optional[str]) -> int:
    meta, series = load_history(path)
    doc = {"meta": meta,
           "series": {k: {"labels": v["labels"], "points": v["points"]}
                      for k, v in series.items()}}
    text = json.dumps(doc, indent=1)
    if out:
        from ..resilience.atomic import write_file_atomic
        write_file_atomic(out, text.encode("utf-8"))
        print(f"exported {len(series)} series -> {out}")
    else:
        print(text)
    return 0


def _cli_plot(path: str, series_key: str, width: int) -> int:
    _meta, series = load_history(path)
    matches = [k for k in series
               if k == series_key or series[k]["name"] == series_key]
    if not matches:
        print(f"error: series {series_key!r} not in {path}; have:",
              *sorted(series), sep="\n  ")
        return 1
    for k in sorted(matches):
        pts = series[k]["points"]
        st = series_stats(pts)
        if not st["points"]:
            continue
        print(f"{k}  [{st['min']:.6g} .. {st['max']:.6g}] "
              f"last={st['last']:.6g}")
        print(f"  |{sparkline([p[1] for p in pts], width=width)}|")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m dcnn_tpu.obs.tsdb",
        description="Inspect persisted tsdb history (history.jsonl from "
                    "flight bundles / bench captures).")
    sub = ap.add_subparsers(dest="cmd")
    rp = sub.add_parser("report", help="per-series min/mean/max/last table")
    rp.add_argument("history", help="history.jsonl path")
    ep = sub.add_parser("export", help="history -> one JSON document")
    ep.add_argument("history")
    ep.add_argument("-o", "--out", default=None,
                    help="output path (default: stdout)")
    pp = sub.add_parser("plot", help="ASCII sparkline of one series")
    pp.add_argument("history")
    pp.add_argument("series", help="series key or bare metric name")
    pp.add_argument("--width", type=int, default=60)
    args = ap.parse_args(argv)
    if args.cmd is None:
        ap.print_help()
        return 2
    try:
        if args.cmd == "report":
            return _cli_report(args.history)
        if args.cmd == "export":
            return _cli_export(args.history, args.out)
        return _cli_plot(args.history, args.series, args.width)
    except BrokenPipeError:
        return 0  # `... report | head` closing early is not an error
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
