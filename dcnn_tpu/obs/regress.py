"""Bench-history regression gate over the ``BENCH_r*.json`` trajectory.

Every driver capture appends one ``BENCH_rNN.json`` to the repo root; the
trajectory (r01 6.7k → r05 26.4k img/s) is the project's performance
record — and until now nothing read it back, so a perf cliff would ship
silently. This module compares the newest capture against a trailing
window of prior captures, per metric, and answers one question: *did we
just get meaningfully worse at anything we already did better?*

Gate semantics (deliberately asymmetric — improvements always pass):

- per metric, the baseline is the **best** value in the trailing window
  (max for higher-is-better, min for lower-is-better). Comparing against
  the best — not the mean — means a regression can't hide behind a weak
  early capture while the trajectory was still climbing;
- a regression is a relative move past the metric's ``tolerance``
  (default 20%): ``newest < best × (1 - tol)`` for higher-is-better,
  ``newest > best × (1 + tol)`` for lower-is-better;
- metrics absent from a capture are skipped for that capture (r01 carries
  only img/s — history grows monotonically richer, the gate never
  requires retro-fitting old files);
- a metric may declare a ``guard`` path: only window captures whose guard
  value equals the newest capture's are comparable (``compile_s`` is
  guarded on ``phases.compile_cache_hit`` — a cold compile after a warm
  one is a cache state change, not a compiler regression).

Per-metric tolerances encode measured run-to-run noise: ``h2d_gbps``
rides the TPU tunnel and has bounced 3x between healthy captures
(r02 0.033 → r03 0.010 → r04 0.032), so its tolerance is wide; img/s at
best-of-5-reps is tight.

Consumers: ``benchmarks/compare.py`` (standalone CLI + ``--self-test``
fixture run, wired into tier-1) and ``bench.py`` (embeds the verdict as a
``regressions`` block in each new capture, so BENCH_r06+ files carry
their own gate result).
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

_BENCH_RE = re.compile(r"BENCH_r(\d+)\.json$")


@dataclass(frozen=True)
class MetricSpec:
    """One gated metric: a dotted ``path`` into the capture's parsed JSON,
    a direction, and an optional noise tolerance / comparability guard.
    ``fallback`` names a second path tried when ``path`` is absent — the
    continuity mechanism for renamed keys (``mfu_formula`` reads old
    captures' ``mfu``, so the r01-r05 trajectory keeps gating the formula
    series across the headline-MFU switch)."""

    name: str
    path: str
    higher_is_better: bool = True
    tolerance: Optional[float] = None  # None -> the gate's default
    guard: Optional[str] = None        # dotted path; must match to compare
    fallback: Optional[str] = None     # alternate path for older captures
    # absolute slack added to the relative band. Essential for
    # lower-is-better metrics that legitimately record 0.0 (zero SLO
    # minutes, an un-delayed reaction): best=0 collapses the relative
    # band to nothing and every later nonzero capture would flag
    # REGRESSED forever
    atol: float = 0.0


# The ISSUE-mandated gate set: img/s, MFU, h2d bandwidth, compile wall,
# int8 serving, and the router-tier headlines (BENCH_SERVE=1 `serving.
# router` block). Tolerances per the noise notes in the module docstring;
# `availability` during the kill-a-replica soak is a correctness-adjacent
# number, so its tolerance is tight.
DEFAULT_METRICS: Sequence[MetricSpec] = (
    MetricSpec("img_per_sec", "value"),
    # headline-MFU switch (this release): `mfu` is now the XLA
    # cost-analysis figure, so the continuous formula series moved to
    # `mfu_formula` — gated with an `mfu` fallback so r01-r05 captures
    # (which only carry `mfu` = the formula value) stay in the window;
    # the analytic series gates separately and only against captures
    # that measured it.
    MetricSpec("mfu_formula", "mfu_formula", fallback="mfu"),
    MetricSpec("mfu_analytic", "mfu_analytic"),
    MetricSpec("h2d_gbps", "h2d_gbps", tolerance=0.75),
    MetricSpec("compile_s", "phases.compile_s", higher_is_better=False,
               tolerance=0.5, guard="phases.compile_cache_hit"),
    # the AOT warm-start wall (BENCH_AOT=1): guarded on the capture's
    # warm_hit flag — on the serialization-fallback path (backend that
    # can't serialize, full disk) NOTHING is committed, so the "warm"
    # pass is a full compile wall; comparing that against hit-path
    # captures would flag a spurious 150 s "regression" (or poison the
    # window and mask a real one). 50% tolerance absorbs deserialize/IO
    # jitter on small absolute values
    MetricSpec("aot_warm_start_s", "phases.aot_warm_start_s",
               higher_is_better=False, tolerance=0.5,
               guard="aot.train.warm_hit"),
    MetricSpec("serve_int8_img_per_sec", "infer_int8_img_per_sec"),
    MetricSpec("serve_router_capacity_img_per_sec",
               "serving.router.capacity_img_per_sec",
               guard="serving.router.replicas"),
    MetricSpec("serve_router_capacity_scaling",
               "serving.router.capacity_scaling_x",
               guard="serving.router.replicas"),
    MetricSpec("serve_router_kill_availability",
               "serving.router.kill_soak.availability", tolerance=0.05),
    # the autoscaler's diurnal soak (BENCH_AUTOSCALE=1, PR 11):
    # availability through kill + canary + every fleet resize is
    # correctness-adjacent like the kill soak, so its tolerance is
    # tight; pre-PR-11 captures simply lack the `autoscale` block and
    # are skipped, not lied about (the gate's absent-metric semantics).
    MetricSpec("autoscale.availability", "autoscale.availability",
               tolerance=0.01),
    # atol: a clean capture records exactly 0.0 minutes/seconds (zero
    # breach, un-delayed first reaction), and the soak gates both at the
    # ~1-minute / one-cooldown budget — values inside the budget are
    # operating-as-designed, not a regression against a perfect window
    MetricSpec("autoscale.slo_violation_minutes",
               "autoscale.slo_violation_minutes", higher_is_better=False,
               tolerance=0.5, atol=1.0),
    # reaction time is budgeted by the configured cooldown — comparing
    # across different budgets would be a config change masquerading as
    # a regression, so the guard pins the knob
    MetricSpec("autoscale.scale_up_reaction_s",
               "autoscale.scale_up_reaction_s", higher_is_better=False,
               tolerance=0.5, guard="autoscale.up_cooldown_s", atol=5.0),
    # the pipeline kill-a-stage probe (BENCH_FAULTS=1, ISSUE 13):
    # detection + repartition-and-resume walls are loopback sub-second
    # numbers with scheduler noise, hence the atol slack; batches_lost is
    # correctness-adjacent (the journal contract says 0), so ANY increase
    # flags. Guarded on the probe's stage count — a topology change is
    # config, not regression. Pre-PR-13 captures lack the block and are
    # skipped, not lied about.
    MetricSpec("pipeline.detection_s", "resilience.pipeline.detection_s",
               higher_is_better=False, tolerance=1.0, atol=0.5,
               guard="resilience.pipeline.stages"),
    MetricSpec("pipeline.repartition_wall_s",
               "resilience.pipeline.repartition_wall_s",
               higher_is_better=False, tolerance=1.0, atol=2.0,
               guard="resilience.pipeline.stages"),
    MetricSpec("pipeline.batches_lost", "resilience.pipeline.batches_lost",
               higher_is_better=False, tolerance=0.0,
               guard="resilience.pipeline.stages"),
    # the uint8-first feed wire (ISSUE 16): bytes actually shipped
    # host-to-device per image is a design invariant of the wire contract
    # (uint8 + int labels — regrowing toward 4x/fp32 would be a feed-path
    # regression, not noise), so its tolerance is tight. Pre-r06 captures
    # lack the key and are skipped, not lied about.
    MetricSpec("wire_bytes_per_image",
               "streaming_timeline.wire_bytes_per_image",
               higher_is_better=False, tolerance=0.05),
    # streaming throughput is only comparable between captures that
    # shipped the same bytes per image — a wire-dtype change re-baselines
    # the feed, so the guard pins it; pre-r06 captures have no guard
    # value and are skipped (skip-not-lie), exactly like the autoscale
    # block's absent-metric semantics
    MetricSpec("streaming_img_per_sec", "streaming_img_per_sec",
               tolerance=0.3,
               guard="streaming_timeline.wire_bytes_per_image"),
    # goodput plane (ISSUE 18): the fraction of the capture's wall the
    # ledger attributes to compute. Only BENCH_OBS=1 r06+ captures carry
    # the block — earlier captures are skipped, not lied about.
    MetricSpec("goodput_fraction",
               "telemetry_essentials.goodput.goodput_fraction",
               tolerance=0.25),
    # gray-failure probes (BENCH_FAULTS=1 `resilience.gray` block,
    # ISSUE 19): detection + eviction walls for the stalled elastic peer
    # (50 ms absolute stall per step, ~10x its healthy compute wall)
    # are loopback sub-second numbers with scheduler noise (atol slack,
    # like the pipeline kill probe above); hedged-serving p99 is gated as
    # the with-hedge/without-hedge ratio so machine speed divides out.
    # Guards pin the probe's topology knobs — pre-r19 captures lack the
    # block and are skipped, not lied about.
    MetricSpec("gray.detection_s", "resilience.gray.detection_s",
               higher_is_better=False, tolerance=1.0, atol=1.0,
               guard="resilience.gray.peers"),
    MetricSpec("gray.evict_wall_s", "resilience.gray.evict_wall_s",
               higher_is_better=False, tolerance=1.0, atol=1.0,
               guard="resilience.gray.peers"),
    MetricSpec("gray.hedge_p99_ratio", "resilience.gray.hedge_p99_ratio",
               higher_is_better=False, tolerance=0.5, atol=0.5,
               guard="resilience.gray.hedge_replicas"),
    # continuous-batching decode (BENCH_DECODE=1 `decode` block,
    # ISSUE 20): generated tokens/s and mean slot occupancy for the
    # continuous batcher on the synthetic length mix; TTFT p99 is a
    # loopback sub-10ms wall, so wide relative tolerance + atol slack
    # (scheduler noise dominates). Guards pin the probe's slot count —
    # pre-r20 captures lack the block and are skipped, not lied about.
    MetricSpec("decode.tokens_per_sec", "decode.tokens_per_sec",
               tolerance=0.3, guard="decode.max_slots"),
    MetricSpec("decode.ttft_p99_ms", "decode.ttft_p99_ms",
               higher_is_better=False, tolerance=1.0, atol=10.0,
               guard="decode.max_slots"),
    MetricSpec("decode.slot_occupancy", "decode.slot_occupancy",
               tolerance=0.25, guard="decode.max_slots"),
)

DEFAULT_TOLERANCE = 0.2
DEFAULT_WINDOW = 4


def get_path(d: Any, path: str) -> Optional[Any]:
    """Resolve a dotted path into nested dicts; None on any miss."""
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def load_capture(path: str) -> Optional[Dict[str, Any]]:
    """One BENCH file → its parsed-metrics dict, or None when unreadable.
    Driver captures wrap the bench JSON under ``"parsed"``; a bare bench
    JSON (a local ``python bench.py > out.json``) is accepted as-is."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"]
    if isinstance(data, dict) and "metric" in data:
        return data
    return None


def find_bench_files(root: str) -> List[str]:
    """``BENCH_r*.json`` under ``root``, oldest → newest by capture
    number (NOT mtime — a re-checkout resets mtimes, numbers don't)."""
    hits = []
    for p in _glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _BENCH_RE.search(os.path.basename(p))
        if m:
            hits.append((int(m.group(1)), p))
    return [p for _, p in sorted(hits)]


def compare(history: Sequence[Dict[str, Any]], *,
            metrics: Sequence[MetricSpec] = DEFAULT_METRICS,
            tolerance: float = DEFAULT_TOLERANCE,
            window: int = DEFAULT_WINDOW) -> Dict[str, Any]:
    """Gate the LAST entry of ``history`` against the trailing window of
    earlier entries. Returns the report dict (see keys below); raises
    ``ValueError`` on an empty history or nonsensical knobs."""
    if not history:
        raise ValueError("empty bench history: nothing to compare")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if not 0 < tolerance < 1:
        raise ValueError(f"tolerance must be in (0, 1), got {tolerance}")
    newest, prior = history[-1], list(history[:-1])
    rows: List[Dict[str, Any]] = []
    regressions: List[str] = []

    def resolve(entry, spec):
        v = get_path(entry, spec.path)
        if v is None and spec.fallback:
            v = get_path(entry, spec.fallback)
        return v

    for spec in metrics:
        tol = spec.tolerance if spec.tolerance is not None else tolerance
        cur = resolve(newest, spec)
        row: Dict[str, Any] = {
            "metric": spec.name, "path": spec.path,
            "higher_is_better": spec.higher_is_better,
            "tolerance": tol, "newest": cur,
        }
        if not isinstance(cur, (int, float)):
            row["verdict"] = "skipped: metric absent from newest capture"
            rows.append(row)
            continue
        guard_val = get_path(newest, spec.guard) if spec.guard else None
        vals: List[float] = []
        for entry in reversed(prior):  # newest-first until the window fills
            v = resolve(entry, spec)
            if not isinstance(v, (int, float)):
                continue
            if spec.guard and get_path(entry, spec.guard) != guard_val:
                continue  # different regime (e.g. cache warmth) — not
                # comparable, and saying so beats a false alarm
            vals.append(float(v))
            if len(vals) >= window:
                break
        if not vals:
            row["verdict"] = "skipped: no comparable prior capture"
            rows.append(row)
            continue
        best = max(vals) if spec.higher_is_better else min(vals)
        ratio = (float(cur) / best) if best else None
        if spec.higher_is_better:
            regressed = float(cur) < best * (1.0 - tol) - spec.atol
        else:
            regressed = float(cur) > best * (1.0 + tol) + spec.atol
        row.update({"window": list(reversed(vals)), "best": best,
                    "ratio": round(ratio, 4) if ratio is not None else None,
                    "verdict": "REGRESSED" if regressed else "ok"})
        rows.append(row)
        if regressed:
            regressions.append(spec.name)
    return {"metrics": rows, "regressions": regressions,
            "ok": not regressions, "window": window,
            "default_tolerance": tolerance}


def compare_files(paths: Sequence[str], **kw) -> Dict[str, Any]:
    """:func:`compare` over capture FILES (oldest → newest). Unreadable
    files are reported, never silently dropped."""
    history, skipped = [], []
    used = []
    for p in paths:
        cap = load_capture(p)
        if cap is None:
            skipped.append(p)
            continue
        history.append(cap)
        used.append(p)
    report = compare(history, **kw)
    report["files"] = used
    report["unparseable_files"] = skipped
    return report


def gate_current(current: Dict[str, Any], root: str, **kw
                 ) -> Optional[Dict[str, Any]]:
    """Gate an in-flight bench result (``bench.py``'s ``out`` dict)
    against the ``BENCH_r*.json`` history under ``root``. ``None`` when
    there is no history (first capture — nothing to regress against);
    never raises: the gate is a passenger on the bench run, not a way to
    crash it."""
    try:
        files = find_bench_files(root)
        history = [c for c in (load_capture(p) for p in files)
                   if c is not None]
        if not history:
            return None
        report = compare(history + [current], **kw)
        report["baseline_files"] = files
        return report
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def format_report(report: Dict[str, Any]) -> str:
    """Human-readable table for the CLI."""
    lines = []
    for row in report["metrics"]:
        if "best" not in row:
            lines.append(f"  {row['metric']:<24} {row['verdict']}")
            continue
        arrow = "↑" if row["higher_is_better"] else "↓"
        lines.append(
            f"  {row['metric']:<24} {arrow} newest {row['newest']:g} "
            f"vs best-of-{len(row['window'])} {row['best']:g} "
            f"(ratio {row['ratio']}, tol {row['tolerance']:.0%}) "
            f"-> {row['verdict']}")
    verdict = ("OK: no regressions" if report["ok"] else
               f"REGRESSED: {', '.join(report['regressions'])}")
    return "\n".join(lines + [verdict])
