"""Fleet aggregation: N telemetry surfaces merged into one monitoring
plane.

PR 9-13 made every process scrapeable (``TelemetryServer`` per trainer /
replica / coordinator), but each surface is an island: the autoscaler
hand-rolled per-replica scrape deltas, the regress gate reads offline
captures, and no endpoint answers "what is the FLEET's p99" or "which
replica is degraded" in one request. :class:`FleetAggregator` is that
missing tier — the in-process analogue of a Prometheus server federating
its scrape targets:

- **Targets** are added by URL (:class:`HttpScraper` transport — real
  fleets), by in-process ``TelemetryServer`` (fast path: reads
  ``metrics_body()`` directly, same text, zero sockets), or by bare
  scrape callable (the autoscaler's replica wiring). One :meth:`poll`
  scrapes every target, parses the exposition through the SAME
  :func:`~dcnn_tpu.obs.exposition.parse_prometheus_text` contract an
  external Prometheus speaks, and merges the scalars into **labeled
  fleet series** in the aggregator's own tsdb: per-replica
  (``m{replica="r0"}``) plus ``m{fleet="sum"}`` / ``m{fleet="max"}``.
- **Scrape self-observability** (the PR 11 parse-failure lesson): every
  target scrape is timed (``fleet_scrape_seconds``) and counted
  (``fleet_scrape_requests_total`` / ``fleet_scrape_errors_total``), a
  per-target ``fleet_target_up{replica=...}`` series records reachability
  history, and ``fleet_targets`` / ``fleet_targets_up`` gauges make a
  silent half-dead target visible on the aggregator's own exposition.
- **Serving**: :meth:`serve` stands up a ``TelemetryServer`` with the
  fleet's registry plus three fleet routes — ``/fleet`` (merged labeled
  series + per-target status), ``/alerts`` (the rule engine's state
  docs), and the standard ``/healthz`` carrying a **fleet roll-up
  check** (degraded when any target is unreachable or itself 503) and,
  when rules are wired, :func:`~dcnn_tpu.obs.rules.rules_check`.
- The :class:`~dcnn_tpu.serve.autoscale.Autoscaler` reads its replica
  signals through an aggregator instead of a private scrape loop — one
  scrape surface for decisions, dashboards, and alerts.

Deterministic and injectable like the rest of ``obs``: tests drive
:meth:`poll` by hand under fake clocks; production uses :meth:`start`'s
``Event.wait``-paced daemon thread.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from .exposition import parse_prometheus_text, scalar_values
from .rules import RuleEngine, rules_check
from .server import TelemetryServer
from .tsdb import TimeSeriesStore


class HttpScraper:
    """Scrape callable over real telemetry endpoints (the production
    transport, shared with the autoscaler): ``scraper =
    HttpScraper({"r0": url, ...})``. Fetches ``<url>/metrics`` exposition
    text with a hard timeout; a fetch failure returns ``None`` (the
    target scores as signal-less — liveness verdicts stay with their
    owners)."""

    def __init__(self, urls: Dict[str, str], *, timeout_s: float = 2.0):
        self.urls = dict(urls)
        self.timeout_s = timeout_s

    def healthz(self, name: str) -> Optional[Dict[str, Any]]:
        """The parsed ``/healthz`` JSON body (any status code — a 503
        carries the machine-readable degradation reasons), or ``None``
        when unreachable."""
        url = self.urls.get(name)
        if url is None:
            return None
        try:
            with urllib.request.urlopen(f"{url}/healthz",
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read().decode("utf-8"))
            except Exception:
                return None
        except Exception:
            return None

    def __call__(self, name: str, replica=None) -> Optional[str]:
        url = self.urls.get(name)
        if url is None:
            return None
        try:
            with urllib.request.urlopen(f"{url}/metrics",
                                        timeout=self.timeout_s) as r:
                return r.read().decode("utf-8")
        except Exception:
            return None


class FleetAggregator:
    """Scrape-merge-serve over N telemetry targets (module docstring).

    ``store`` defaults to a fresh :class:`TimeSeriesStore` on the same
    clock; ``rules`` (a :class:`~dcnn_tpu.obs.rules.RuleEngine` over that
    store) is evaluated after every poll, so fleet-level alert rules see
    each new merge immediately. The aggregator's own instruments land on
    ``registry`` (default: process-global)."""

    def __init__(self, *, store: Optional[TimeSeriesStore] = None,
                 registry=None, rules: Optional[RuleEngine] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tick_clock: Callable[[], float] = time.perf_counter,
                 timeout_s: float = 2.0):
        self._clock = clock
        self._tick = tick_clock
        self.timeout_s = timeout_s
        self.store = store if store is not None \
            else TimeSeriesStore(clock=clock)
        self.rules = rules
        if registry is None:
            from .registry import get_registry
            registry = get_registry()
        self._reg = registry
        self._lock = threading.Lock()
        self._targets: Dict[str, Dict[str, Any]] = {}  # dcnn: guarded_by=_lock
        self._last: Dict[str, Dict[str, Any]] = {}     # dcnn: guarded_by=_lock
        self._polls = 0                                # dcnn: guarded_by=_lock
        self._server: Optional[TelemetryServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._scrapes = registry.counter(
            "fleet_scrape_requests_total", "fleet target scrapes attempted")
        self._scrape_errors = registry.counter(
            "fleet_scrape_errors_total",
            "fleet target scrapes that failed to fetch or parse")
        self._scrape_hist = registry.histogram(
            "fleet_scrape_seconds", "wall per fleet target scrape")
        self._targets_gauge = registry.gauge(
            "fleet_targets", "targets registered with the aggregator")
        self._up_gauge = registry.gauge(
            "fleet_targets_up", "targets whose last scrape succeeded")
        self._polls_counter = registry.counter(
            "fleet_polls_total", "fleet poll passes completed")

    # -- targets -----------------------------------------------------------
    def add_target(self, name: str, *, url: Optional[str] = None,
                   server: Optional[TelemetryServer] = None,
                   scrape: Optional[Callable[[], Optional[str]]] = None,
                   healthz: Optional[Callable[[], Optional[Dict]]] = None
                   ) -> "FleetAggregator":
        """Register one scrape target: exactly one of ``url`` (HTTP),
        ``server`` (in-process fast path), or ``scrape`` (bare text
        callable; pair with ``healthz`` to join the health roll-up)."""
        if sum(x is not None for x in (url, server, scrape)) != 1:
            raise ValueError(
                f"target {name!r}: exactly one of url/server/scrape")
        spec = {"url": url, "server": server, "scrape": scrape,
                "healthz": healthz}
        with self._lock:
            if name in self._targets:
                raise ValueError(f"target {name!r} already registered")
            self._targets[name] = spec
            self._targets_gauge.set(len(self._targets))
        return self

    def remove_target(self, name: str) -> None:
        with self._lock:
            self._targets.pop(name, None)
            self._last.pop(name, None)
            self._targets_gauge.set(len(self._targets))

    def targets(self) -> List[str]:
        with self._lock:
            return sorted(self._targets)

    # -- scraping ----------------------------------------------------------
    def _fetch(self, name: str, spec: Dict[str, Any]) -> Optional[str]:
        if spec.get("url") is not None:
            return HttpScraper({name: spec["url"]},
                               timeout_s=self.timeout_s)(name)
        if spec.get("server") is not None:
            try:
                return spec["server"].metrics_body()
            except Exception:
                return None
        try:
            return spec["scrape"]()
        except Exception:
            return None

    def _fetch_healthz(self, spec: Dict[str, Any]
                       ) -> Optional[Dict[str, Any]]:
        if spec.get("url") is not None:
            return HttpScraper({"_": spec["url"]},
                               timeout_s=self.timeout_s).healthz("_")
        if spec.get("server") is not None:
            try:
                return spec["server"].health()[1]
            except Exception:
                return None
        if spec.get("healthz") is not None:
            try:
                return spec["healthz"]()
            except Exception:
                return None
        return None  # bare scrape targets opt out of the roll-up

    def _probe(self, name: str, spec: Dict[str, Any]):
        """One target's fetch pass (worker-thread body): metrics text +
        — only when the text arrived and the target is health-capable —
        its ``/healthz`` body. A dead target costs ONE timeout, not
        two."""
        t0 = self._tick()
        text = self._fetch(name, spec)
        dur = self._tick() - t0
        health = None
        if text is not None and (spec.get("url") is not None
                                 or spec.get("server") is not None
                                 or spec.get("healthz") is not None):
            health = self._fetch_healthz(spec)
        return text, dur, health

    def poll(self, targets: Optional[Dict[str, Callable[[], Optional[str]]]]
             = None) -> Dict[str, Dict[str, Any]]:
        """One scrape-and-merge pass. ``targets`` overrides the
        registered set for this pass with ``{name: scrape_callable}`` —
        the autoscaler's dynamic replica fleet — otherwise every
        registered target is scraped. Returns per-target results::

            {name: {"values": {metric: value} | None,   # parsed scalars
                    "fetched": bool,                     # text arrived
                    "parse_error": str | None,
                    "dur_s": float}}

        Every pass also writes the merged series (per-replica +
        sum/max), per-target up/latency history, and — when a rule
        engine is wired — evaluates the rules against the fresh merge.
        Fetches run OUTSIDE the aggregator lock and CONCURRENTLY across
        targets (one dead host costs the pass one timeout, not
        targets x timeout — rule hold windows stay on cadence); parsing
        and store writes stay on the calling thread."""
        if targets is not None:
            specs: Dict[str, Dict[str, Any]] = {
                n: {"scrape": fn} for n, fn in targets.items()}
        else:
            with self._lock:
                specs = dict(self._targets)
        now = self._clock()
        store = self.store  # thread-safe under its OWN lock (obs/tsdb.py)
        probes: Dict[str, Any] = {}
        if len(specs) > 1:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(
                    max_workers=min(8, len(specs)),
                    thread_name_prefix="dcnn-fleet-scrape") as pool:
                futs = {n: pool.submit(self._probe, n, spec)
                        for n, spec in specs.items()}
                probes = {n: f.result() for n, f in futs.items()}
        else:
            probes = {n: self._probe(n, spec)
                      for n, spec in specs.items()}
        results: Dict[str, Dict[str, Any]] = {}
        merged: Dict[str, Dict[str, float]] = {}
        healths: Dict[str, Optional[Dict[str, Any]]] = {}
        up = 0
        for name in specs:
            text, dur, healths[name] = probes[name]
            self._scrapes.inc()
            self._scrape_hist.observe(dur)
            res: Dict[str, Any] = {"values": None, "fetched": text is not
                                   None, "parse_error": None, "dur_s": dur}
            if text is None:
                self._scrape_errors.inc()
            else:
                try:
                    vals = scalar_values(parse_prometheus_text(text))
                except ValueError as e:
                    res["parse_error"] = str(e)
                    self._scrape_errors.inc()
                else:
                    res["values"] = vals
                    up += 1
                    for m, v in vals.items():
                        store.add(m, v, t=now, labels={"replica": name})
                        merged.setdefault(m, {})[name] = v
            store.add("fleet_target_up",
                      1.0 if res["values"] is not None else 0.0,
                      t=now, labels={"replica": name})
            results[name] = res
        for m, by_replica in merged.items():
            vals = list(by_replica.values())
            store.add(m, sum(vals), t=now, labels={"fleet": "sum"})
            store.add(m, max(vals), t=now, labels={"fleet": "max"})
        self._up_gauge.set(up)
        self._polls_counter.inc()
        with self._lock:
            self._polls += 1
            if targets is not None:
                # an explicit mapping IS the fleet for this pass: a
                # replica the autoscaler scaled away must age out of
                # /fleet and the health roll-up, not 503 them forever
                for stale in set(self._last) - set(specs):
                    self._last.pop(stale, None)
            for name, res in results.items():
                body = healths.get(name)
                self._last[name] = {
                    "t": now, "up": res["values"] is not None,
                    "dur_s": res["dur_s"],
                    "parse_error": res["parse_error"],
                    "values": res["values"],
                    # health cached AT POLL TIME so the roll-up check
                    # never blocks a /healthz probe on live fetches
                    "health_status": (body.get("status")
                                      if body is not None else None),
                    "health_reasons": (list(body.get("reasons") or [])
                                       if body is not None else []),
                }
        if self.rules is not None:
            self.rules.evaluate()
        return results

    # -- endpoint bodies ---------------------------------------------------
    def fleet_doc(self) -> Dict[str, Any]:
        """The ``/fleet`` body: per-target status + the merged labeled
        series' latest values (``sum`` / ``max`` / per-replica) + store
        shape — one request answers "what is the fleet doing"."""
        with self._lock:
            last = {n: dict(v) for n, v in self._last.items()}
            polls = self._polls
        series: Dict[str, Dict[str, Any]] = {}
        for name, info in last.items():
            for m, v in (info.get("values") or {}).items():
                row = series.setdefault(m, {"replicas": {}})
                row["replicas"][name] = v
        for m, row in series.items():
            vals = list(row["replicas"].values())
            row["sum"] = sum(vals)
            row["max"] = max(vals)
        return {
            "polls": polls,
            "targets": {n: {k: v for k, v in info.items()
                            if k != "values"}
                        for n, info in last.items()},
            "series": series,
            "slot_goodput": self._slot_goodput(last),
            "tsdb": self.store.summary(),
        }

    @staticmethod
    def _slot_goodput(last: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
        """Per-replica + fleet slot-occupancy goodput derived from the
        ``serve_slot_{occupied,idle,draining}_seconds_total`` counters
        each replica exposes (serve/metrics.py): occupied / total, the
        serving twin of the trainer's ``goodput_fraction``. Replicas not
        exposing the counters (trainers, old builds) are skipped."""
        per: Dict[str, Dict[str, Any]] = {}
        tot = {"occupied": 0.0, "idle": 0.0, "draining": 0.0}
        for name, info in last.items():
            vals = info.get("values") or {}
            secs = {s: vals.get(f"serve_slot_{s}_seconds_total")
                    for s in tot}
            if any(v is None for v in secs.values()):
                continue
            total = sum(secs.values())
            per[name] = {"seconds": secs,
                         "goodput": (secs["occupied"] / total)
                         if total > 0 else None}
            for s in tot:
                tot[s] += secs[s]
        fleet_total = sum(tot.values())
        return {"replicas": per,
                "fleet": {"seconds": tot,
                          "goodput": (tot["occupied"] / fleet_total)
                          if fleet_total > 0 else None}}

    def alerts_doc(self) -> Dict[str, Any]:
        """The ``/alerts`` body: every rule's state doc (firing first),
        or an explicit "no rules wired" shape."""
        if self.rules is None:
            return {"rules": 0, "alerts": []}
        docs = self.rules.alerts()
        return {"rules": len(docs), "alerts": docs,
                "firing": self.rules.firing()}

    def health_rollup(self) -> Optional[str]:
        """Fleet ``/healthz`` roll-up check: degraded when any target's
        last scrape failed, or any health-capable target reported itself
        unhealthy at the last poll (its own reasons quoted — one probe
        explains the whole fleet). Reads ONLY poll-time cached state, so
        a probe never blocks on live fetches to slow/dead targets.
        Healthy before the first poll: an empty aggregator is not a
        degraded one."""
        with self._lock:
            last = {n: dict(v) for n, v in self._last.items()}
        problems: List[str] = []
        for name in sorted(last):
            info = last[name]
            if not info["up"]:
                why = info.get("parse_error") or "scrape failed"
                problems.append(f"{name}: {why}")
            elif info.get("health_status") not in ("ok", None):
                reasons = ", ".join(info.get("health_reasons") or []) \
                    or "unhealthy"
                problems.append(f"{name}: {reasons}")
        if problems:
            return "; ".join(problems)
        return None

    # -- serving -----------------------------------------------------------
    def serve(self, *, host: str = "127.0.0.1", port: int = 0
              ) -> TelemetryServer:
        """Stand up THE fleet scrape surface: ``/fleet``, ``/alerts``,
        ``/metrics`` (aggregator registry + per-rule ``alert_state``
        lines), and ``/healthz`` carrying the fleet roll-up and firing
        alerts. Idempotent per aggregator; :meth:`close` stops it."""
        if self._server is not None:
            return self._server
        srv = TelemetryServer(registry=self._reg, host=host, port=port,
                              clock=self._clock)
        srv.set_identity(component="fleet")
        srv.add_route("/fleet", self.fleet_doc)
        srv.add_route("/alerts", self.alerts_doc)
        srv.add_check("fleet_targets", self.health_rollup)
        if self.rules is not None:
            srv.add_check("alerts", rules_check(self.rules))
            srv.metrics_text = self.rules.metrics_text(srv.metrics_text)
        srv.add_snapshot("tsdb", self.store.summary)
        self._server = srv.start()
        return srv

    @property
    def server(self) -> Optional[TelemetryServer]:
        return self._server

    # -- background polling ------------------------------------------------
    def start(self, interval_s: float = 2.0) -> "FleetAggregator":
        """Poll on a daemon thread every ``interval_s``; idempotent.
        Tests drive :meth:`poll` by hand instead."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval_s,), daemon=True,
            name="dcnn-fleet-aggregator")
        self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.poll()
            except Exception:
                pass  # a broken pass must not kill the cadence

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    def close(self) -> None:
        """Stop the poll thread and the fleet server (idempotent)."""
        self.stop()
        srv = self._server
        self._server = None
        if srv is not None:
            srv.stop()

    def __enter__(self) -> "FleetAggregator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        with self._lock:
            n, polls = len(self._targets), self._polls
        return f"FleetAggregator(targets={n}, polls={polls})"
