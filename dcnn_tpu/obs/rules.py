"""Declarative recording + alert rules over the in-process tsdb.

The Prometheus recording/alerting-rule model, scaled down to one
process: a :class:`RuleEngine` owns a list of rules evaluated against a
:class:`~dcnn_tpu.obs.tsdb.TimeSeriesStore` on every sampling pass
(``TsdbSampler.add_after_sample(engine.evaluate)``) or by hand in tests.

- **Recording rules** precompute a query (``rate`` / ``delta`` /
  ``avg_over_time`` / ``max_over_time`` / ``quantile_over_time`` /
  ``latest``) into a NEW tsdb series each evaluation — the derived
  series dashboards and other rules read (``router_rps`` from
  ``serve_samples_submitted_total``).
- **Alert rules** (:class:`AlertRule`) come in three kinds —
  ``threshold`` (a query result compared against a bound), ``rate``
  (per-second increase compared against a bound: "errors are climbing"),
  and ``absence`` (no new sample for ``window_s``: a half-dead scrape
  target or a stalled sampler) — each with a ``for_s`` **hold window**:
  the condition must stay true that long before the alert fires, so a
  one-tick spike stays ``pending`` and ages out instead of paging.

State machine per alert (the Prometheus vocabulary)::

    inactive -> pending   condition newly true (held < for_s)
    pending  -> firing    condition held for >= for_s   [EDGE: fired]
    pending  -> inactive  condition cleared before the hold elapsed
    firing   -> inactive  condition cleared              [EDGE: resolved]

Firing edges drive the existing degradation machinery:

- ``alerts_fired_total`` / ``alerts_resolved_total`` counters and
  ``alerts_firing`` / ``alerts_pending`` gauges on the wired registry,
  plus per-rule ``alert_state{rule="..."}`` series on the shared text
  exposition via :meth:`RuleEngine.prometheus_lines` (0 inactive,
  1 pending, 2 firing);
- a :class:`~dcnn_tpu.obs.flight.FlightRecorder` bundle per firing edge
  (trigger ``alert_firing``) carrying the rule, the observed value, and
  the offending series' recent window — the minutes *before* the page;
- :func:`rules_check` degrades a ``TelemetryServer``'s ``/healthz`` to
  503 while any alert is firing, with the rule named in ``reasons``.

Evaluation is injectable-clock and sleep-free like everything else in
``obs``; the engine never raises from :meth:`evaluate` hooks (a broken
rule is counted on ``alert_eval_errors_total`` and surfaced per rule).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from .exposition import escape_label_value
from .tsdb import TimeSeriesStore

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

#: query verbs a rule may apply to its series before comparing
_FNS = ("latest", "rate", "delta", "avg_over_time", "max_over_time",
        "min_over_time", "quantile_over_time")


def _query(store: TimeSeriesStore, series: str, fn: str, window_s: float,
           q: float) -> Optional[float]:
    if fn == "latest":
        pt = store.latest(series)
        return pt[1] if pt is not None else None
    if fn == "quantile_over_time":
        return store.quantile_over_time(series, q, window_s)
    return getattr(store, fn)(series, window_s)


@dataclass
class RecordingRule:
    """``name = fn(series[window_s])`` evaluated each pass into the
    store (``quantile_over_time`` reads ``q``; ``latest`` ignores the
    window)."""

    name: str
    series: str
    fn: str = "latest"
    window_s: float = 60.0
    q: float = 0.99

    def __post_init__(self):
        if self.fn not in _FNS:
            raise ValueError(f"recording rule {self.name}: fn must be one "
                             f"of {_FNS}, got {self.fn!r}")


@dataclass
class AlertRule:
    """One declarative alert (module docstring for the state machine).

    ``kind="threshold"``: ``fn(series[window_s]) op threshold``.
    ``kind="rate"``: ``rate(series[window_s]) op threshold``.
    ``kind="absence"``: no sample for ``series`` within ``window_s``
    (``threshold``/``op``/``fn`` unused — the condition is staleness).
    """

    name: str
    series: str
    kind: str = "threshold"
    op: str = ">"
    threshold: float = 0.0
    window_s: float = 60.0
    for_s: float = 0.0
    fn: str = "latest"
    q: float = 0.99
    severity: str = "page"
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("threshold", "rate", "absence"):
            raise ValueError(f"alert {self.name}: kind must be "
                             f"threshold|rate|absence, got {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"alert {self.name}: op must be one of "
                             f"{sorted(_OPS)}, got {self.op!r}")
        if self.fn not in _FNS:
            raise ValueError(f"alert {self.name}: fn must be one of "
                             f"{_FNS}, got {self.fn!r}")
        if self.for_s < 0 or self.window_s <= 0:
            raise ValueError(f"alert {self.name}: need for_s >= 0 and "
                             f"window_s > 0")


@dataclass
class _AlertState:
    rule: AlertRule
    state: str = "inactive"          # inactive | pending | firing
    pending_since: Optional[float] = None
    firing_since: Optional[float] = None
    value: Optional[float] = None
    last_error: Optional[str] = None
    fired_total: int = 0
    resolved_total: int = 0

    def doc(self) -> Dict[str, Any]:
        r = self.rule
        return {
            "name": r.name, "series": r.series, "kind": r.kind,
            "state": self.state, "value": self.value,
            "pending_since": self.pending_since,
            "firing_since": self.firing_since,
            "for_s": r.for_s, "window_s": r.window_s,
            "threshold": None if r.kind == "absence" else r.threshold,
            "op": None if r.kind == "absence" else r.op,
            "severity": r.severity, "description": r.description,
            "fired_total": self.fired_total,
            "resolved_total": self.resolved_total,
            "last_error": self.last_error,
        }


class RuleEngine:
    """Recording + alert rules over one store; see the module docstring.

    Wire rules before handing :meth:`evaluate` to a sampler; the engine
    lock makes wiring-after-start safe anyway. ``history_window_s``
    bounds the series window a firing bundle carries."""

    def __init__(self, store: TimeSeriesStore, *, registry=None,
                 flight=None, clock: Callable[[], float] = time.monotonic,
                 history_window_s: float = 120.0):
        self.store = store
        if registry is None:
            from .registry import get_registry
            registry = get_registry()
        self._reg = registry
        self._flight = flight  # None: the process-global recorder
        self._clock = clock
        self.history_window_s = history_window_s
        self._lock = threading.Lock()
        self._recording: List[RecordingRule] = []  # dcnn: guarded_by=_lock
        self._alerts: List[_AlertState] = []       # dcnn: guarded_by=_lock
        self._fired = registry.counter(
            "alerts_fired_total", "alert pending->firing transitions")
        self._resolved = registry.counter(
            "alerts_resolved_total", "alert firing->inactive transitions")
        self._eval_errors = registry.counter(
            "alert_eval_errors_total", "rule evaluations that raised")
        self._firing_gauge = registry.gauge(
            "alerts_firing", "alert rules currently firing")
        self._pending_gauge = registry.gauge(
            "alerts_pending", "alert rules currently pending")

    # -- wiring ------------------------------------------------------------
    def add_recording(self, rule: "RecordingRule | None" = None, **kw
                      ) -> "RuleEngine":
        rule = rule if rule is not None else RecordingRule(**kw)
        with self._lock:
            self._recording.append(rule)
        return self

    def add_alert(self, rule: "AlertRule | None" = None, **kw
                  ) -> "RuleEngine":
        rule = rule if rule is not None else AlertRule(**kw)
        with self._lock:
            if any(a.rule.name == rule.name for a in self._alerts):
                raise ValueError(f"alert {rule.name!r} already registered")
            self._alerts.append(_AlertState(rule))
        return self

    # -- evaluation --------------------------------------------------------
    def evaluate(self, _store=None) -> List[Dict[str, Any]]:
        """One pass over every rule; returns the TRANSITIONS this pass
        produced (``{"rule", "from", "to", "value"}`` dicts — what tests
        and the fleet ``/alerts`` change feed assert on). Never raises:
        a broken rule records its error and stays put. The ``_store``
        parameter is ignored (it lets the bound method BE the sampler's
        ``after_sample`` hook)."""
        now = self._clock()
        with self._lock:
            recording = list(self._recording)
            alerts = list(self._alerts)
        for rr in recording:
            try:
                v = _query(self.store, rr.series, rr.fn, rr.window_s, rr.q)
            except Exception:
                self._eval_errors.inc()
                continue
            if v is not None:
                self.store.add(rr.name, v, t=now)
        transitions: List[Dict[str, Any]] = []
        fire_bundles: List[Dict[str, Any]] = []
        for st in alerts:
            try:
                cond, value = self._condition(st.rule, now)
            except Exception as e:
                self._eval_errors.inc()
                with self._lock:
                    st.last_error = f"{type(e).__name__}: {e}"
                continue
            with self._lock:
                st.last_error = None
                st.value = value
                before = st.state
                if cond:
                    if st.state == "inactive":
                        st.state = "pending"
                        st.pending_since = now
                    if st.state == "pending" \
                            and now - st.pending_since >= st.rule.for_s:
                        st.state = "firing"
                        st.firing_since = now
                        st.fired_total += 1
                else:
                    if st.state == "firing":
                        st.resolved_total += 1
                    st.state = "inactive"
                    st.pending_since = None
                    st.firing_since = None
                after = st.state
            if after != before:
                transitions.append({"rule": st.rule.name, "from": before,
                                    "to": after, "value": value, "t": now})
                if after == "firing":
                    self._fired.inc()
                    fire_bundles.append(self._fire_payload(st, value, now))
                if before == "firing":
                    self._resolved.inc()
            # the per-rule state series rides the tsdb too, so history
            # shows WHEN an alert was pending/firing next to the data
            self.store.add("alert_state", self._state_num(after), t=now,
                           labels={"rule": st.rule.name})
        with self._lock:
            firing = sum(1 for a in self._alerts if a.state == "firing")
            pending = sum(1 for a in self._alerts if a.state == "pending")
        self._firing_gauge.set(firing)
        self._pending_gauge.set(pending)
        # flight dumps OUTSIDE the lock (file I/O must not serialize
        # handler threads reading alert state); record() never raises
        for payload in fire_bundles:
            from .flight import resolve_flight_recorder
            resolve_flight_recorder(self._flight).record(
                "alert_firing", registry=self._reg, **payload)
        return transitions

    def _condition(self, rule: AlertRule, now: float):
        if rule.kind == "absence":
            pt = self.store.latest(rule.series)
            age = None if pt is None else now - pt[0]
            absent = pt is None or age > rule.window_s
            return absent, age
        if rule.kind == "rate":
            v = self.store.rate(rule.series, rule.window_s)
        else:
            v = _query(self.store, rule.series, rule.fn, rule.window_s,
                       rule.q)
        if v is None:
            return False, None  # no data is NOT a threshold breach
        return _OPS[rule.op](v, rule.threshold), v

    @staticmethod
    def _state_num(state: str) -> int:
        return {"inactive": 0, "pending": 1, "firing": 2}[state]

    def _fire_payload(self, st: _AlertState, value, now: float
                      ) -> Dict[str, Any]:
        r = st.rule
        reason = (f"alert {r.name}: {r.kind} on {r.series} "
                  + (f"(no sample for > {r.window_s:g}s)"
                     if r.kind == "absence"
                     else f"({value} {r.op} {r.threshold:g})")
                  + f" held {r.for_s:g}s")
        return {
            "reasons": [reason],
            "config": {"rule": r.name, "series": r.series, "kind": r.kind,
                       "op": r.op, "threshold": r.threshold,
                       "window_s": r.window_s, "for_s": r.for_s,
                       "severity": r.severity,
                       "description": r.description},
            "extra": {"value": value, "t": now,
                      "window": self.store.range(
                          r.series, self.history_window_s)},
        }

    # -- export ------------------------------------------------------------
    def alerts(self) -> List[Dict[str, Any]]:
        """Every alert's current state doc, firing first — the
        ``/alerts`` endpoint body."""
        with self._lock:
            docs = [a.doc() for a in self._alerts]
        order = {"firing": 0, "pending": 1, "inactive": 2}
        docs.sort(key=lambda d: (order.get(d["state"], 3), d["name"]))
        return docs

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(a.rule.name for a in self._alerts
                          if a.state == "firing")

    def prometheus_lines(self) -> List[str]:
        """Per-rule ``alert_state{rule="..."}`` exposition lines
        (0 inactive / 1 pending / 2 firing) — append to a registry
        exposition via ``metrics_text`` composition."""
        with self._lock:
            states = [(a.rule.name, self._state_num(a.state))
                      for a in self._alerts]
        lines = ["# TYPE alert_state gauge"] if states else []
        for name, num in sorted(states):
            lines.append(
                f'alert_state{{rule="{escape_label_value(name)}"}} {num}')
        return lines

    def metrics_text(self, base: Callable[[], str]) -> Callable[[], str]:
        """Wrap a ``/metrics`` body provider so the per-rule
        ``alert_state`` series ride the same exposition."""
        def _text() -> str:
            body = base()
            lines = self.prometheus_lines()
            if not lines:
                return body
            return body.rstrip("\n") + "\n" + "\n".join(lines) + "\n"
        return _text


def rules_check(engine: RuleEngine) -> Callable[[], Optional[str]]:
    """Health check for a :class:`~dcnn_tpu.obs.server.TelemetryServer`:
    degraded while ANY alert rule is firing, naming every firing rule —
    the ``/healthz`` 503 an operator (or the fleet roll-up) reads."""
    def _check() -> Optional[str]:
        firing = engine.firing()
        if firing:
            return "alerts firing: " + ", ".join(firing)
        return None
    return _check


def goodput_alert_rules(*, window_s: float = 120.0, for_s: float = 180.0,
                        min_goodput: float = 0.25) -> List[AlertRule]:
    """The shipped goodput alert pack (docs/observability.md "Goodput &
    bottleneck attribution"). Series come from the
    :class:`~dcnn_tpu.obs.goodput.GoodputMonitor` poll (classifier 0/1
    state series) and the tsdb-sampled ``goodput_fraction`` gauge.
    ``for_s`` over the 0/1 ``min_over_time`` is exactly "feed-bound
    sustained > N windows" — a single-window blip never pages."""
    return [
        AlertRule(name="goodput_feed_bound_sustained",
                  series="goodput_bottleneck_feed_bound",
                  op=">=", threshold=1.0, fn="min_over_time",
                  window_s=window_s, for_s=for_s, severity="ticket",
                  description="classifier has held feed-bound for the "
                              "whole window — the host feed is the wall"),
        AlertRule(name="goodput_compile_bound_sustained",
                  series="goodput_bottleneck_compile_bound",
                  op=">=", threshold=1.0, fn="min_over_time",
                  window_s=window_s, for_s=for_s, severity="ticket",
                  description="sustained compile-bound windows — likely "
                              "a retrace storm (check TS06 / AOT cache)"),
        AlertRule(name="goodput_low_fraction",
                  series="goodput_fraction",
                  op="<", threshold=min_goodput, fn="avg_over_time",
                  window_s=window_s, for_s=for_s, severity="ticket",
                  description="average goodput below the floor — most "
                              "wall time is not compute"),
    ]


def gray_failure_alert_rules(*, window_s: float = 120.0,
                             for_s: float = 60.0,
                             max_imbalance: float = 2.0,
                             max_hedge_rate: float = 0.5) -> List[AlertRule]:
    """The shipped gray-failure (fail-slow) alert pack
    (docs/reliability.md §11). Series are the tsdb-sampled detector
    surfaces: conviction/hedge counters and the imbalance/probation
    gauges. Convictions page immediately (an eviction already happened —
    the hold is on the *band* alerts, which watch symptoms that may
    self-resolve)."""
    return [
        AlertRule(name="gray_straggler_convicted",
                  series="elastic_stragglers_evicted_total",
                  kind="rate", op=">", threshold=0.0,
                  window_s=window_s, for_s=0.0, severity="page",
                  description="the elastic leader convicted and evicted a "
                              "straggler — a host is fail-slow (flight "
                              "bundle trigger straggler_convicted has the "
                              "verdict)"),
        AlertRule(name="gray_stage_imbalance_sustained",
                  series="pipeline_stage_imbalance",
                  op=">", threshold=max_imbalance, fn="min_over_time",
                  window_s=window_s, for_s=for_s, severity="ticket",
                  description="max/median pipeline stage wall has held "
                              "above the band for the whole window — a "
                              "stage is dragging the pipeline (rebalance "
                              "should fire; if it did and imbalance "
                              "persists, the host itself is sick)"),
        AlertRule(name="gray_hedge_rate_high",
                  series="serve_router_hedges_total",
                  kind="rate", op=">", threshold=max_hedge_rate,
                  window_s=window_s, for_s=for_s, severity="ticket",
                  description="hedged requests per second above the band "
                              "— tail latency is chronically bad, not a "
                              "blip (check replica probation + p99)"),
        AlertRule(name="gray_replica_probation",
                  series="serve_router_probation_replicas",
                  op=">=", threshold=1.0, fn="min_over_time",
                  window_s=window_s, for_s=for_s, severity="ticket",
                  description="at least one serving replica has sat in "
                              "slow-replica probation for the whole "
                              "window — it is not recovering on its own"),
    ]
