"""Compiled-executable introspection: XLA cost analysis, compile-event
accounting, HBM watermarks.

The headline MFU has so far been computed from the model's own
``forward_complexity() × 3`` analytic formula — an estimate of what the
model *should* cost, not what the compiled program *does* cost. XLA knows
the truth: every compiled executable carries a cost analysis (FLOPs and
bytes accessed, post-fusion/post-layout) and the runtime exposes per-device
HBM occupancy. This module is the thin, version-tolerant shim between
those APIs and the obs registry:

- :func:`executable_cost` / :func:`jit_cost` — normalized
  ``{flops, bytes_accessed, bytes_per_flop}`` from
  ``lowered.compile().cost_analysis()`` (which returns a list-of-dicts on
  some jax versions, a dict on others, and nothing on some backends —
  callers always see one dict or ``None``, never a version branch).
  ``bytes_per_flop`` is the roofline coordinate: against a chip's
  ``HBM GB/s ÷ peak FLOP/s`` ridge it says whether an executable is
  compute- or bandwidth-bound.
- :func:`record_compile` — the ``compile_total`` /
  ``compile_seconds_total`` counters every compile site feeds (bench's
  headline step, the serve engine's per-bucket sessions), so the 149.9 s
  compile wall (ROADMAP item 4) is a scrapeable series, not a one-off
  bench field.
- :func:`sample_hbm` — HBM gauges from ``jax.Device.memory_stats()``
  (the ``utils/hardware.py`` path): ``hbm_bytes_in_use`` /
  ``hbm_bytes_limit`` summed over devices plus a monotone
  ``hbm_peak_bytes`` watermark. Cheap to call on epoch/dispatch
  boundaries; on backends without memory stats (CPU) the first failed
  probe latches and every later call is a no-op.

jax is imported lazily inside each function — the ``obs`` package stays
importable before backend selection, as its package docstring promises.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .registry import MetricsRegistry, get_registry

# tri-state memory_stats support latch: None = unprobed, True/False after
# the first attempt — keeps per-dispatch sampling free on CPU backends
_HBM_SUPPORTED: Optional[bool] = None


def executable_cost(compiled: Any) -> Optional[Dict[str, float]]:
    """Normalized cost analysis of a compiled executable (the object
    ``jitted.lower(...).compile()`` returns). ``None`` when the backend
    exposes no analysis — callers must treat cost as optional."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    # jax has returned list-of-dicts (one per partition), a bare dict, and
    # None across versions; take the first partition's properties
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    by = ca.get("bytes accessed")
    if flops is None and by is None:
        return None
    out: Dict[str, float] = {}
    if flops is not None and flops > 0:
        out["flops"] = float(flops)
    if by is not None and by > 0:
        out["bytes_accessed"] = float(by)
    if "flops" in out and "bytes_accessed" in out:
        out["bytes_per_flop"] = out["bytes_accessed"] / out["flops"]
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["temp_bytes"] = float(mem.temp_size_in_bytes)
            out["argument_bytes"] = float(mem.argument_size_in_bytes)
            out["output_bytes"] = float(mem.output_size_in_bytes)
    except Exception:
        pass  # memory analysis is a bonus, never a requirement
    return out or None


def jit_cost(jitted: Any, *args, **kwargs) -> Optional[Dict[str, float]]:
    """Cost analysis of ``jitted`` at the avals of ``args``/``kwargs``
    (concrete arrays or ``jax.ShapeDtypeStruct`` specs — lowering never
    executes). With the persistent compile cache on, the ``.compile()``
    here is served from cache when the caller already compiled these
    shapes; on any failure (backend without lowering introspection, aval
    mismatch) the answer is ``None``, not an exception — cost telemetry
    must never break the measurement it describes."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception:
        return None
    return executable_cost(compiled)


def record_compile(seconds: float, *, what: str = "",
                   registry: Optional[MetricsRegistry] = None) -> None:
    """Count one compile event: ``compile_total`` += 1,
    ``compile_seconds_total`` += ``seconds`` (and, when ``what`` is given,
    the per-site ``compile_<what>_seconds_total`` twin). The registry pair
    is the rate-able series the AOT-cache work (ROADMAP item 4) will be
    judged against."""
    reg = registry if registry is not None else get_registry()
    reg.counter("compile_total", "XLA executables compiled").inc()
    reg.counter("compile_seconds_total",
                "wall seconds spent compiling").inc(max(seconds, 0.0))
    if what:
        reg.counter(f"compile_{what}_seconds_total",
                    f"wall seconds compiling {what} executables").inc(
            max(seconds, 0.0))


def record_aot(event: str, seconds: float = 0.0, *,
               registry: Optional[MetricsRegistry] = None) -> None:
    """Account one AOT executable-cache event (``dcnn_tpu/aot``):
    ``hit`` (+ deserialize seconds), ``miss``, ``commit``,
    ``quarantined`` (corrupt entry set aside), ``stale`` (version
    mismatch skipped), ``fallback`` (backend can't serialize). The
    hit/miss ratio against :func:`record_compile`'s
    ``compile_seconds_total`` is THE judgment series for the compile-wall
    work (ROADMAP item 4)."""
    reg = registry if registry is not None else get_registry()
    names = {
        "hit": ("aot_hits_total", "AOT executable cache hits"),
        "miss": ("aot_misses_total", "AOT executable cache misses"),
        "commit": ("aot_commits_total", "AOT executables committed"),
        "quarantined": ("aot_quarantined_total",
                        "corrupt AOT entries quarantined"),
        "stale": ("aot_stale_total",
                  "stale-version AOT entries skipped"),
        "fallback": ("aot_fallback_total",
                     "AOT serialize/deserialize fallbacks to plain "
                     "compilation"),
    }
    name, help_ = names.get(event, (f"aot_{event}_total",
                                    f"AOT cache {event} events"))
    reg.counter(name, help_).inc()  # dcnn: metric=aot_*_total
    if event == "hit" and seconds > 0:
        reg.counter("aot_deserialize_seconds_total",
                    "wall seconds deserializing cached AOT "
                    "executables").inc(seconds)


def analytic_mfu(flops_per_sample: Optional[float],
                 samples_per_sec: Optional[float],
                 peak_tflops: Optional[float]) -> Optional[float]:
    """MFU from measured executable FLOPs: achieved FLOP/s over the chip
    peak. ``None`` whenever an input is unknown (no cost analysis, no
    known peak) — absent beats fabricated."""
    if not flops_per_sample or not samples_per_sec or not peak_tflops:
        return None
    return (flops_per_sample * samples_per_sec) / (peak_tflops * 1e12)


def sample_hbm(registry: Optional[MetricsRegistry] = None,
               devices=None) -> Optional[Dict[str, float]]:
    """Sample device memory into HBM gauges; returns the sample dict or
    ``None`` when the backend has no memory stats.

    - ``hbm_bytes_in_use`` / ``hbm_bytes_limit``: summed over devices
      (the fleet-level occupancy a scraper plots);
    - ``hbm_peak_bytes``: monotone high-water mark — the max per-device
      ``peak_bytes_in_use`` seen by ANY sample this process (falls back
      to tracking max ``bytes_in_use`` when the runtime reports no peak).
    """
    global _HBM_SUPPORTED
    if _HBM_SUPPORTED is False:
        return None
    try:
        import jax

        devs = devices if devices is not None else jax.devices()
        in_use = limit = 0.0
        peak = 0.0
        got = False
        for d in devs:
            stats = d.memory_stats()
            if not stats:
                continue
            got = True
            in_use += float(stats.get("bytes_in_use") or 0)
            limit += float(stats.get("bytes_limit") or 0)
            peak = max(peak, float(stats.get("peak_bytes_in_use")
                                   or stats.get("bytes_in_use") or 0))
        if not got:
            _HBM_SUPPORTED = False
            return None
    except Exception:
        _HBM_SUPPORTED = False
        return None
    _HBM_SUPPORTED = True
    reg = registry if registry is not None else get_registry()
    reg.gauge("hbm_bytes_in_use",
              "device memory in use, summed over devices").set(in_use)
    if limit:
        reg.gauge("hbm_bytes_limit",
                  "device memory capacity, summed over devices").set(limit)
    g = reg.gauge("hbm_peak_bytes",
                  "high-water per-device memory this process")
    if peak > g.value:
        g.set(peak)
    return {"hbm_bytes_in_use": in_use, "hbm_bytes_limit": limit or None,
            "hbm_peak_bytes": max(peak, g.value)}
