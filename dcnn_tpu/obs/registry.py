"""Metrics registry: Counter / Gauge / Histogram behind one export surface.

The repo grew three disconnected measurement dialects — ``ServeMetrics``'
hand-rolled counters, ``bench.py``'s ad-hoc stats dicts, and the
per-shard/per-chunk span dicts in ``data/transfer.py``. This module is the
one vocabulary they now share:

- **O(1) on the hot path.** ``Counter.inc`` / ``Gauge.set`` /
  ``Histogram.observe`` each take one lock and touch one slot; the only
  non-constant work (sorting names, formatting text) happens in
  ``snapshot()`` / ``prometheus()`` on the *reader's* thread — the same
  split ``ServeMetrics`` established (recorders O(1), export pays the
  sort).
- **Injectable clock everywhere** (the ``ServeMetrics`` rule generalized):
  anything time-derived is driven by a ``clock=`` callable so tests advance
  time by hand and tier-1 stays sleep-free.
- **Histogram buckets are fixed and log-spaced** — latencies and byte
  counts span orders of magnitude, so linear buckets would waste 90% of
  their resolution; log-spaced upper bounds (``start * factor**i``) give
  constant *relative* error at every scale, and a fixed layout keeps
  ``observe`` allocation-free.
- Two exports: ``snapshot()`` (plain dict — what bench.py embeds in its
  JSON) and ``prometheus()`` (text exposition format, the lingua franca of
  scrape-based monitoring — counters get ``# TYPE``/``# HELP`` headers,
  histograms emit cumulative ``_bucket{le=...}`` series plus ``_sum`` /
  ``_count``).

A process-global registry (``get_registry()``) is the default sink for the
framework's own instruments (train/feed/serve); private registries are for
isolation (``ServeMetrics`` keeps one per instance so its snapshot contract
stays bit-for-bit per instance — see serve/metrics.py).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Tuple


def _valid_name(name: str) -> str:
    """Prometheus metric names: ``[a-zA-Z_:][a-zA-Z0-9_:]*``. Dots (our
    span-style names) map to underscores; anything else invalid raises —
    a silently mangled name is a metric nobody finds again."""
    out = name.replace(".", "_")
    # isascii() too: str.isalnum is Unicode-aware, but the Prometheus
    # grammar is ASCII-only — 'µ' must raise here, not poison the scrape
    ok = (bool(out) and out.isascii() and not out[0].isdigit()
          and all(c.isalnum() or c in "_:" for c in out))
    if not ok:
        raise ValueError(f"invalid metric name {name!r}")
    return out


class Counter:
    """Monotone cumulative count. ``inc`` is O(1) and thread-safe."""

    __slots__ = ("name", "help", "_lock", "_v")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._v = 0

    def inc(self, n: "int | float" = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._v += n

    @property
    def value(self):
        with self._lock:
            return self._v

    def reset(self) -> None:
        with self._lock:
            self._v = 0


class Gauge:
    """Last-written value (queue depth, lr, inflight peak)."""

    __slots__ = ("name", "help", "_lock", "_v")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def add(self, n: float) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self):
        with self._lock:
            return self._v

    def reset(self) -> None:
        with self._lock:
            self._v = 0.0


class Histogram:
    """Fixed log-spaced-bucket histogram.

    Upper bounds are ``start * factor**i`` for ``i in range(buckets)`` plus
    an implicit +Inf overflow bucket. The default layout (1 µs → ~18 min at
    x2) covers every duration this framework measures; byte-sized
    histograms pass their own ``start``/``factor``. ``observe`` is O(log B)
    over B≈31 fixed bounds (one ``bisect`` on a prebuilt list — no
    allocation, no resize, safely "O(1)" for hot-path purposes).
    """

    __slots__ = ("name", "help", "bounds", "_lock", "_counts", "_sum",
                 "_count", "_min", "_max")

    def __init__(self, name: str, help: str = "", *, start: float = 1e-6,
                 factor: float = 2.0, buckets: int = 31):
        if start <= 0 or factor <= 1 or buckets < 1:
            raise ValueError(
                f"histogram {name}: need start > 0, factor > 1, buckets >= 1"
                f" (got {start}, {factor}, {buckets})")
        self.name = name
        self.help = help
        self.bounds: List[float] = [start * factor ** i for i in range(buckets)]
        self._lock = threading.Lock()
        self._counts = [0] * (buckets + 1)  # +1: the +Inf overflow bucket
        self._sum = 0.0
        self._count = 0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def value(self) -> Dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "mean": (self._sum / self._count) if self._count else None,
                "buckets": {b: c for b, c in zip(self.bounds, self._counts)
                            if c},
                "overflow": self._counts[-1],
            }

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._sum = 0.0
            self._count = 0
            self._min = self._max = None

    def cumulative(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs ending with (inf, count) —
        the Prometheus ``_bucket{le=...}`` series."""
        with self._lock:
            out, acc = [], 0
            for b, c in zip(self.bounds, self._counts):
                acc += c
                out.append((b, acc))
            out.append((float("inf"), acc + self._counts[-1]))
            return out


class MetricsRegistry:
    """Thread-safe get-or-create instrument store.

    ``counter(name)`` twice returns the SAME object (the point of a
    registry: two modules incrementing ``h2d_bytes_total`` share one
    stream); asking for an existing name as a different kind raises.
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}
        self._t0 = clock()

    def _get_or_create(self, cls, name: str, help: str, **kw):
        name = _valid_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, **kw)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "", *, start: float = 1e-6,
                  factor: float = 2.0, buckets: int = 31) -> Histogram:
        return self._get_or_create(Histogram, name, help, start=start,
                                   factor=factor, buckets=buckets)

    def instruments(self) -> List[Tuple[str, object]]:
        """Sorted ``(name, instrument)`` pairs — the typed view the tsdb
        sampler reads (histograms keep their ``cumulative()`` buckets,
        which ``snapshot()`` flattens away)."""
        with self._lock:
            return sorted(self._instruments.items())

    # -- export ------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """Point-in-time ``{name: value}`` dict (histograms expand to their
        stats dict). Sorted for stable JSON diffs."""
        with self._lock:
            items = sorted(self._instruments.items())
        out: Dict[str, object] = {}
        for name, inst in items:
            out[name] = inst.value
        out["_wall_s"] = max(self._clock() - self._t0, 0.0)
        return out

    def prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4). Rendering lives in
        :mod:`~dcnn_tpu.obs.exposition` — shared with
        ``ServeMetrics.prometheus`` so escape/format rules can't drift."""
        from .exposition import render_instruments

        with self._lock:
            items = sorted(self._instruments.items())
        return "\n".join(render_instruments(items)) + "\n"

    def reset(self) -> None:
        """Zero every instrument and restart the wall clock (tests; a fresh
        bench section). Instrument identities are preserved — holders of a
        Counter keep a valid object."""
        with self._lock:
            insts = list(self._instruments.values())
            self._t0 = self._clock()
        for inst in insts:
            inst.reset()


_GLOBAL_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry — the default sink for the framework's
    own train/feed/pipeline/serve instruments."""
    return _GLOBAL_REGISTRY
