"""Step guards: non-finite loss/grad defense and a stall watchdog.

One NaN step can poison an entire run — Adam's second-moment EMA never
recovers from an Inf, and every later checkpoint inherits the damage. The
defense is split the way jit demands:

- **In-graph detection + neutralization** lives in
  ``train.make_train_step(guard=True)``: the step computes
  ``bad = ~isfinite(loss) | ~isfinite(grad_global_norm²)`` and selects
  (``jnp.where``) between the updated and the *incoming*
  params/state/opt_state/step — a skipped step is bit-identical to not
  having run it, with no host round-trip inside the graph.
- **Host-side policy** lives here, in :class:`StepGuard`: the Trainer
  feeds it each step's ``bad`` flag (read with the loss it already pulls
  to host) and the guard decides what the flag *means*:

  - ``"raise"`` — abort with :class:`NonFiniteError` naming the step;
  - ``"skip_step"`` — count it (``train_skipped_steps_total`` on the obs
    registry) and keep going: params/opt_state were never touched;
  - ``"rollback"`` — like skip, until ``rollback_after`` *consecutive*
    bad steps, then tell the Trainer to restore the last checkpoint
    (return value ``"rollback"``) — the Check-N-Run answer to a run whose
    state is already subtly poisoned rather than one transient bad batch.

The :class:`StallWatchdog` covers the other failure shape: a step or data
fetch that never returns (hung remote TPU tunnel, wedged producer thread).
Progress sites call :meth:`~StallWatchdog.beat`; a poll (background thread
in production, direct :meth:`~StallWatchdog.check` with a fake clock in
tests) flags ``train_stalled`` / ``train_stall_flags_total`` on the obs
registry once no beat arrives within ``timeout_s`` — detection only, by
design: killing a hung dispatch is the scheduler's job, surfacing it is
ours.
"""

from __future__ import annotations

import threading
import math
import time
import warnings
from typing import Callable, Optional

from ..obs import get_registry


class NonFiniteError(FloatingPointError):
    """Training produced a non-finite loss or gradient norm."""

    def __init__(self, step: int, loss: float):
        self.step = step
        self.loss = loss
        super().__init__(
            f"non-finite loss/gradient at train step {step} (loss={loss!r}); "
            f"policy 'raise' aborts — use nonfinite_policy='skip_step' or "
            f"'rollback' to continue past transient bad batches")


def global_norm_sq(tree):
    """Σ‖leaf‖² over a pytree — the jit-friendly non-finiteness probe (the
    square root is irrelevant for an isfinite check and costs a kernel)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros(())
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


class StepGuard:
    """Host-side policy for the in-graph ``bad`` flag. Returns one of
    ``"ok" | "skipped" | "rollback"`` per step; raises for policy
    ``"raise"``."""

    POLICIES = ("raise", "skip_step", "rollback")

    def __init__(self, policy: str = "raise", *, rollback_after: int = 3,
                 registry=None, flight=None):
        if policy not in self.POLICIES:
            raise ValueError(f"nonfinite_policy must be one of "
                             f"{self.POLICIES}, got {policy!r}")
        if rollback_after < 1:
            raise ValueError(f"rollback_after must be >= 1, "
                             f"got {rollback_after}")
        self.policy = policy
        self.rollback_after = rollback_after
        self._reg = registry if registry is not None else get_registry()
        self._flight = flight  # None: process-global recorder
        self.consecutive_bad = 0
        self.total_skipped = 0

    def _flight_recorder(self):
        from ..obs.flight import resolve_flight_recorder
        return resolve_flight_recorder(self._flight)

    def observe(self, step: int, bad: bool,
                loss: float = math.nan) -> str:
        if not bad:
            self.consecutive_bad = 0
            return "ok"
        if self.policy == "raise":
            # postmortem before the abort: the step that poisoned the run
            # plus the spans/metrics leading into it (no-op when the
            # flight recorder is disabled; never raises on its own)
            self._flight_recorder().record(
                "nonfinite_guard",
                reasons=[f"non-finite loss/grad at step {step} "
                         f"(loss={loss!r}); policy 'raise' aborts"],
                registry=self._reg,
                extra={"step": step, "loss": repr(loss),
                       "policy": self.policy})
            raise NonFiniteError(step, loss)
        if self.consecutive_bad == 0:
            # degradation EDGE (start of a bad-step streak): one bundle
            # per episode — the per-trigger cooldown bounds a run whose
            # data keeps re-tripping it
            self._flight_recorder().record(
                "nonfinite_guard",
                reasons=[f"non-finite loss/grad at step {step}: "
                         f"policy {self.policy!r}"],
                registry=self._reg,
                extra={"step": step, "loss": repr(loss),
                       "policy": self.policy,
                       "rollback_after": self.rollback_after})
        self.consecutive_bad += 1
        self.total_skipped += 1
        self._reg.counter("train_skipped_steps_total",
                          "train steps skipped by the non-finite guard").inc()
        warnings.warn(
            f"non-finite loss/grad at step {step}: step skipped "
            f"({self.consecutive_bad} consecutive)", stacklevel=2)
        if (self.policy == "rollback"
                and self.consecutive_bad >= self.rollback_after):
            self.consecutive_bad = 0
            self._reg.counter(
                "train_rollbacks_total",
                "rollbacks to last checkpoint by the guard").inc()
            return "rollback"
        return "skipped"


class StallWatchdog:
    """Flags (never kills) a training loop that stopped making progress.

    ``beat()`` on every progress event; ``check()`` returns True and
    records on the registry iff the last beat is older than ``timeout_s``.
    ``start()`` polls ``check`` on a daemon thread for production runs;
    tests drive ``check()`` directly with an injected clock and never
    sleep. Repeated checks during one stall flag once (edge-triggered) —
    a new flag needs a beat in between.
    """

    def __init__(self, timeout_s: float, *,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None, name: str = "train", flight=None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self._clock = clock
        self._reg = registry if registry is not None else get_registry()
        self._name = name
        self._flight = flight  # None: process-global recorder
        # beat() runs on the training thread, check() on the poll thread:
        # the beat/flag pair must change together or a beat landing between
        # check()'s read and its flag write un-stalls a loop the poll
        # thread is about to (wrongly) flag
        self._lock = threading.Lock()
        self._last_beat = clock()  # dcnn: guarded_by=_lock
        self._flagged = False  # dcnn: guarded_by=_lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        with self._lock:
            self._last_beat = self._clock()
            was_flagged, self._flagged = self._flagged, False
        if was_flagged:
            self._reg.gauge(f"{self._name}_stalled",
                            "1 while the loop is flagged stalled").set(0)

    def check(self) -> bool:
        with self._lock:
            age = self._clock() - self._last_beat
            stalled = age > self.timeout_s
            newly = stalled and not self._flagged
            if newly:
                self._flagged = True
        self._reg.gauge(
            f"{self._name}_last_progress_age_s",
            "seconds since the loop last made progress").set(age)
        if not stalled:
            return False
        if newly:
            self._reg.counter(f"{self._name}_stall_flags_total",
                              "distinct stalls flagged").inc()
            self._reg.gauge(f"{self._name}_stalled",
                            "1 while the loop is flagged stalled").set(1)
            warnings.warn(
                f"{self._name} loop stalled: no progress for {age:.1f}s "
                f"(timeout {self.timeout_s:.1f}s)", stacklevel=2)
            # edge-triggered postmortem (the flag is edge-triggered too):
            # the spans leading into the stall say WHAT stopped beating
            from ..obs.flight import resolve_flight_recorder
            resolve_flight_recorder(self._flight).record(
                "watchdog_stall",
                reasons=[f"{self._name} loop: no progress for {age:.1f}s "
                         f"(timeout {self.timeout_s:g}s)"],
                registry=self._reg,
                extra={"watchdog": self._name, "age_s": age,
                       "timeout_s": self.timeout_s})
        return True

    def start(self, poll_s: Optional[float] = None) -> "StallWatchdog":
        if self._thread is not None:
            return self
        interval = poll_s if poll_s is not None else max(
            self.timeout_s / 4.0, 0.05)

        def loop():
            while not self._stop.wait(interval):
                self.check()

        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"dcnn-{self._name}-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._stop = threading.Event()
