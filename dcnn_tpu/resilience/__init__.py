"""Fault tolerance: atomic/async checkpointing, step guards, retry, faults.

The ROADMAP's north star is production-scale training and serving on
*preemptible* fleets — machines that vanish mid-write, lose packets, and
occasionally hand back an Inf. This package makes failure a first-class,
*tested* event across the stack:

- :mod:`~dcnn_tpu.resilience.checkpoint` — :class:`CheckpointManager`:
  atomic commits (staged dir + manifest with per-file SHA-256 +
  ``os.replace``), background async saves that never block the step loop
  on disk, keep-last-K retention, and :func:`restore_latest` that skips
  torn/corrupt checkpoints to the newest valid one.
- :mod:`~dcnn_tpu.resilience.guards` — :class:`StepGuard` policies over
  the jit-level non-finite detector in ``train.make_train_step(guard=
  True)`` (``raise`` / ``skip_step`` / ``rollback``), plus
  :class:`StallWatchdog` for hung steps/fetches.
- :mod:`~dcnn_tpu.resilience.retry` — the one bounded-exponential-backoff
  primitive (``retry_call`` / ``@retriable``), reused by pipeline worker
  connects, dataset downloads, and checkpoint I/O; retries are counted on
  the obs registry.
- :mod:`~dcnn_tpu.resilience.faults` — deterministic seeded fault
  injection (:class:`FaultPlan`): crash-before/after-rename, bit flips,
  producer raises, forced non-finite steps, dropped sends. Every recovery
  claim above is proven under it in ``tests/test_resilience.py``.

Trainer integration: ``TrainingConfig(checkpoint_dir=..., checkpoint_every
=N, resume="auto", nonfinite_policy="skip_step", stall_timeout_s=120)``.
Recovery semantics and the fault-injection cookbook: docs/reliability.md.

Submodule imports are lazy: ``train/checkpoint.py`` uses
:mod:`~dcnn_tpu.resilience.atomic` while :mod:`~dcnn_tpu.resilience.checkpoint`
imports ``train/checkpoint.py`` — laziness keeps that cycle-free, and
``import dcnn_tpu.resilience`` stays jax-free.
"""

from __future__ import annotations

_EXPORTS = {
    "CheckpointManager": ("checkpoint", "CheckpointManager"),
    "RestoredCheckpoint": ("checkpoint", "RestoredCheckpoint"),
    "restore_latest": ("checkpoint", "restore_latest"),
    "list_steps": ("checkpoint", "list_steps"),
    "StepGuard": ("guards", "StepGuard"),
    "StallWatchdog": ("guards", "StallWatchdog"),
    "NonFiniteError": ("guards", "NonFiniteError"),
    "retry_call": ("retry", "retry_call"),
    "retriable": ("retry", "retriable"),
    "backoff_delays": ("retry", "backoff_delays"),
    "FaultPlan": ("faults", "FaultPlan"),
    "InjectedFault": ("faults", "InjectedFault"),
    "InjectedCrash": ("faults", "InjectedCrash"),
    "SlownessConfig": ("slowness", "SlownessConfig"),
    "SlownessDetector": ("slowness", "SlownessDetector"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(f".{mod_name}", __name__), attr)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
