"""Checkpoint v2: atomic commits, async saves, checksum-verified restore.

``train/checkpoint.py`` defines the *format* (``model.json`` +
``arrays.msgpack``, flax serialization) and keeps its simple
save/load-one-directory API. This module adds the *durability and
lifecycle* layer the ROADMAP's preemptible-fleet north star needs —
the Orbax/Check-N-Run recipe, natively:

- **Atomic commit.** A save stages everything under ``tmp-<uuid>/`` inside
  the checkpoint root, fsyncs, writes a ``MANIFEST.json`` (per-file
  SHA-256 + byte sizes + step/metadata) *last*, then publishes with one
  ``os.replace(tmp, ckpt-<step>)``. A preemption at ANY instant leaves
  either no ``ckpt-<step>`` (previous checkpoint intact) or a complete,
  checksum-valid one — never a torn directory that a later run half-loads.
- **Async save.** :meth:`CheckpointManager.save_async` snapshots device
  arrays on the calling (training) thread — ``jax.device_get`` only — and
  hands serialization + hashing + disk I/O to a dedicated saver thread.
  The step loop's save cost is the D2H copy, independent of filesystem
  speed (asserted in tests with a gated fake writer).
- **Retention.** ``keep=K`` newest committed checkpoints survive; older
  ones are GC'd after each successful commit (never before — the new
  checkpoint must be durable before any old one dies).
- **Verified restore.** :func:`restore_latest` scans ``ckpt-*`` newest
  first, verifies every file against the manifest, and transparently skips
  torn/corrupt/bit-flipped candidates to the newest valid one — recording
  each skip on the obs registry (``ckpt_restore_skipped_total``).

Fault-injection trip points (``resilience/faults.py``): ``ckpt.write``
(mid-stage, files partial), ``ckpt.before_rename`` (staged but not
committed), ``ckpt.after_rename`` (committed, GC not yet run). The
recovery claims above are each proven under these in
``tests/test_resilience.py``.
"""

from __future__ import annotations

import json
import os
import queue
import re
import shutil
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, NamedTuple, Optional

from ..obs import get_registry, get_tracer
from . import faults
from .atomic import commit_dir, sha256_file, stage_dir, sweep_stale_tmp

_MANIFEST = "MANIFEST.json"
_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def _ckpt_name(step: int) -> str:
    return f"ckpt-{step:08d}"


def _default_write(path: str, data: bytes) -> None:
    # plain write inside a tmp-<uuid> staging dir — commit_dir (the
    # caller's publish point) fsyncs and os.replace's the whole directory,
    # so per-file atomicity here would be redundant work
    with open(path, "wb") as f:  # dcnn: disable=AT01
        f.write(data)


class RestoredCheckpoint(NamedTuple):
    model: Any
    params: Any
    state: Any
    opt_state: Any
    optimizer: Any
    metadata: Dict[str, Any]
    step: int
    path: str


def verify_dir(path: str) -> bool:
    """True iff ``path`` holds a complete checkpoint whose files match its
    manifest's SHA-256 sums. Cheap checks (existence, size) run first.
    Public: the serving tier (``serve/swap.py``) uses it to pick the
    newest *valid* version without loading anything."""
    mpath = os.path.join(path, _MANIFEST)
    try:
        with open(mpath, "r", encoding="utf-8") as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError):
        return False
    for name, info in files.items():
        fpath = os.path.join(path, name)
        try:
            if os.path.getsize(fpath) != info["bytes"]:
                return False
            if sha256_file(fpath) != info["sha256"]:
                return False
        except (OSError, KeyError):
            return False
    return True


def list_steps(directory: str) -> Dict[int, str]:
    """Committed checkpoint steps under ``directory`` → absolute path.
    Presence only; validity is :func:`verify_dir`'s job."""
    out: Dict[int, str] = {}
    if not os.path.isdir(directory):
        return out
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m and os.path.isdir(os.path.join(directory, name)):
            out[int(m.group(1))] = os.path.join(directory, name)
    return out


def restore_latest(directory: str, seed: int = 0,
                   registry=None) -> Optional[RestoredCheckpoint]:
    """Load the newest checksum-valid checkpoint under ``directory``,
    skipping torn/corrupt candidates (each skip increments
    ``ckpt_restore_skipped_total`` and warns). Returns ``None`` when no
    valid checkpoint exists — callers decide whether that means "cold
    start" (``resume='auto'``) or an error."""
    from ..train.checkpoint import load_checkpoint

    import uuid
    import warnings

    reg = registry if registry is not None else get_registry()
    tracer = get_tracer()
    steps = sorted(list_steps(directory).items(), reverse=True)
    for step, path in steps:
        with tracer.span("checkpoint.restore", track="ckpt", step=step):
            if not verify_dir(path):
                # quarantine, don't just skip: a resumed run will want to
                # commit this step number again, and an immutable corrupt
                # dir squatting on it would turn recovery into
                # FileExistsError. The bytes survive (renamed) for
                # forensics; corrupt-* never matches list_steps.
                quarantine = os.path.join(
                    directory,
                    f"corrupt-{os.path.basename(path)}-{uuid.uuid4().hex}")
                try:
                    os.replace(path, quarantine)
                    where = f"quarantined as {quarantine}"
                except OSError:
                    where = "left in place (rename failed)"
                warnings.warn(
                    f"skipping torn/corrupt checkpoint {path} "
                    f"(manifest/checksum mismatch); {where}", stacklevel=2)
                reg.counter("ckpt_restore_skipped_total",
                            "corrupt checkpoints skipped on restore").inc()
                continue
            model, params, state, opt_state, optimizer, metadata = \
                load_checkpoint(path, seed=seed)
            reg.counter("ckpt_restores_total",
                        "successful checkpoint restores").inc()
            return RestoredCheckpoint(model, params, state, opt_state,
                                      optimizer, metadata, step, path)
    return None


class CheckpointManager:
    """Owns one checkpoint root directory: atomic saves (sync or async),
    keep-last-K retention, verified restore.

    ``io_write(path, data)`` is injectable so tests can model a slow or
    crashing filesystem without touching real disk timing; ``clock`` feeds
    the save-duration histogram.
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 io_write: Callable[[str, bytes], None] = _default_write,
                 clock: Callable[[], float] = time.perf_counter,
                 registry=None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.keep = keep
        self._io_write = io_write
        self._clock = clock
        self._reg = registry if registry is not None else get_registry()
        os.makedirs(directory, exist_ok=True)
        # stale tmp-* dirs are a previous (preempted) process's unfinished
        # saves; corrupt-* dirs are checksum-failed quarantines from prior
        # restores — committed ckpt-* dirs are never touched here
        sweep_stale_tmp(directory, prefixes=("tmp-", "corrupt-"))
        self._q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._pending: list = []  # async-save futures not yet inspected
        self._last_failure: Optional[BaseException] = None  # health() latch

    # -- serialization (format owned by train/checkpoint.py) --
    @staticmethod
    def _snapshot(model, params, state, opt_state, optimizer,
                  metadata) -> tuple:
        """Everything save needs, device arrays pulled to host — the ONLY
        work that must happen on the training thread. Serialization and
        disk I/O happen wherever the save runs."""
        import jax

        tree = {"params": params, "state": state}
        if opt_state is not None:
            tree["opt_state"] = opt_state
        host_tree = jax.tree_util.tree_map(lambda a: jax.device_get(a), tree)
        manifest = {
            "model": model.get_config(),
            "optimizer": optimizer.get_config() if optimizer is not None
            else None,
            # json round-trip = deep freeze: the caller may keep mutating
            # the object it passed (the Trainer appends to its history list
            # every epoch) while the saver thread is still serializing —
            # the snapshot must capture THIS instant, bit-exact
            "metadata": json.loads(json.dumps(metadata or {})),
            "has_opt_state": opt_state is not None,
        }
        return manifest, host_tree

    def _write_and_commit(self, step: int, model_manifest: dict,
                          host_tree: dict) -> str:
        from flax import serialization

        t0 = self._clock()
        final = os.path.join(self.directory, _ckpt_name(step))
        if os.path.exists(final):
            raise FileExistsError(
                f"checkpoint for step {step} already exists at {final}; "
                f"committed checkpoints are immutable")
        tmp = stage_dir(self.directory)
        try:
            model_bytes = json.dumps(model_manifest, indent=2).encode("utf-8")
            self._io_write(os.path.join(tmp, "model.json"), model_bytes)
            faults.trip("ckpt.write", step=step)
            array_bytes = serialization.to_bytes(host_tree)
            self._io_write(os.path.join(tmp, "arrays.msgpack"), array_bytes)
            manifest = {
                "format": 1,
                "step": step,
                "metadata": model_manifest.get("metadata", {}),
                "files": {
                    "model.json": {
                        "sha256": sha256_file(os.path.join(tmp, "model.json")),
                        "bytes": len(model_bytes)},
                    "arrays.msgpack": {
                        "sha256": sha256_file(
                            os.path.join(tmp, "arrays.msgpack")),
                        "bytes": len(array_bytes)},
                },
            }
            self._io_write(os.path.join(tmp, _MANIFEST),
                           json.dumps(manifest, indent=2).encode("utf-8"))
            faults.trip("ckpt.before_rename", step=step)
            commit_dir(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        faults.trip("ckpt.after_rename", step=step)
        self._reg.counter("ckpt_saves_total", "committed checkpoints").inc()
        self._reg.gauge("ckpt_last_step", "last committed step").set(step)
        self._reg.histogram("ckpt_save_seconds",
                            "serialize+write+commit wall").observe(
            max(self._clock() - t0, 0.0))
        self._gc()
        return final

    def _gc(self) -> None:
        steps = sorted(list_steps(self.directory).items(), reverse=True)
        for step, path in steps[self.keep:]:
            shutil.rmtree(path, ignore_errors=True)
            self._reg.counter("ckpt_gc_removed_total",
                              "checkpoints removed by retention").inc()

    # -- sync save --
    def save(self, step: int, model, params, state, opt_state=None,
             optimizer=None, metadata: Optional[Dict[str, Any]] = None,
             ) -> str:
        """Atomic synchronous save; returns the committed directory."""
        with get_tracer().span("checkpoint.save", track="ckpt", step=step,
                               mode="sync"):
            manifest, host_tree = self._snapshot(
                model, params, state, opt_state, optimizer, metadata)
            with self._lock:
                return self._write_and_commit(step, manifest, host_tree)

    # -- async save --
    def _saver_loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            step, manifest, host_tree, fut = job
            if not fut.set_running_or_notify_cancel():
                continue
            if step is None:  # wait() barrier marker: everything before it ran
                fut.set_result(None)
                continue
            try:
                with get_tracer().span("checkpoint.save", track="ckpt",
                                       step=step, mode="async"):
                    with self._lock:
                        path = self._write_and_commit(step, manifest,
                                                      host_tree)
                fut.set_result(path)
            except BaseException as e:  # surfaced via the future / wait()
                fut.set_exception(e)

    def save_async(self, step: int, model, params, state, opt_state=None,
                   optimizer=None,
                   metadata: Optional[Dict[str, Any]] = None) -> Future:
        """Non-blocking save: device_get runs here (the training thread's
        only cost); serialize/hash/write/commit run on the saver thread.
        Returns a Future resolving to the committed path."""
        with get_tracer().span("checkpoint.snapshot", track="ckpt",
                               step=step):
            manifest, host_tree = self._snapshot(
                model, params, state, opt_state, optimizer, metadata)
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._saver_loop, daemon=True, name="dcnn-ckpt-saver")
            self._thread.start()
        fut: Future = Future()
        self._pending.append(fut)
        self._q.put((step, manifest, host_tree, fut))
        return fut

    def check(self) -> None:
        """Non-blocking failure probe: re-raises the first *completed*
        async save's exception, dropping inspected futures. Call once per
        save cadence (the Trainer does, each checkpoint epoch) so a run
        that believes it is preemption-safe learns its saves are failing
        at the SECOND checkpoint, not after the last epoch."""
        still_pending = []
        first_exc = None
        for f in self._pending:
            if not f.done():
                still_pending.append(f)
                continue
            exc = f.exception()
            if exc is not None and first_exc is None:
                first_exc = exc
        self._pending = still_pending
        if first_exc is not None:
            self._last_failure = first_exc
            raise first_exc

    def health(self) -> Optional[BaseException]:
        """NON-consuming failure probe for health endpoints: the first
        known save failure (latched — once a save has failed this manager
        reports unhealthy until the process decides otherwise), or
        ``None``. Unlike :meth:`check` it never drops pending futures and
        never raises, so a ``/healthz`` scrape can poll it at any cadence
        WITHOUT disarming the trainer's own per-cadence ``check()``
        fail-fast (obs/server.py ``checkpoint_check`` uses this)."""
        if self._last_failure is None:
            for f in self._pending:
                if f.done():
                    exc = f.exception()
                    if exc is not None:
                        self._last_failure = exc
                        break
        return self._last_failure

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every queued async save has committed. Re-raises the
        first failed save's exception. Call before process exit (and the
        Trainer does, at the end of ``fit``) — an abandoned queue is a
        silently missing checkpoint."""
        if self._thread is None or not self._thread.is_alive():
            return
        # a barrier marker rides the same queue: once its future resolves,
        # every job enqueued before it has fully run (single saver thread,
        # FIFO queue)
        fut: Future = Future()
        self._q.put((None, None, None, fut))
        fut.result(timeout=timeout)
        pending, self._pending = self._pending, []
        for f in pending:
            exc = f.exception()
            if exc is not None:
                raise exc

    def restore_latest(self, seed: int = 0) -> Optional[RestoredCheckpoint]:
        return restore_latest(self.directory, seed=seed, registry=self._reg)

    def latest_step(self) -> Optional[int]:
        steps = list_steps(self.directory)
        return max(steps) if steps else None

    def close(self) -> None:
        """Stop the saver thread after draining queued saves."""
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=60.0)
        self._thread = None

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
