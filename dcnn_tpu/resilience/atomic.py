"""Low-level atomic file/directory commit helpers.

The durability contract every checkpoint path in this repo now builds on:

- :func:`write_file_atomic` — write to ``<path>.tmp-<uuid>``, flush,
  ``fsync``, ``os.replace`` onto ``path``. POSIX rename atomicity means a
  reader (or a restart after preemption) sees either the old complete
  bytes or the new complete bytes, never a torn prefix.
- :func:`commit_dir` — the directory analog (Orbax's scheme): the caller
  stages a *complete* checkpoint under a ``tmp-<uuid>`` sibling, then one
  ``os.replace(tmp, final)`` is the commit point. ``fsync`` on the parent
  directory makes the rename itself durable, not just reorderable cache
  state.

These helpers are deliberately free of any model/JAX imports — they are
shared by ``train/checkpoint.py`` (the v1 torn-write fix) and
``resilience/checkpoint.py`` (the v2 manager), and importing them must
never pull a backend.
"""

from __future__ import annotations

import hashlib
import os
import uuid


def fsync_path(path: str) -> None:
    """fsync a file or directory (directories need their own fd on POSIX;
    platforms that refuse O_RDONLY dir fsync just skip — rename ordering is
    still preserved by the filesystem there)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_file_atomic(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` such that ``path`` is never observable
    half-written: tmp sibling + fsync + ``os.replace``."""
    tmp = f"{path}.tmp-{uuid.uuid4().hex}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_path(os.path.dirname(os.path.abspath(path)))


def stage_dir(parent: str) -> str:
    """Create and return a fresh ``tmp-<uuid>`` staging directory under
    ``parent``. Stale ones (from a preempted process) are cleaned by
    :func:`sweep_stale_tmp`."""
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f"tmp-{uuid.uuid4().hex}")
    os.makedirs(tmp)
    return tmp


def commit_dir(tmp: str, final: str) -> None:
    """Atomically publish a fully-staged directory: fsync its files and
    itself, then one ``os.replace`` rename. ``final`` must not exist (the
    caller's naming scheme — step-numbered checkpoint dirs — guarantees
    uniqueness; overwriting a committed checkpoint is never correct)."""
    for name in sorted(os.listdir(tmp)):
        fsync_path(os.path.join(tmp, name))
    fsync_path(tmp)
    os.replace(tmp, final)
    fsync_path(os.path.dirname(os.path.abspath(final)))


def sweep_stale_tmp(parent: str, prefixes=("tmp-",)) -> int:
    """Remove leftover ``tmp-*`` staging dirs (a preempted process's
    unfinished saves) — and, when asked, ``corrupt-*`` quarantine dirs
    from prior restores. Returns how many were removed. Only call from a
    context that owns ``parent`` exclusively (manager startup), never
    concurrently with an in-flight save."""
    import shutil

    removed = 0
    if not os.path.isdir(parent):
        return 0
    for name in os.listdir(parent):
        if name.startswith(tuple(prefixes)):
            shutil.rmtree(os.path.join(parent, name), ignore_errors=True)
            removed += 1
    return removed


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()
