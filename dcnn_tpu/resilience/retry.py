"""One bounded-exponential-backoff-with-jitter primitive for the repo.

Before this module, transient-failure handling was re-invented per call
site: ``parallel/comm.connect`` looped on a fixed 200 ms delay,
``data/download._fetch`` gave up on the first error, and checkpoint I/O had
nothing. One primitive, three rules:

- **Bounded.** Every loop ends — by attempt count (``attempts``) or by
  deadline (``timeout`` seconds from the first call), whichever comes
  first. The last exception is re-raised (wrapped in nothing: callers keep
  their existing ``except OSError`` semantics).
- **Exponential with jitter.** Delay before retry *i* (0-based) is
  ``min(cap, base * 2**i)``, scaled by equal-jitter
  (``0.5 + 0.5*rand()``): synchronized retry storms from many workers
  hitting one coordinator decorrelate, while the expected schedule stays
  predictable for timeout budgeting. The rng is injectable and seedable —
  tests assert the exact delay sequence.
- **Injectable clock/sleep.** ``sleep=``/``clock=`` default to
  ``time.sleep``/``time.monotonic``; tests pass fakes and the whole retry
  schedule runs sleep-free.

Observability: every *retry* (not first attempts) increments the shared
registry's ``retry_attempts_total`` plus a per-site
``<name>_retry_attempts_total`` counter, so a fleet quietly riding its
backoff budget is visible before it becomes an outage.

Two forms: :func:`retry_call` (explicit — call sites that compute
arguments per attempt) and :func:`retriable` (decorator — call sites whose
whole body is the attempt). Both honor an armed
:class:`~dcnn_tpu.resilience.faults.FaultPlan` transparently, because the
fault is raised *inside* the wrapped callable by its own trip points.
"""

from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from ..obs import get_registry

T = TypeVar("T")


def backoff_delays(attempts: int, *, base: float = 0.2, cap: float = 5.0,
                   rng: Optional[random.Random] = None):
    """The delay schedule :func:`retry_call` uses, as a generator —
    ``min(cap, base*2**i)`` equal-jittered to ``[0.5d, d)``. Exposed so
    tests (and capacity planning) can enumerate it without running a
    failure."""
    r = rng if rng is not None else random
    for i in range(attempts):
        d = min(cap, base * (2.0 ** i))
        yield d * (0.5 + 0.5 * r.random())


def retry_call(fn: Callable[..., T], *args,
               attempts: int = 5,
               base: float = 0.2, cap: float = 5.0,
               timeout: Optional[float] = None,
               retry_on: Tuple[Type[BaseException], ...] = (OSError,),
               retry_if: Optional[Callable[[BaseException], bool]] = None,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic,
               rng: Optional[random.Random] = None,
               name: str = "generic",
               on_retry: Optional[Callable[[int, BaseException, float],
                                           None]] = None,
               registry=None,
               **kwargs) -> T:
    """Call ``fn(*args, **kwargs)``; on ``retry_on`` exceptions, back off
    and retry up to ``attempts`` total tries or until ``timeout`` seconds
    have elapsed since the first try. Re-raises the last exception.

    ``retry_if(exc)``, when given, refines ``retry_on``: a matching
    exception is only retried if the predicate returns True (the hook for
    "OSError, but not a permanent HTTP 404"). ``on_retry(attempt_index,
    exc, delay_s)`` is invoked before each sleep — the hook call sites use
    for logging without coupling this module to any logger."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    reg = registry if registry is not None else get_registry()
    deadline = (clock() + timeout) if timeout is not None else None
    delays = backoff_delays(attempts - 1, base=base, cap=cap, rng=rng)
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if retry_if is not None and not retry_if(e):
                raise
            last = e
            if i == attempts - 1:
                break
            delay = next(delays)
            if deadline is not None:
                remaining = deadline - clock()
                if remaining <= 0:
                    break
                delay = min(delay, remaining)
            reg.counter("retry_attempts_total",
                        "retries across all call sites").inc()
            reg.counter(f"{name}_retry_attempts_total",
                        f"retries at the {name} call site").inc()
            if on_retry is not None:
                on_retry(i, e, delay)
            sleep(delay)
    assert last is not None
    raise last


def retriable(**retry_kwargs):
    """Decorator form: ``@retriable(attempts=3, retry_on=(OSError,),
    name="download")``. Keyword arguments are :func:`retry_call`'s."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, **retry_kwargs, **kwargs)

        return wrapper

    return deco
