"""Gray-failure (fail-slow) detection: relative-performance scoring.

Every fault-tolerance protocol in this repo detects *fail-stop* — death,
closed sockets, silence past a timeout. A component that stays alive
while running 10x slower defeats all of them: it keeps beating, keeps
answering pings, and silently drags the whole fleet's goodput down
("Fail-Slow at Scale", Gunawi et al., FAST'18 — the dominant un-handled
failure mode in real fleets). This module is the shared detector the
three mitigation surfaces drive:

- **elastic DP straggler eviction** (``parallel/elastic.py``) — BEAT /
  GRADS frames piggyback per-peer local-compute walls; the leader runs a
  detector over them and evicts a convicted straggler through the
  generation-fenced reconfiguration (treated as a lost peer).
- **pipeline stage rebalance** (``parallel/distributed_pipeline.py``) —
  per-stage walls feed a proportional layer repartition when imbalance
  exceeds a band (stages are unique: rebalance, never evict).
- **router hedged requests + slow-replica probation**
  (``serve/router.py``) — a latency-outlier replica is weighted down
  into probation and auto-rejoined on recovery; tail requests are hedged
  ("The Tail at Scale", Dean & Barroso).

Detector contract (docs/reliability.md §11):

- **Relative, not absolute.** A component is judged against the *fleet
  median* of its peers' EWMA walls — there are no absolute "slow"
  thresholds to mis-tune per model size. The outlier test is
  MAD-based (median absolute deviation — robust to the outlier itself
  polluting the spread) AND ratio-floored (``ewma > ratio * median``),
  so a tiny-MAD fleet cannot convict on noise.
- **A fleet-wide slowdown never convicts a victim.** Everyone slow
  together moves the median with them — no component is an outlier
  relative to its peers, and with fewer than ``min_peers`` scored
  components nobody is ever judged at all. This is the hard rule: gray
  failure means *one* component degraded, not "the input got bigger".
- **Probation → convict with dwell + exit hysteresis.** An outlier
  enters probation; only after ``dwell_s`` of *sustained* outlier-hood
  is it convicted (one GC pause is not a gray failure). Exit requires
  dropping below ``exit_ratio * median`` — a band gap below the entry
  threshold so a component oscillating at the line does not flap.
- **Injectable clock, no threads.** ``observe()`` is O(1); callers pump
  :meth:`evaluate` from their existing sweeps. Tier-1 drives everything
  with fake clocks, sleep-free.

Stdlib-only and import-safe from any layer (the faults.py rule).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional

#: Detector states, in escalation order.
STATES = ("healthy", "probation", "convicted")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v not in (None, "") else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


@dataclass(frozen=True)
class SlownessConfig:
    """Knobs for one :class:`SlownessDetector` (all surfaces share this
    shape; each surface resolves its own instance). Env overrides via
    :meth:`from_env` use the ``DCNN_SLOW_*`` names in the table in
    docs/reliability.md §11."""

    #: EWMA weight of the newest wall sample (higher = faster reaction,
    #: noisier score).
    ewma_alpha: float = 0.3
    #: Samples a component must contribute before it is scored at all.
    min_samples: int = 3
    #: Scored components required before ANYONE can be judged — below
    #: this there is no meaningful fleet median (and a 2-component
    #: "fleet" would let each convict the other).
    min_peers: int = 3
    #: MAD multiplier: outlier iff ``ewma > median + mad_k * MAD`` …
    mad_k: float = 4.0
    #: … AND ``ewma > ratio * median`` (the floor that keeps a tiny-MAD
    #: fleet from convicting on noise).
    ratio: float = 2.0
    #: Exit hysteresis: probation/conviction clears only below
    #: ``exit_ratio * median`` (must be < ratio to make a real band).
    exit_ratio: float = 1.5
    #: Seconds of *sustained* outlier-hood in probation before convict.
    dwell_s: float = 1.0

    def __post_init__(self):
        if not (0.0 < self.ewma_alpha <= 1.0):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {self.ewma_alpha}")
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {self.min_samples}")
        if self.min_peers < 2:
            raise ValueError(f"min_peers must be >= 2, got {self.min_peers}")
        if self.ratio <= 1.0:
            raise ValueError(f"ratio must be > 1, got {self.ratio}")
        if not (1.0 <= self.exit_ratio <= self.ratio):
            raise ValueError(
                f"exit_ratio must be in [1, ratio={self.ratio}], "
                f"got {self.exit_ratio}")
        if self.dwell_s < 0.0:
            raise ValueError(f"dwell_s must be >= 0, got {self.dwell_s}")

    @classmethod
    def from_env(cls, base: Optional["SlownessConfig"] = None
                 ) -> "SlownessConfig":
        b = base if base is not None else cls()
        return replace(
            b,
            ewma_alpha=_env_float("DCNN_SLOW_EWMA_ALPHA", b.ewma_alpha),
            min_samples=_env_int("DCNN_SLOW_MIN_SAMPLES", b.min_samples),
            min_peers=_env_int("DCNN_SLOW_MIN_PEERS", b.min_peers),
            mad_k=_env_float("DCNN_SLOW_MAD_K", b.mad_k),
            ratio=_env_float("DCNN_SLOW_RATIO", b.ratio),
            exit_ratio=_env_float("DCNN_SLOW_EXIT_RATIO", b.exit_ratio),
            dwell_s=_env_float("DCNN_SLOW_DWELL_S", b.dwell_s),
        )


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class SlownessDetector:
    """Per-component relative-performance scoring with a probation →
    convict state machine.

    ``observe(component, wall_s)`` feeds one wall sample (O(1) EWMA
    update); ``evaluate()`` re-scores the fleet and returns the state
    transitions that fired — the caller acts on ``to == "convicted"``
    (evict / probation / rebalance) and ``to == "healthy"`` (rejoin).
    A caller that removes a component from the fleet calls
    :meth:`forget` so a stale score cannot shift the median.
    """

    def __init__(self, config: Optional[SlownessConfig] = None, *,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config if config is not None else SlownessConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._ewma: Dict[str, float] = {}    # dcnn: guarded_by=_lock
        self._n: Dict[str, int] = {}         # dcnn: guarded_by=_lock
        self._state: Dict[str, str] = {}     # dcnn: guarded_by=_lock
        self._since: Dict[str, float] = {}   # dcnn: guarded_by=_lock
        # probation entry stamp, for the dwell test

    # -- feeding -----------------------------------------------------------
    def observe(self, component: str, wall_s: float) -> None:
        """One wall-clock sample for ``component`` (seconds or any
        consistent unit — the detector is scale-free, all tests are
        relative to the fleet median)."""
        if wall_s < 0.0:
            return  # clock skew artifact; never poison the score
        a = self.config.ewma_alpha
        with self._lock:
            prev = self._ewma.get(component)
            self._ewma[component] = (wall_s if prev is None
                                     else (1.0 - a) * prev + a * wall_s)
            self._n[component] = self._n.get(component, 0) + 1
            self._state.setdefault(component, "healthy")

    def forget(self, component: str) -> None:
        """Drop a component (evicted / decommissioned) so its stale
        score stops shifting the fleet median."""
        with self._lock:
            self._ewma.pop(component, None)
            self._n.pop(component, None)
            self._state.pop(component, None)
            self._since.pop(component, None)

    # -- scoring -----------------------------------------------------------
    def _scored(self) -> Dict[str, float]:
        # dcnn: guarded_by=_lock (caller holds)
        ms = self.config.min_samples
        return {c: v for c, v in self._ewma.items()
                if self._n.get(c, 0) >= ms}

    def fleet_median(self) -> Optional[float]:
        with self._lock:
            scored = self._scored()
        return _median(list(scored.values())) if scored else None

    def evaluate(self) -> List[Dict[str, object]]:
        """Re-score every component against the fleet median and step
        the state machines. Returns the transitions that fired, each
        ``{"component", "from", "to", "ewma", "median", "t"}`` — enough
        for the caller's flight bundle to explain the verdict."""
        now = self._clock()
        cfg = self.config
        out: List[Dict[str, object]] = []
        with self._lock:
            scored = self._scored()
            if len(scored) < cfg.min_peers:
                # the hard rule's small-fleet half: no meaningful median
                # below min_peers components — nobody is judged, and
                # anyone already in probation un-flags (the fleet they
                # were an outlier of no longer exists)
                for c, st in list(self._state.items()):
                    if st == "probation":
                        self._state[c] = "healthy"
                        self._since.pop(c, None)
                        out.append({"component": c, "from": st,
                                    "to": "healthy",
                                    "ewma": self._ewma.get(c),
                                    "median": None, "t": now})
                return out
            med = _median(list(scored.values()))
            mad = _median([abs(v - med) for v in scored.values()])
            enter = max(med + cfg.mad_k * mad, cfg.ratio * med)
            leave = cfg.exit_ratio * med
            for c, v in scored.items():
                st = self._state.get(c, "healthy")
                new = st
                if st == "healthy":
                    if v > enter:
                        new = "probation"
                        self._since[c] = now
                elif st == "probation":
                    if v <= leave:
                        new = "healthy"
                        self._since.pop(c, None)
                    elif (v > enter
                          and now - self._since.get(c, now) >= cfg.dwell_s):
                        new = "convicted"
                else:  # convicted
                    if v <= leave:
                        new = "healthy"
                        self._since.pop(c, None)
                if new != st:
                    self._state[c] = new
                    out.append({"component": c, "from": st, "to": new,
                                "ewma": v, "median": med, "t": now})
        return out

    def probe_ok(self, component: str, wall_s: float) -> bool:
        """Recovery probe: would a component performing ``wall_s`` be
        clean relative to the current fleet (below the exit band)?
        Drives evicted-host rejoin and probation release. With no scored
        fleet to compare against it passes — the same fail-open stance
        as the fleet-wide rule (no relative evidence, no verdict)."""
        with self._lock:
            scored = {c: v for c, v in self._scored().items()
                      if c != component}
        if len(scored) < max(self.config.min_peers - 1, 1):
            return True
        med = _median(list(scored.values()))
        return wall_s <= self.config.exit_ratio * med

    # -- introspection -----------------------------------------------------
    def state(self, component: str) -> str:
        with self._lock:
            return self._state.get(component, "healthy")

    def states(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._state)

    def convicted(self) -> List[str]:
        with self._lock:
            return sorted(c for c, s in self._state.items()
                          if s == "convicted")

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Per-component ``{ewma, samples, state, ratio_to_median}`` —
        the ``/healthz`` + flight-bundle view."""
        with self._lock:
            scored = self._scored()
            med = _median(list(scored.values())) if scored else None
            return {c: {"ewma": self._ewma[c],
                        "samples": self._n.get(c, 0),
                        "state": self._state.get(c, "healthy"),
                        "ratio_to_median": (self._ewma[c] / med
                                            if med else None)}
                    for c in self._ewma}

    def __repr__(self) -> str:
        with self._lock:
            n = len(self._ewma)
            bad = sorted(c for c, s in self._state.items()
                         if s != "healthy")
        return f"SlownessDetector(components={n}, flagged={bad})"
