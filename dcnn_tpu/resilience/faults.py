"""Deterministic, seeded fault injection for testing recovery paths.

Every robustness claim in this subsystem ("a preemption mid-save leaves the
previous checkpoint loadable", "a NaN step is skipped without touching
params") is only a claim until something can *make* the failure happen on
demand. This module is that something: production call sites carry named,
zero-cost **trip points** (``trip("ckpt.before_rename", step=...)``), and a
test arms a :class:`FaultPlan` that decides — deterministically, from its
seed and arm counts — which invocation of which point raises.

Design rules:

- **Zero cost when disarmed.** ``trip()`` is a module-level function that
  checks one global against ``None`` — no allocation, no locking on the
  hot path. Production code never pays for the harness it carries.
- **Deterministic.** A plan is armed for a *point name* plus an optional
  ``at=`` invocation index (0-based, per point). The same plan + the same
  code path = the same failure, every run. The only randomness —
  :meth:`FaultPlan.bit_flip`'s choice of byte — comes from the plan's own
  seeded ``random.Random``.
- **Monkeypatch-friendly.** Arming is ``install(plan)`` / ``clear()`` or
  the ``with plan:`` context manager; tests never have to reach into
  private state. ``InjectedFault`` is a normal ``RuntimeError`` subclass
  so production ``except OSError`` clauses do NOT swallow it (a fault the
  harness injects must surface unless the code path under test is
  *supposed* to absorb it, in which case the test arms an ``exc=OSError``
  explicitly).

Trip points wired in this PR (grep for ``faults.trip`` to enumerate):

==============================  ==============================================
``ckpt.before_rename``          crash after a checkpoint's files are fully
                                written but before the atomic commit rename
``ckpt.after_rename``           crash just after the commit rename (the new
                                checkpoint exists; retention GC never ran)
``ckpt.write``                  crash mid-write, files partially on disk
``stream.produce``              raise in the streaming feed's producer thread
                                at shard ``at=i``
``train.nonfinite_input``       poison the training batch at global step
                                ``at=j`` so the loss/grads go non-finite
``comm.send``                   fail a channel send attempt pre-wire (drives
                                the send backoff/retry path; armed with
                                ``exc=InjectedCrash`` it is the "host died
                                mid-send" simulation)
``comm.connect``                fail a connection attempt (drives the
                                backoff/retry path)
``elastic.heartbeat``           raise in the elastic controller's beat path
                                at beat ``at=k`` — armed with
                                ``exc=InjectedCrash`` this IS the
                                kill-a-host-mid-epoch simulation
                                (``parallel/elastic.py``)
``elastic.reconfigure``         raise at reconfiguration entry — armed with
                                ``exc=InjectedCrash`` on a *second* peer it
                                proves a loss during recovery is survived
                                (reconfigure idempotence)
``pipeline.stage_death``        raise in a TCP stage worker's dispatch path at
                                job ``at=k`` (a deterministic per-worker
                                sequence: FORWARD/BACKWARD/UPDATE/CONFIG/
                                GATHER) — armed with ``exc=InjectedCrash``
                                this IS the kill-a-stage-mid-batch
                                simulation: the worker's sockets close and
                                the coordinator recovers
                                (``parallel/worker.py``)
``pipeline.weight_ship``        fail the coordinator's recovery weight
                                re-ship for stage ``at=i`` — armed with
                                ``exc=OSError`` it is the torn-weight-ship
                                simulation; recovery re-enters idempotently
                                (``parallel/distributed_pipeline.py``)
``serve.route``                 fail the router's admission/dispatch path for
                                request ``at=i`` (``serve/router.py``) — the
                                routing-layer-itself chaos hook
``serve.replica_infer``         fire in a replica's dispatch: ``InjectedFault``
                                is one failed request (the canary-degradation
                                fixture — the router re-admits it elsewhere
                                and counts it against the replica/version);
                                ``InjectedCrash`` kills the replica (in-flight
                                requests die, the router ejects + re-admits;
                                ``serve/replica.py``)
``serve.swap``                  fail a version swap's engine-load step
                                (``serve/swap.py``) — the replica rejoins on
                                its OLD version; ``InjectedCrash`` = died
                                mid-swap
``aot.commit``                  fail an executable-cache commit before its
                                staging (``aot/cache.py``) — the compile
                                still succeeds, only the cache stays cold;
                                ``InjectedCrash`` = preempted mid-publish
                                (the atomic commit_dir guarantees no torn
                                entry is ever visible)
``aot.load``                    fail an executable-cache lookup before its
                                read — the warm path must degrade to a
                                transparent recompile, never an error
``decode.admit``                fail admitting sequence ``at=i`` into the
                                continuous decode batch: ``InjectedFault``
                                fails just that sequence's future, typed;
                                ``InjectedCrash`` escalates to the step
                                handler (``serve/decode.py``)
``decode.step``                 fire before decode step ``at=k`` dispatches —
                                armed with ``exc=InjectedCrash`` it is the
                                scheduler-died-mid-decode simulation: every
                                accepted sequence (active AND queued) fails
                                typed, none silently dropped
                                (``serve/decode.py``)
``elastic.slow_peer``           delay hook (``FaultPlan.slow``) in the
                                elastic step's local-compute window — makes
                                this peer a straggler without killing it
                                (``parallel/elastic.py``)
``pipeline.slow_stage``         delay hook in a TCP stage worker's dispatch
                                path — one slow stage drags the whole
                                pipeline (``parallel/worker.py``)
``serve.slow_replica``          delay hook in a replica's engine dispatch —
                                the gray-failure serving fixture
                                (``serve/replica.py``)
``feed.slow_worker``            delay hook in a feed worker's shard-prep
                                path (``data/workers.py``)
==============================  ==============================================

Fail-stop points raise; the four ``slow_*`` points are **delay** hooks:
production code calls :func:`slowdown` (or ``plan.slowdown``) with the
wall it is about to spend and sleeps the returned extra seconds — zero
when disarmed. ``FaultPlan.slow(point, factor=10)`` scales the measured
wall (a 10x-slow component); ``delay_s=`` adds a fixed stall instead.
Both honor the same deterministic ``at=`` / ``times=`` windowing as
:meth:`FaultPlan.arm`.

This module is stdlib-only and import-safe from any layer.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Tuple, Type


class InjectedFault(RuntimeError):
    """A fault raised by an armed :class:`FaultPlan` trip point."""

    def __init__(self, point: str, invocation: int, **context):
        self.point = point
        self.invocation = invocation
        self.context = context
        ctx = "".join(f" {k}={v!r}" for k, v in sorted(context.items()))
        super().__init__(
            f"injected fault at {point!r} (invocation {invocation}){ctx}")


class InjectedCrash(InjectedFault):
    """A fault standing in for a hard preemption (SIGKILL) — the process
    would be gone, so recovery code must never rely on catching it. Tests
    catch it at top level to simulate the restart."""


class FaultPlan:
    """A seeded set of armed trip points.

    ``plan.arm("ckpt.before_rename", exc=InjectedCrash)`` arms every
    invocation; ``at=k`` starts firing at the (0-based) k-th invocation of
    that point; ``times=n`` (default unlimited) disarms after n firings.
    Compositions read naturally: ``at=2, times=1`` is "exactly the third
    invocation"; ``at=4, times=2`` is "two consecutive faults starting at
    the fifth"; ``times=2, exc=OSError`` is the "fail twice then recover"
    idiom retry tests want.

    Invocation counters are per point, start at 0, and are also the
    post-mortem record: ``plan.count("ckpt.before_rename")`` tells a test
    how often production code actually passed the point.
    """

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self._armed: Dict[str, Tuple[Optional[int], Optional[int],
                                     Type[BaseException]]] = {}
        self._counts: Dict[str, int] = {}
        # delay-injection arms (FaultPlan.slow): point -> (at, times,
        # factor, delay_s); counters separate from the fail-stop ones so
        # a point can carry both kinds without aliasing windows
        self._slow_armed: Dict[str, Tuple[Optional[int], Optional[int],
                                          Optional[float],
                                          Optional[float]]] = {}  # dcnn: guarded_by=_lock
        self._slow_counts: Dict[str, int] = {}  # dcnn: guarded_by=_lock

    def arm(self, point: str, *, at: Optional[int] = None,
            times: Optional[int] = None,
            exc: Type[BaseException] = InjectedFault) -> "FaultPlan":
        with self._lock:
            self._armed[point] = (at, times, exc)
        return self

    def disarm(self, point: str) -> "FaultPlan":
        with self._lock:
            self._armed.pop(point, None)
        return self

    def count(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    def _check(self, point: str, context: dict) -> None:
        with self._lock:
            n = self._counts.get(point, 0)
            self._counts[point] = n + 1
            spec = self._armed.get(point)
            if spec is None:
                return
            at, times, exc = spec
            if at is not None and n < at:
                return
            if times is not None:
                times -= 1
                if times <= 0:
                    self._armed.pop(point, None)
                else:
                    self._armed[point] = (at, times, exc)
        if issubclass(exc, InjectedFault):
            raise exc(point, n, **context)
        raise exc(f"injected fault at {point!r} (invocation {n})")

    def trip(self, point: str, **context) -> None:
        """Per-plan trip: check THIS plan (not the process-global one).

        Multi-peer simulations (``parallel/elastic.py`` tests run several
        in-process peers) arm one plan per victim and hand it to that
        peer's controller — the global :func:`install` slot would fault
        every peer at once."""
        self._check(point, context)

    # -- delay injection (fail-slow, not fail-stop) ------------------------
    def slow(self, point: str, *, factor: Optional[float] = None,
             delay_s: Optional[float] = None, at: Optional[int] = None,
             times: Optional[int] = None) -> "FaultPlan":
        """Arm ``point`` as a **delay** hook: every matching
        :meth:`slowdown` query returns extra seconds for the call site to
        sleep. Exactly one of ``factor`` (scale the measured wall — a
        ``factor=10`` component runs 10x slow) or ``delay_s`` (fixed
        stall) must be given; ``at``/``times`` window invocations exactly
        like :meth:`arm`."""
        if (factor is None) == (delay_s is None):
            raise ValueError(
                "FaultPlan.slow wants exactly one of factor= or delay_s=")
        if factor is not None and factor < 1.0:
            raise ValueError(f"factor must be >= 1, got {factor}")
        if delay_s is not None and delay_s < 0.0:
            raise ValueError(f"delay_s must be >= 0, got {delay_s}")
        with self._lock:
            self._slow_armed[point] = (at, times, factor, delay_s)
        return self

    def unslow(self, point: str) -> "FaultPlan":
        """Disarm a :meth:`slow` point — the fault "clears" (recovery /
        probation-rejoin fixtures)."""
        with self._lock:
            self._slow_armed.pop(point, None)
        return self

    def slow_count(self, point: str) -> int:
        with self._lock:
            return self._slow_counts.get(point, 0)

    def slowdown(self, point: str, base_s: float = 0.0,
                 **context) -> float:
        """Per-plan delay query: extra seconds the call site should
        sleep on top of the ``base_s`` wall it measured — 0.0 unless
        :meth:`slow` armed this point and the invocation window matches.
        Deterministic like :meth:`trip`; never raises."""
        with self._lock:
            n = self._slow_counts.get(point, 0)
            self._slow_counts[point] = n + 1
            spec = self._slow_armed.get(point)
            if spec is None:
                return 0.0
            at, times, factor, delay_s = spec
            if at is not None and n < at:
                return 0.0
            if times is not None:
                times -= 1
                if times <= 0:
                    self._slow_armed.pop(point, None)
                else:
                    self._slow_armed[point] = (at, times, factor, delay_s)
        if delay_s is not None:
            return delay_s
        return base_s * max(float(factor) - 1.0, 0.0)

    # -- corruption utility (not a trip point: tests call it directly) --
    def bit_flip(self, path: str) -> Tuple[int, int]:
        """Flip one bit of one byte of ``path`` in place (choice drawn from
        the plan's seeded rng). Returns ``(offset, bit)`` for the record.
        The canonical way to manufacture a checksum-invalid checkpoint."""
        with open(path, "rb") as f:
            data = bytearray(f.read())
        if not data:
            raise ValueError(f"cannot bit-flip empty file {path}")
        off = self.rng.randrange(len(data))
        bit = self.rng.randrange(8)
        data[off] ^= 1 << bit
        # in-place corruption IS the point here — this manufactures the
        # torn/bit-flipped artifact the restore path must survive
        with open(path, "wb") as f:  # dcnn: disable=AT01
            f.write(data)
        return off, bit

    # -- context-manager arming --
    def __enter__(self) -> "FaultPlan":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        clear()


# One process-global active plan: production trip points check a single
# module global against None, so the disarmed cost is one load + one jump.
_ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> None:
    global _ACTIVE
    _ACTIVE = plan


def clear() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultPlan]:
    return _ACTIVE


def trip(point: str, **context) -> None:
    """Production-side hook: raises iff a plan is installed and armed for
    this point/invocation. Free (one global check) otherwise."""
    if _ACTIVE is not None:
        _ACTIVE._check(point, context)


def slowdown(point: str, base_s: float = 0.0, **context) -> float:
    """Production-side delay hook (the fail-slow twin of :func:`trip`):
    extra seconds to sleep at this point — 0.0 (one global check, no
    allocation) unless an installed plan armed it via
    :meth:`FaultPlan.slow`. Call sites sleep the return value INSIDE
    their measured timing window so detectors see the slowness exactly
    as a degraded host would produce it."""
    if _ACTIVE is not None:
        return _ACTIVE.slowdown(point, base_s, **context)
    return 0.0
