"""Model zoo (reference ``include/nn/example_models.hpp:13-404``) plus the
generative-serving decoder family (``decoder.py``, no reference analog)."""

from .decoder import MHADecoder, create_mha_decoder
from .zoo import (
    MODEL_ZOO, create_cifar10_trainer_v1, create_cifar10_trainer_v2,
    create_cnn_cifar100, create_cnn_tiny_imagenet, create_mha_classifier,
    create_mnist_trainer, create_model,
    create_resnet9_cifar10, create_resnet9_tiny_imagenet,
    create_resnet18_cifar10, create_resnet18_tiny_imagenet,
    create_resnet20_cifar10, create_resnet34_tiny_imagenet,
    create_resnet50_cifar10, create_resnet50_imagenet,
    create_resnet50_tiny_imagenet,
)

__all__ = [
    "MODEL_ZOO", "create_model",
    "MHADecoder", "create_mha_decoder",
    "create_mnist_trainer", "create_cifar10_trainer_v1", "create_cifar10_trainer_v2",
    "create_cnn_cifar100", "create_mha_classifier",
    "create_resnet9_cifar10", "create_resnet18_cifar10", "create_resnet20_cifar10",
    "create_resnet50_cifar10", "create_resnet9_tiny_imagenet", "create_cnn_tiny_imagenet",
    "create_resnet18_tiny_imagenet", "create_resnet34_tiny_imagenet",
    "create_resnet50_tiny_imagenet", "create_resnet50_imagenet",
]
