"""Model zoo — architecture-for-architecture with the reference.

Reference: ``include/nn/example_models.hpp`` — mnist CNN (:13), cifar10 v1/v2
(:33/:50), resnet9-cifar10 (:95), resnet18/20/50-cifar10 (:136/:165/:194),
resnet9/cnn/resnet18/34/50-tiny-imagenet (:227/:262/:306/:334/:369),
resnet50-imagenet (:404). Layer sequences, channel widths, strides, bias
flags and BN epsilons are reproduced exactly (including quirks like
resnet50-cifar10 flattening the 4×4 map with no avgpool, and the
tiny-imagenet resnet18/34 stem using 32 channels with BN eps 1e-3).

Every builder takes ``data_format`` so the same architectures run in NHWC for
the TPU fast path.
"""

from __future__ import annotations

from typing import Callable, Dict

from ..nn import Sequential, SequentialBuilder


def create_mnist_trainer(data_format: str = "NCHW") -> Sequential:
    """LeNet-style MNIST CNN (example_models.hpp:13-31)."""
    shape = (1, 28, 28) if data_format == "NCHW" else (28, 28, 1)
    return (SequentialBuilder("mnist_cnn_model", data_format)
            .input(shape)
            .conv2d(8, 5, 1, 0, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
            .maxpool2d(3, 3, 0, "pool1")
            .conv2d(16, 1, 1, 0, True, "conv2_1x1").batchnorm(name="bn2").activation("relu", "relu2")
            .conv2d(48, 5, 1, 0, True, "conv3").batchnorm(name="bn3").activation("relu", "relu3")
            .maxpool2d(2, 2, 0, "pool2")
            .flatten("flatten")
            .dense(10, True, "output")
            .build())


def create_cifar10_trainer_v1(data_format: str = "NCHW") -> Sequential:
    """Small CIFAR-10 CNN (example_models.hpp:33-48)."""
    shape = (3, 32, 32) if data_format == "NCHW" else (32, 32, 3)
    return (SequentialBuilder("cifar10_cnn_classifier_v1", data_format)
            .input(shape)
            .conv2d(16, 3, 1, 0, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
            .maxpool2d(3, 3, 0, "maxpool1")
            .conv2d(64, 3, 1, 0, True, "conv2").activation("relu", "relu2")
            .maxpool2d(4, 4, 0, "maxpool2")
            .flatten("flatten")
            .dense(10, True, "fc1")
            .build())


def create_cifar10_trainer_v2(data_format: str = "NCHW") -> Sequential:
    """VGG-style CIFAR-10 CNN (example_models.hpp:50-93)."""
    shape = (3, 32, 32) if data_format == "NCHW" else (32, 32, 3)
    b = (SequentialBuilder("cifar10_cnn_classifier", data_format)
         .input(shape)
         .conv2d(64, 3, 1, 1, False, "conv0").batchnorm(name="bn0").activation("relu", "relu0")
         .conv2d(64, 3, 1, 1, False, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
         .maxpool2d(2, 2, 0, "pool0")
         .conv2d(128, 3, 1, 1, False, "conv2").batchnorm(name="bn2").activation("relu", "relu2")
         .conv2d(128, 3, 1, 1, False, "conv3").batchnorm(name="bn3").activation("relu", "relu3")
         .maxpool2d(2, 2, 0, "pool1")
         .conv2d(256, 3, 1, 1, False, "conv4").batchnorm(name="bn5").activation("relu", "relu5")
         .conv2d(256, 3, 1, 1, False, "conv5").activation("relu", "relu6")
         .conv2d(256, 3, 1, 1, False, "conv6").batchnorm(name="bn6").activation("relu", "relu6b")
         .maxpool2d(2, 2, 0, "pool2")
         .conv2d(512, 3, 1, 1, False, "conv7").batchnorm(name="bn8").activation("relu", "relu7")
         .conv2d(512, 3, 1, 1, False, "conv8").batchnorm(name="bn9").activation("relu", "relu8")
         .conv2d(512, 3, 1, 1, False, "conv9").batchnorm(name="bn10").activation("relu", "relu9")
         .maxpool2d(2, 2, 0, "pool3")
         .flatten("flatten")
         .dense(512, True, "fc0").activation("relu", "relu10")
         .dense(10, True, "fc1"))
    return b.build()


def create_cnn_cifar100(data_format: str = "NCHW") -> Sequential:
    """CIFAR-100 CNN: the reference's cifar100 trainer reuses the VGG-style
    cifar10_v2 architecture verbatim (examples/cifar100_cnn_trainer.cpp:40-79)
    — including a final ``dense(10)`` head even though CIFAR-100 has 100
    classes (a latent reference bug: its loader one-hots to 100). Reproduced
    layer-for-layer except the head, deliberately corrected to 100."""
    shape = (3, 32, 32) if data_format == "NCHW" else (32, 32, 3)
    b = (SequentialBuilder("cifar100_cnn_classifier", data_format)
         .input(shape)
         .conv2d(64, 3, 1, 1, False, "conv0").batchnorm(name="bn0").activation("relu", "relu0")
         .conv2d(64, 3, 1, 1, False, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
         .maxpool2d(2, 2, 0, "pool0")
         .conv2d(128, 3, 1, 1, False, "conv2").batchnorm(name="bn2").activation("relu", "relu2")
         .conv2d(128, 3, 1, 1, False, "conv3").batchnorm(name="bn3").activation("relu", "relu3")
         .maxpool2d(2, 2, 0, "pool1")
         .conv2d(256, 3, 1, 1, False, "conv4").batchnorm(name="bn5").activation("relu", "relu5")
         .conv2d(256, 3, 1, 1, False, "conv5").activation("relu", "relu6")
         .conv2d(256, 3, 1, 1, False, "conv6").batchnorm(name="bn6").activation("relu", "relu6b")
         .maxpool2d(2, 2, 0, "pool2")
         .conv2d(512, 3, 1, 1, False, "conv7").batchnorm(name="bn8").activation("relu", "relu7")
         .conv2d(512, 3, 1, 1, False, "conv8").batchnorm(name="bn9").activation("relu", "relu8")
         .conv2d(512, 3, 1, 1, False, "conv9").batchnorm(name="bn10").activation("relu", "relu9")
         .maxpool2d(2, 2, 0, "pool3")
         .flatten("flatten")
         .dense(512, True, "fc0").activation("relu", "relu10")
         .dense(100, True, "fc1"))
    return b.build()


def create_resnet9_cifar10(data_format: str = "NCHW") -> Sequential:
    """ResNet-9 (example_models.hpp:95-134)."""
    shape = (3, 32, 32) if data_format == "NCHW" else (32, 32, 3)
    return (SequentialBuilder("ResNet-9-CIFAR10", data_format)
            .input(shape)
            .conv2d(64, 3, 1, 1, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
            .conv2d(128, 3, 1, 1, True, "conv2").batchnorm(name="bn2").activation("relu", "relu2")
            .maxpool2d(2, 2, 0, "pool1")
            .basic_residual_block(128, 128, 1, "res_block1")
            .basic_residual_block(128, 128, 1, "res_block2")
            .conv2d(256, 3, 1, 1, True, "conv3").batchnorm(name="bn3").activation("relu", "relu3")
            .maxpool2d(2, 2, 0, "pool2")
            .basic_residual_block(256, 256, 1, "res_block3")
            .basic_residual_block(256, 256, 1, "res_block4")
            .conv2d(512, 3, 1, 1, True, "conv4").batchnorm(name="bn4").activation("relu", "relu4")
            .maxpool2d(2, 2, 0, "pool3")
            .basic_residual_block(512, 512, 1, "res_block5")
            .avgpool2d(4, 1, 0, "avgpool")
            .flatten("flatten")
            .dense(10, True, "output")
            .build())


def create_resnet18_cifar10(data_format: str = "NCHW") -> Sequential:
    """ResNet-18 CIFAR-10 (example_models.hpp:136-163; note the reference uses
    11 basic blocks with a commented-out 12th — reproduced as-is)."""
    shape = (3, 32, 32) if data_format == "NCHW" else (32, 32, 3)
    return (SequentialBuilder("ResNet-18-CIFAR10", data_format)
            .input(shape)
            .conv2d(64, 3, 1, 1, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
            .basic_residual_block(64, 64, 1, "layer1_block1")
            .basic_residual_block(64, 64, 1, "layer1_block2")
            .basic_residual_block(64, 128, 2, "layer2_block1")
            .basic_residual_block(128, 128, 1, "layer2_block2")
            .basic_residual_block(128, 128, 1, "layer2_block3")
            .basic_residual_block(128, 256, 2, "layer3_block1")
            .basic_residual_block(256, 256, 1, "layer3_block2")
            .basic_residual_block(256, 256, 1, "layer3_block3")
            .basic_residual_block(256, 512, 2, "layer4_block1")
            .basic_residual_block(512, 512, 1, "layer4_block2")
            .avgpool2d(4, 4, 0, "avgpool")
            .flatten("flatten")
            .dense(10, True, "output")
            .build())


def create_resnet20_cifar10(data_format: str = "NCHW") -> Sequential:
    """ResNet-20 CIFAR-10 (example_models.hpp:165-192)."""
    shape = (3, 32, 32) if data_format == "NCHW" else (32, 32, 3)
    return (SequentialBuilder("ResNet-20-CIFAR10", data_format)
            .input(shape)
            .conv2d(64, 3, 1, 1, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
            .basic_residual_block(64, 64, 1, "layer1_block1")
            .basic_residual_block(64, 64, 1, "layer1_block2")
            .basic_residual_block(64, 64, 1, "layer1_block3")
            .basic_residual_block(64, 128, 2, "layer2_block1")
            .basic_residual_block(128, 128, 1, "layer2_block2")
            .basic_residual_block(128, 128, 1, "layer2_block3")
            .basic_residual_block(128, 256, 2, "layer3_block1")
            .basic_residual_block(256, 256, 1, "layer3_block2")
            .basic_residual_block(256, 256, 1, "layer3_block3")
            .avgpool2d(8, 1, 0, "avgpool")
            .flatten("flatten")
            .dense(10, True, "output")
            .build())


def create_resnet50_cifar10(data_format: str = "NCHW") -> Sequential:
    """ResNet-50 CIFAR-10 (example_models.hpp:194-225; the reference flattens
    the 4×4×2048 map directly — no avgpool — reproduced as-is)."""
    shape = (3, 32, 32) if data_format == "NCHW" else (32, 32, 3)
    b = (SequentialBuilder("ResNet-50-CIFAR10", data_format)
         .input(shape)
         .conv2d(64, 3, 1, 1, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1"))
    _resnet50_body(b, 64)
    return b.flatten("flatten").dense(10, True, "fc").build()


def _resnet50_body(b: SequentialBuilder, cin: int) -> SequentialBuilder:
    """The four bottleneck stages shared by every ResNet-50 variant
    (example_models.hpp:199-221/:377-395)."""
    b.bottleneck_residual_block(cin, 64, 256, 1, "layer1_block1")
    b.bottleneck_residual_block(256, 64, 256, 1, "layer1_block2")
    b.bottleneck_residual_block(256, 64, 256, 1, "layer1_block3")
    b.bottleneck_residual_block(256, 128, 512, 2, "layer2_block1")
    for i in (2, 3, 4):
        b.bottleneck_residual_block(512, 128, 512, 1, f"layer2_block{i}")
    b.bottleneck_residual_block(512, 256, 1024, 2, "layer3_block1")
    for i in (2, 3, 4, 5, 6):
        b.bottleneck_residual_block(1024, 256, 1024, 1, f"layer3_block{i}")
    b.bottleneck_residual_block(1024, 512, 2048, 2, "layer4_block1")
    for i in (2, 3):
        b.bottleneck_residual_block(2048, 512, 2048, 1, f"layer4_block{i}")
    return b


def create_resnet9_tiny_imagenet(data_format: str = "NCHW") -> Sequential:
    """ResNet-9 Tiny-ImageNet (example_models.hpp:227-260)."""
    shape = (3, 64, 64) if data_format == "NCHW" else (64, 64, 3)
    return (SequentialBuilder("ResNet-9-Tiny-ImageNet", data_format)
            .input(shape)
            .conv2d(64, 3, 1, 1, False, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
            .conv2d(128, 3, 1, 1, False, "conv2").batchnorm(name="bn2").activation("relu", "relu2")
            .maxpool2d(2, 2, 0, "pool1")
            .basic_residual_block(128, 128, 1, "res1")
            .conv2d(256, 3, 1, 1, False, "conv3").batchnorm(name="bn3").activation("relu", "relu3")
            .maxpool2d(2, 2, 0, "pool2")
            .basic_residual_block(256, 256, 1, "res2")
            .conv2d(512, 3, 1, 1, False, "conv4").batchnorm(name="bn4").activation("relu", "relu4")
            .maxpool2d(2, 2, 0, "pool3")
            .basic_residual_block(512, 512, 1, "res3")
            .avgpool2d(4, 1, 0, "avgpool")
            .flatten("flatten")
            .dense(200, True, "fc")
            .build())


def create_cnn_tiny_imagenet(data_format: str = "NCHW") -> Sequential:
    """VGG-style Tiny-ImageNet CNN (example_models.hpp:262-304)."""
    shape = (3, 64, 64) if data_format == "NCHW" else (64, 64, 3)
    b = (SequentialBuilder("cnn_tiny_imagenet", data_format)
         .input(shape)
         .conv2d(64, 3, 1, 1, False, "conv0").batchnorm(name="bn0").activation("relu", "relu0")
         .conv2d(64, 3, 1, 1, False, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
         .maxpool2d(2, 2, 0, "pool0")
         .conv2d(128, 3, 1, 1, False, "conv2").batchnorm(name="bn2").activation("relu", "relu2")
         .conv2d(128, 3, 1, 1, False, "conv3").batchnorm(name="bn3").activation("relu", "relu3")
         .maxpool2d(2, 2, 0, "pool1")
         .conv2d(256, 3, 1, 1, False, "conv4").batchnorm(name="bn5").activation("relu", "relu5")
         .conv2d(256, 3, 1, 1, False, "conv5").activation("relu", "relu6")
         .conv2d(256, 3, 1, 1, False, "conv6").batchnorm(name="bn6").activation("relu", "relu6b")
         .maxpool2d(2, 2, 0, "pool2")
         .conv2d(512, 3, 1, 1, False, "conv7").batchnorm(name="bn8").activation("relu", "relu7")
         .conv2d(512, 3, 1, 1, False, "conv8").batchnorm(name="bn9").activation("relu", "relu8")
         .conv2d(512, 3, 1, 1, False, "conv9").batchnorm(name="bn10").activation("relu", "relu9")
         .maxpool2d(2, 2, 0, "pool3")
         .flatten("flatten")
         .dense(1024, True, "fc0").activation("relu", "relu10")
         .dense(200, True, "fc1"))
    return b.build()


def create_resnet18_tiny_imagenet(data_format: str = "NCHW") -> Sequential:
    """ResNet-18 Tiny-ImageNet — the north-star benchmark model
    (example_models.hpp:306-332): 32-channel stem with BN eps 1e-3, maxpool,
    4 stages of basic blocks (64/128/256/512), avgpool-4, fc-200."""
    shape = (3, 64, 64) if data_format == "NCHW" else (64, 64, 3)
    return (SequentialBuilder("ResNet-18-Tiny-ImageNet", data_format)
            .input(shape)
            .conv2d(32, 3, 1, 1, False, "conv1")
            .batchnorm(1e-3, 0.1, True, "bn1")
            .activation("relu", "relu1")
            .maxpool2d(2, 2, 0, "maxpool")
            .basic_residual_block(32, 64, 1, "layer1_block1")
            .basic_residual_block(64, 64, 1, "layer1_block2")
            .basic_residual_block(64, 128, 2, "layer2_block1")
            .basic_residual_block(128, 128, 1, "layer2_block2")
            .basic_residual_block(128, 256, 2, "layer3_block1")
            .basic_residual_block(256, 256, 1, "layer3_block2")
            .basic_residual_block(256, 512, 2, "layer4_block1")
            .basic_residual_block(512, 512, 1, "layer4_block2")
            .avgpool2d(4, 1, 0, "avgpool")
            .flatten("flatten")
            .dense(200, True, "fc")
            .build())


def create_resnet34_tiny_imagenet(data_format: str = "NCHW") -> Sequential:
    """ResNet-34 Tiny-ImageNet (example_models.hpp:334-367)."""
    shape = (3, 64, 64) if data_format == "NCHW" else (64, 64, 3)
    b = (SequentialBuilder("ResNet-34-Tiny-ImageNet", data_format)
         .input(shape)
         .conv2d(32, 3, 1, 1, False, "conv1")
         .batchnorm(1e-3, 0.1, True, "bn1")
         .activation("relu", "relu1")
         .maxpool2d(2, 2, 0, "maxpool"))
    b.basic_residual_block(32, 64, 1, "layer1_block1")
    for i in (2, 3):
        b.basic_residual_block(64, 64, 1, f"layer1_block{i}")
    b.basic_residual_block(64, 128, 2, "layer2_block1")
    for i in (2, 3, 4):
        b.basic_residual_block(128, 128, 1, f"layer2_block{i}")
    b.basic_residual_block(128, 256, 2, "layer3_block1")
    for i in (2, 3, 4, 5, 6):
        b.basic_residual_block(256, 256, 1, f"layer3_block{i}")
    b.basic_residual_block(256, 512, 2, "layer4_block1")
    for i in (2, 3):
        b.basic_residual_block(512, 512, 1, f"layer4_block{i}")
    return (b.avgpool2d(4, 1, 0, "avgpool").flatten("flatten")
            .dense(200, True, "fc").build())


def create_resnet50_tiny_imagenet(data_format: str = "NCHW") -> Sequential:
    """ResNet-50 Tiny-ImageNet (example_models.hpp:369-402)."""
    shape = (3, 64, 64) if data_format == "NCHW" else (64, 64, 3)
    b = (SequentialBuilder("ResNet-50-Tiny-ImageNet", data_format)
         .input(shape)
         .conv2d(64, 3, 1, 1, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
         .maxpool2d(3, 2, 1, "maxpool"))
    _resnet50_body(b, 64)
    return (b.avgpool2d(4, 1, 0, "avgpool").flatten("flatten")
            .dense(200, True, "fc").build())


def create_resnet50_imagenet(data_format: str = "NCHW") -> Sequential:
    """ResNet-50 ImageNet-1k (example_models.hpp:404-437)."""
    shape = (3, 224, 224) if data_format == "NCHW" else (224, 224, 3)
    b = (SequentialBuilder("ResNet-50-ImageNet", data_format)
         .input(shape)
         .conv2d(64, 7, 2, 3, True, "conv1").batchnorm(name="bn1").activation("relu", "relu1")
         .maxpool2d(3, 2, 1, "maxpool"))
    _resnet50_body(b, 64)
    return (b.avgpool2d(7, 1, 0, "avgpool").flatten("flatten")
            .dense(1000, True, "fc").build())


def create_mha_classifier(data_format: str = "NCHW") -> Sequential:
    """Small self-attention sequence classifier: 2 MHA blocks + dense head
    on (S=32, E=64) inputs. No reference analog (the reference is CNN-only,
    SURVEY.md §5.7) — this makes the long-context subsystem a first-class
    zoo citizen: built by the factory, trainable by the Trainer,
    checkpointable, and pipeline-splittable like every CNN model.
    ``data_format`` is accepted for zoo-signature uniformity and ignored."""
    from ..nn.attention_layer import MultiHeadAttentionLayer
    from ..nn.residual import ResidualBlock

    def attn_block(name: str) -> ResidualBlock:
        # out = relu(attn(x) + x): the residual keeps token identity intact
        # (without it, two stacked softmax mixes average per-token features
        # toward the sequence mean and the head sees almost no per-example
        # signal — measured logits-std over a batch of 5e-4)
        return ResidualBlock(
            layers=[MultiHeadAttentionLayer(num_heads=4, impl="flash",
                                            name=f"{name}_mha")],
            shortcut=[], activation="relu", name=name)

    return (SequentialBuilder("mha_classifier")
            .input((32, 64))
            .add_layer(attn_block("attn0"))
            .add_layer(attn_block("attn1"))
            .flatten("flatten")
            .dense(10, True, "head")
            .build())


def _create_mha_decoder(data_format: str = "NCHW"):
    """Causal decoder for generative serving (models/decoder.py) — lazy
    import so the zoo stays importable without pulling the decode stack."""
    from .decoder import create_mha_decoder
    return create_mha_decoder(data_format)


# zoo values are Sequential factories with one exception: "mha_decoder"
# builds models.decoder.MHADecoder — token input + per-layer KV state
# don't fit the (B, *input_shape) float Sequential contract, but the
# generative-serving stack (serve/decode.py) still deserves a factory
# entry discoverable next to its classifier siblings.
MODEL_ZOO: Dict[str, Callable[..., Sequential]] = {
    "mnist_cnn": create_mnist_trainer,
    "cifar10_cnn_v1": create_cifar10_trainer_v1,
    "cifar10_cnn_v2": create_cifar10_trainer_v2,
    "cnn_cifar100": create_cnn_cifar100,
    "resnet9_cifar10": create_resnet9_cifar10,
    "resnet18_cifar10": create_resnet18_cifar10,
    "resnet20_cifar10": create_resnet20_cifar10,
    "resnet50_cifar10": create_resnet50_cifar10,
    "resnet9_tiny_imagenet": create_resnet9_tiny_imagenet,
    "cnn_tiny_imagenet": create_cnn_tiny_imagenet,
    "resnet18_tiny_imagenet": create_resnet18_tiny_imagenet,
    "resnet34_tiny_imagenet": create_resnet34_tiny_imagenet,
    "resnet50_tiny_imagenet": create_resnet50_tiny_imagenet,
    "resnet50_imagenet": create_resnet50_imagenet,
    "mha_classifier": create_mha_classifier,
    "mha_decoder": _create_mha_decoder,
}


def create_model(name: str, data_format: str = "NCHW") -> Sequential:
    if name not in MODEL_ZOO:
        raise ValueError(f"unknown model {name!r}; known: {sorted(MODEL_ZOO)}")
    return MODEL_ZOO[name](data_format)
