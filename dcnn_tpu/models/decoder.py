"""Causal decoder model for generative serving (ISSUE 20).

No reference analog (the reference is a CNN-only classifier framework,
SURVEY.md §5.7); this is the ``mha_classifier`` family grown one step: the
same ``MultiHeadAttentionLayer`` blocks with the same relu-residual wiring
(``out = relu(attn(x) + x)``), but causal, over a learned token embedding,
with a vocab-projection head — the smallest model whose serving shape is
*iterative* (one token per step, hundreds of steps per request) instead of
one-shot. That execution shape is the whole point: the continuous batcher
(``serve/decode.py``) and the paged KV cache (``serve/kvcache.py``) exist
to serve it.

Two forward paths, one parameter set:

- :meth:`MHADecoder.apply` — full-sequence causal forward ``(B, S)`` →
  ``(B, S, V)`` logits. The numerics oracle (naive materialized attention),
  used by training-shaped code and the decode-consistency tests;
- :meth:`MHADecoder.decode_step` — single-token forward against explicit
  per-layer K/V contexts (the serving hot path; the engine feeds it
  gathered KV pages). Per-row independent: a row's output depends only on
  that row's token/position/context, which is what makes continuous
  batching bit-stable per sequence (``tests/test_decode.py``).

Kept out of ``Sequential`` deliberately: integer token input and per-layer
cache state don't fit the ``(B, *input_shape)`` float pipeline contract,
and wedging them in would cost more than the factory conveniences buy.
``get_config``/``from_config`` keep it checkpoint- and AOT-key-friendly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.precision import get_precision
from ..nn import initializers as init
from ..nn.attention_layer import MultiHeadAttentionLayer


class MHADecoder:
    """Tiny causal transformer decoder: embed → N × (causal MHA + relu
    residual) → vocab head. Greedy decode over it is deterministic, which
    the serving tests lean on (bit-identical replay per sequence)."""

    def __init__(self, vocab_size: int = 64, embed_dim: int = 64,
                 num_heads: int = 4, num_layers: int = 2,
                 max_seq_len: int = 64, use_bias: bool = True,
                 name: str = "mha_decoder"):
        if vocab_size < 2:
            raise ValueError(f"vocab_size must be >= 2, got {vocab_size}")
        if embed_dim % num_heads:
            raise ValueError(f"embed dim {embed_dim} not divisible by "
                             f"{num_heads} heads")
        self.name = name
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.num_heads = int(num_heads)
        self.num_layers = int(num_layers)
        self.max_seq_len = int(max_seq_len)
        self.use_bias = bool(use_bias)
        # naive impl: the materializing oracle — exact, and the decode
        # path's masking convention matches it term for term
        self.blocks: List[MultiHeadAttentionLayer] = [
            MultiHeadAttentionLayer(num_heads, embed_dim, causal=True,
                                    impl="naive", use_bias=use_bias,
                                    name=f"{name}_mha{i}")
            for i in range(num_layers)]

    # -- params --
    def init(self, key: jax.Array) -> Dict[str, Any]:
        keys = jax.random.split(key, self.num_layers + 3)
        e, v = self.embed_dim, self.vocab_size
        params: Dict[str, Any] = {
            "embed": init.kaiming_uniform(keys[0], (v, e), e),
            "head_w": init.kaiming_uniform(keys[1], (e, v), e),
            "head_b": init.zeros((v,)),
            "blocks": [],
        }
        for i, blk in enumerate(self.blocks):
            bp, _ = blk.init(keys[i + 2], (self.max_seq_len, e))
            params["blocks"].append(bp)
        return params

    # -- full-sequence oracle --
    def apply(self, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
        """Full causal forward: ``tokens (B, S)`` int32 → logits
        ``(B, S, V)``. The training-shaped path and the decode oracle."""
        x = jnp.take(params["embed"], tokens, axis=0)
        for blk, bp in zip(self.blocks, params["blocks"]):
            y, _ = blk.apply(bp, {}, x)
            x = jax.nn.relu(y + x)
        return (jnp.matmul(x, params["head_w"], precision=get_precision())
                + params["head_b"])

    # -- single-token serving path --
    def embed_tokens(self, params: Dict[str, Any],
                     tokens: jax.Array) -> jax.Array:
        """``(B,)`` int32 token ids → ``(B, E)`` embeddings."""
        return jnp.take(params["embed"], tokens, axis=0)

    def head(self, params: Dict[str, Any], x_t: jax.Array) -> jax.Array:
        """``(B, E)`` final hidden → ``(B, V)`` logits."""
        return (jnp.matmul(x_t, params["head_w"],
                           precision=get_precision()) + params["head_b"])

    def decode_dense(self, params: Dict[str, Any], x_t: jax.Array,
                     k_caches: Sequence[jax.Array],
                     v_caches: Sequence[jax.Array], positions: jax.Array,
                     ) -> Tuple[jax.Array, List[jax.Array], List[jax.Array]]:
        """Single-token decode through per-layer DENSE KV caches (each
        ``(B, T, E)``): write this token's K/V rows at ``positions``,
        attend over the prefix (current token included — the oracle's
        causal diagonal), relu-residual, head. Returns ``(logits,
        k_caches, v_caches)``. This is the un-paged reference for the
        serving engine's paged step (``serve/decode.py``), which does the
        same write → gather → attend dance against a shared page pool."""
        x = x_t
        new_k: List[jax.Array] = []
        new_v: List[jax.Array] = []
        for blk, bp, kc, vc in zip(self.blocks, params["blocks"],
                                   k_caches, v_caches):
            y, kc, vc = blk.decode(bp, {}, x, kc, vc, positions)
            x = jax.nn.relu(y + x)
            new_k.append(kc)
            new_v.append(vc)
        return self.head(params, x), new_k, new_v

    # -- config --
    def get_config(self) -> Dict[str, Any]:
        return {"type": "mha_decoder", "name": self.name,
                "vocab_size": self.vocab_size, "embed_dim": self.embed_dim,
                "num_heads": self.num_heads, "num_layers": self.num_layers,
                "max_seq_len": self.max_seq_len, "use_bias": self.use_bias}

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "MHADecoder":
        cfg = dict(cfg)
        cfg.pop("type", None)
        return cls(**cfg)

    def __repr__(self) -> str:
        return (f"MHADecoder({self.name!r}, vocab={self.vocab_size}, "
                f"embed={self.embed_dim}, heads={self.num_heads}, "
                f"layers={self.num_layers}, max_seq={self.max_seq_len})")


def create_mha_decoder(data_format: str = "NCHW") -> MHADecoder:
    """Zoo factory for the default small decoder. ``data_format`` is
    accepted for zoo-signature uniformity and ignored (token input)."""
    return MHADecoder()
