// Byte-shuffle filter (the transform at the heart of Blosc): for elements of
// size T, gather byte-plane i of every element contiguously —
// dst[i*n + j] = src[j*T + i]. Numeric arrays (exponent/sign bytes highly
// correlated across elements) compress far better after this transform;
// paired with zstd it fills the reference's BloscCompressor slot
// (include/pipeline/compression_impl/internal_compressor.hpp:5-15) with a
// TPU-host-native implementation. The inverse restores element order.

#include <cstdint>
#include <cstring>

extern "C" {

// n_bytes must be a multiple of typesize; returns -1 otherwise.
int dcnn_byte_shuffle(const std::uint8_t *src, std::uint8_t *dst,
                      std::int64_t n_bytes, std::int32_t typesize) {
  if (typesize <= 0 || n_bytes % typesize) return -1;
  const std::int64_t n = n_bytes / typesize;
  for (std::int32_t i = 0; i < typesize; ++i) {
    const std::uint8_t *s = src + i;
    std::uint8_t *d = dst + std::int64_t(i) * n;
    for (std::int64_t j = 0; j < n; ++j) d[j] = s[j * typesize];
  }
  return 0;
}

int dcnn_byte_unshuffle(const std::uint8_t *src, std::uint8_t *dst,
                        std::int64_t n_bytes, std::int32_t typesize) {
  if (typesize <= 0 || n_bytes % typesize) return -1;
  const std::int64_t n = n_bytes / typesize;
  for (std::int32_t i = 0; i < typesize; ++i) {
    const std::uint8_t *s = src + std::int64_t(i) * n;
    std::uint8_t *d = dst + i;
    for (std::int64_t j = 0; j < n; ++j) d[j * typesize] = s[j];
  }
  return 0;
}

}  // extern "C"
