// LZ4 block-format codec (compress + decompress), implemented from the
// public LZ4 block specification. Fills the reference's Lz4hcCompressor slot
// (include/pipeline/compression_impl/internal_compressor.hpp:5-15) in the
// meta-compressor dispatch: same wire role (a fast byte codec behind a codec
// id), TPU-host-native implementation.
//
// The compressor is the classic greedy single-probe hash-table matcher
// (64 Ki entries). It emits streams any spec-conforming LZ4 block
// decompressor accepts: token = [lit-len nibble | match-len nibble], 15 in a
// nibble extends with 255-run bytes, match offset is 2 bytes little-endian,
// minimum match 4, final sequence is literals-only, and matches never start
// within the last 12 bytes (the spec's end-of-block rule for encoders).
// The decompressor accepts any conforming stream (it does not require the
// encoder-side end rules) and hard-checks every bound, returning -1 on
// malformed input rather than reading/writing out of range.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kMinMatch = 4;
constexpr std::int64_t kEndLiterals = 5;   // last 5 bytes must be literals
constexpr std::int64_t kMatchGuard = 12;   // no match may start in last 12
constexpr int kHashLog = 16;
constexpr std::int64_t kMaxOffset = 65535;

inline std::uint32_t read32(const std::uint8_t *p) {
  std::uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline std::uint32_t hash32(std::uint32_t v) {
  return (v * 2654435761u) >> (32 - kHashLog);
}

}  // namespace

extern "C" {

// Worst-case compressed size for n input bytes (token + 255-run literal
// length bytes + the literals themselves + terminator slack).
std::int64_t dcnn_lz4_compress_bound(std::int64_t n) {
  return n + n / 255 + 16;
}

// Compress src[0..n) into dst (capacity cap). Returns the compressed size,
// or -1 if dst is too small. n == 0 emits the canonical 1-byte empty block.
std::int64_t dcnn_lz4_compress(const std::uint8_t *src, std::int64_t n,
                               std::uint8_t *dst, std::int64_t cap) {
  std::vector<std::int64_t> table(std::size_t(1) << kHashLog, -1);
  std::int64_t ip = 0, anchor = 0, op = 0;
  const std::int64_t match_limit = n - kMatchGuard;  // may be negative
  const std::int64_t extend_limit = n - kEndLiterals;

  auto emit_run = [&](std::uint8_t *token, int shift, std::int64_t len) {
    // Encode len into the token nibble at `shift`, extending with 255-runs.
    if (len < 15) {
      *token |= std::uint8_t(len << shift);
    } else {
      *token |= std::uint8_t(15 << shift);
      len -= 15;
      while (len >= 255) { dst[op++] = 255; len -= 255; }
      dst[op++] = std::uint8_t(len);
    }
  };

  while (ip < match_limit) {
    const std::uint32_t h = hash32(read32(src + ip));
    const std::int64_t ref = table[h];
    table[h] = ip;
    if (ref < 0 || ip - ref > kMaxOffset || read32(src + ref) != read32(src + ip)) {
      ++ip;
      continue;
    }
    // Extend the match; stop so the last kEndLiterals bytes stay literal.
    std::int64_t mlen = kMinMatch;
    while (ip + mlen < extend_limit && src[ref + mlen] == src[ip + mlen]) ++mlen;
    const std::int64_t litlen = ip - anchor;
    if (op + 1 + litlen + litlen / 255 + 1 + 2 + mlen / 255 + 1 > cap) return -1;
    std::uint8_t *token = dst + op;
    *token = 0;
    ++op;
    emit_run(token, 4, litlen);
    std::memcpy(dst + op, src + anchor, std::size_t(litlen));
    op += litlen;
    const std::uint16_t off = std::uint16_t(ip - ref);
    dst[op++] = std::uint8_t(off & 0xff);
    dst[op++] = std::uint8_t(off >> 8);
    emit_run(token, 0, mlen - kMinMatch);
    // Seed the table inside the match so runs keep matching.
    if (ip + 2 < match_limit) table[hash32(read32(src + ip + 2))] = ip + 2;
    ip += mlen;
    anchor = ip;
  }

  // Final literals-only sequence.
  const std::int64_t litlen = n - anchor;
  if (op + 1 + litlen + litlen / 255 + 1 > cap) return -1;
  std::uint8_t *token = dst + op;
  *token = 0;
  ++op;
  emit_run(token, 4, litlen);
  std::memcpy(dst + op, src + anchor, std::size_t(litlen));
  op += litlen;
  return op;
}

// HC (high-compression) variant: hash-chain match search + one-byte lazy
// evaluation, the same algorithmic family as the reference's Lz4hc slot
// (include/pipeline/compression_impl/internal_compressor.hpp:10-15). Emits
// the identical block format — dcnn_lz4_decompress reads both — so the codec
// id on the wire is unchanged; only the encoder-side search is deeper.
// `level` scales the chain-walk budget: attempts = 1 << clamp(level, 1, 13).
std::int64_t dcnn_lz4_compress_hc(const std::uint8_t *src, std::int64_t n,
                                  std::uint8_t *dst, std::int64_t cap,
                                  std::int32_t level) {
  if (level < 1) level = 1;
  if (level > 13) level = 13;
  const int max_attempts = 1 << level;

  // head[h]: most recent position with hash h. chain[p & 0xffff]: previous
  // position sharing p's hash. An entry for position p is only overwritten
  // by position p + 65536, which is outside every window that could still
  // reach p — so entries are always valid while reachable, and chains are
  // strictly decreasing (no cycles).
  std::vector<std::int64_t> head(std::size_t(1) << kHashLog, -1);
  std::vector<std::int64_t> chain(65536, -1);
  std::int64_t ip = 0, anchor = 0, op = 0, next_insert = 0;
  const std::int64_t match_limit = n - kMatchGuard;  // may be negative
  const std::int64_t extend_limit = n - kEndLiterals;

  auto insert_upto = [&](std::int64_t limit) {
    if (limit > match_limit) limit = match_limit;
    for (; next_insert < limit; ++next_insert) {
      const std::uint32_t h = hash32(read32(src + next_insert));
      chain[next_insert & 0xffff] = head[h];
      head[h] = next_insert;
    }
  };

  // Longest match for src[pos..] over the chain (nearest-first, so ties keep
  // the smallest offset). Returns 0 if nothing reaches kMinMatch.
  auto best_match = [&](std::int64_t pos, std::int64_t *best_ref) {
    std::int64_t best_len = 0;
    std::int64_t ref = head[hash32(read32(src + pos))];
    int tries = max_attempts;
    while (ref >= 0 && pos - ref <= kMaxOffset && tries-- > 0) {
      // quick reject: a candidate can only improve on best_len if it also
      // matches at the byte best_len — O(1) filter before the O(len) extend
      // (without it, low-entropy runs degrade to O(attempts × run_length))
      if (ref < pos && src[ref + best_len] == src[pos + best_len] &&
          read32(src + ref) == read32(src + pos)) {
        std::int64_t len = kMinMatch;
        while (pos + len < extend_limit && src[ref + len] == src[pos + len])
          ++len;
        if (len > best_len) {
          best_len = len;
          *best_ref = ref;
          if (pos + len >= extend_limit) break;  // cannot be beaten
        }
      }
      ref = chain[ref & 0xffff];
    }
    return best_len;
  };

  auto emit_run = [&](std::uint8_t *token, int shift, std::int64_t len) {
    if (len < 15) {
      *token |= std::uint8_t(len << shift);
    } else {
      *token |= std::uint8_t(15 << shift);
      len -= 15;
      while (len >= 255) { dst[op++] = 255; len -= 255; }
      dst[op++] = std::uint8_t(len);
    }
  };

  while (ip < match_limit) {
    insert_upto(ip + 1);
    std::int64_t ref = -1;
    std::int64_t mlen = best_match(ip, &ref);
    if (mlen == 0) {
      ++ip;
      continue;
    }
    // One-byte lazy evaluation: if starting one byte later yields a strictly
    // longer match, ship this byte as a literal and move on.
    while (ip + 1 < match_limit) {
      insert_upto(ip + 2);
      std::int64_t ref2 = -1;
      const std::int64_t mlen2 = best_match(ip + 1, &ref2);
      if (mlen2 > mlen) {
        ++ip;
        mlen = mlen2;
        ref = ref2;
      } else {
        break;
      }
    }
    const std::int64_t litlen = ip - anchor;
    if (op + 1 + litlen + litlen / 255 + 1 + 2 + mlen / 255 + 1 > cap)
      return -1;
    std::uint8_t *token = dst + op;
    *token = 0;
    ++op;
    emit_run(token, 4, litlen);
    std::memcpy(dst + op, src + anchor, std::size_t(litlen));
    op += litlen;
    const std::uint16_t off = std::uint16_t(ip - ref);
    dst[op++] = std::uint8_t(off & 0xff);
    dst[op++] = std::uint8_t(off >> 8);
    emit_run(token, 0, mlen - kMinMatch);
    insert_upto(ip + mlen);  // full interior insertion (the HC ratio lever)
    ip += mlen;
    anchor = ip;
  }

  const std::int64_t litlen = n - anchor;
  if (op + 1 + litlen + litlen / 255 + 1 > cap) return -1;
  std::uint8_t *token = dst + op;
  *token = 0;
  ++op;
  emit_run(token, 4, litlen);
  std::memcpy(dst + op, src + anchor, std::size_t(litlen));
  op += litlen;
  return op;
}

// Decompress src[0..n) into dst (capacity cap = exact raw size known from
// the frame header). Returns bytes written, or -1 on malformed input.
std::int64_t dcnn_lz4_decompress(const std::uint8_t *src, std::int64_t n,
                                 std::uint8_t *dst, std::int64_t cap) {
  std::int64_t ip = 0, op = 0;
  while (ip < n) {
    const std::uint8_t token = src[ip++];
    std::int64_t litlen = token >> 4;
    if (litlen == 15) {
      std::uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        litlen += b;
      } while (b == 255);
    }
    if (litlen > n - ip || litlen > cap - op) return -1;
    std::memcpy(dst + op, src + ip, std::size_t(litlen));
    ip += litlen;
    op += litlen;
    if (ip >= n) break;  // literals-only terminator
    if (n - ip < 2) return -1;
    const std::int64_t offset = src[ip] | (std::int64_t(src[ip + 1]) << 8);
    ip += 2;
    if (offset == 0 || offset > op) return -1;
    std::int64_t mlen = token & 15;
    if (mlen == 15) {
      std::uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        mlen += b;
      } while (b == 255);
    }
    mlen += kMinMatch;
    if (mlen > cap - op) return -1;
    // Byte-wise copy: offsets < mlen legitimately overlap (RLE encoding).
    for (std::int64_t i = 0; i < mlen; ++i, ++op) dst[op] = dst[op - offset];
  }
  return op;
}

}  // extern "C"
