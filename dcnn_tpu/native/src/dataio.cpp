// Native data-loading kernels for the host side of the TPU input pipeline.
//
// Reference equivalent: the reference's data layer is C++ throughout
// (include/data_loading/*.hpp, src/data_loading/) — CSV parsing, binary
// decode, normalization all native. Feeding a TPU slice moves the bottleneck
// entirely onto the host input pipeline (SURVEY.md §7 hard part 5), so the
// decode/normalize path is native here too: one pass over the bytes,
// chunk-parallel across std::thread workers, writing float32 directly into
// the caller's (numpy) buffer.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC -pthread
//        dataio.cpp -o libdcnn_native.so

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

unsigned hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 1 : n;
}

// Run fn(chunk_index) over [0, chunks) on up to hw_threads() workers.
template <typename F>
void parallel_chunks(std::size_t chunks, F fn) {
  unsigned workers = std::min<std::size_t>(hw_threads(), chunks);
  if (workers <= 1) {
    for (std::size_t i = 0; i < chunks; ++i) fn(i);
    return;
  }
  std::atomic<std::size_t> next{0};
  std::vector<std::thread> pool;
  pool.reserve(workers);
  for (unsigned w = 0; w < workers; ++w) {
    pool.emplace_back([&] {
      for (;;) {
        std::size_t i = next.fetch_add(1);
        if (i >= chunks) return;
        fn(i);
      }
    });
  }
  for (auto &t : pool) t.join();
}

}  // namespace

extern "C" {

// u8 → f32 with scale (the /255 normalize): dst[i] = src[i] * scale.
void dcnn_u8_to_f32(const std::uint8_t *src, float *dst, std::int64_t n,
                    float scale) {
  const std::int64_t chunk = 1 << 20;
  const std::int64_t chunks = (n + chunk - 1) / chunk;
  parallel_chunks(static_cast<std::size_t>(chunks), [&](std::size_t c) {
    const std::int64_t lo = static_cast<std::int64_t>(c) * chunk;
    const std::int64_t hi = std::min(n, lo + chunk);
    for (std::int64_t i = lo; i < hi; ++i)
      dst[i] = static_cast<float>(src[i]) * scale;
  });
}

// Decode CIFAR-style records: n records of
//   [skip_bytes label bytes][img_bytes pixels], label at index label_index.
// Writes normalized float images (img_bytes floats per record, scaled by
// 1/255) and int32 labels. Returns 0 on success.
int dcnn_decode_label_records(const std::uint8_t *raw, std::int64_t raw_len,
                              std::int64_t n, std::int32_t skip_bytes,
                              std::int32_t label_index, std::int64_t img_bytes,
                              float *out_images, std::int32_t *out_labels) {
  const std::int64_t rec = skip_bytes + img_bytes;
  if (raw_len < n * rec) return 1;
  parallel_chunks(static_cast<std::size_t>(n), [&](std::size_t i) {
    const std::uint8_t *r = raw + static_cast<std::int64_t>(i) * rec;
    out_labels[i] = static_cast<std::int32_t>(r[label_index]);
    float *dst = out_images + static_cast<std::int64_t>(i) * img_bytes;
    const std::uint8_t *px = r + skip_bytes;
    for (std::int64_t j = 0; j < img_bytes; ++j)
      dst[j] = static_cast<float>(px[j]) * (1.0f / 255.0f);
  });
  return 0;
}

// Parse a label,pix0,...,pixK CSV (MNIST format). `text` need not be
// NUL-terminated; newlines delimit rows; the first row is skipped when
// `skip_header` != 0. Rows are located serially (newline scan), parsed in
// parallel. Returns the number of rows parsed, or -1 on malformed input.
std::int64_t dcnn_parse_label_csv(const char *text, std::int64_t len,
                                  std::int32_t pixels_per_row,
                                  std::int32_t skip_header, float scale,
                                  std::int64_t max_rows, float *out_pixels,
                                  std::int32_t *out_labels) {
  // index row start offsets
  std::vector<std::int64_t> starts;
  starts.reserve(1 << 16);
  std::int64_t pos = 0;
  bool first = true;
  while (pos < len && static_cast<std::int64_t>(starts.size()) < max_rows) {
    std::int64_t eol = pos;
    while (eol < len && text[eol] != '\n') ++eol;
    if (eol > pos) {
      if (first && skip_header) {
        first = false;
      } else {
        first = false;
        starts.push_back(pos);
      }
    }
    pos = eol + 1;
  }
  const std::int64_t rows = static_cast<std::int64_t>(starts.size());
  std::atomic<bool> ok{true};
  parallel_chunks(static_cast<std::size_t>(rows), [&](std::size_t r) {
    const char *p = text + starts[r];
    const char *end = text + len;
    // label
    std::int32_t label = 0;
    bool any = false;
    while (p < end && *p >= '0' && *p <= '9') {
      label = label * 10 + (*p - '0');
      ++p;
      any = true;
    }
    if (!any) { ok.store(false); return; }
    out_labels[r] = label;
    float *dst = out_pixels + static_cast<std::int64_t>(r) * pixels_per_row;
    for (std::int32_t j = 0; j < pixels_per_row; ++j) {
      if (p >= end || *p != ',') { ok.store(false); return; }
      ++p;  // comma
      std::int32_t v = 0;
      bool digit = false;
      while (p < end && *p >= '0' && *p <= '9') {
        v = v * 10 + (*p - '0');
        ++p;
        digit = true;
      }
      if (!digit) { ok.store(false); return; }
      dst[j] = static_cast<float>(v) * scale;
    }
    // The row must be fully consumed: extra columns mean the file does not
    // match the expected pixels_per_row layout — reject rather than silently
    // training on misaligned pixels.
    if (p < end && *p == '\r') ++p;
    if (p < end && *p != '\n') { ok.store(false); return; }
  });
  return ok.load() ? rows : -1;
}

}  // extern "C"
