// Chunk-parallel row gather: dst[i] = src[idx[i]] for arbitrary row sizes.
//
// The host side of the streaming feed (data/streaming.py) permutes the
// dataset every epoch and gathers each shard's rows with numpy fancy
// indexing — a single-threaded memcpy loop that costs real wall time on the
// multi-MB uint8 shards the transfer engine ships (data/transfer.py). This
// kernel is the same gather, blocked over rows and spread across hardware
// threads, writing straight into the caller's (numpy) destination buffer.
// Dtype-agnostic: rows are opaque byte spans (row_bytes = itemsize *
// trailing-dim product), so one symbol serves uint8 images and int32 labels
// alike. Bit-identical to src[idx] by construction (pure memcpy).
//
// Exposed as a plain C ABI for ctypes, like dataio.cpp.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

unsigned gather_hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 1 : n;
}

// Run fn(block_index) over [0, blocks) on up to hw threads (work-stealing
// counter, same shape as dataio.cpp's parallel_chunks — duplicated here
// because that helper lives in dataio.cpp's anonymous namespace).
template <typename F>
void gather_parallel(std::size_t blocks, F fn) {
  unsigned workers = std::min<std::size_t>(gather_hw_threads(), blocks);
  if (workers <= 1) {
    for (std::size_t i = 0; i < blocks; ++i) fn(i);
    return;
  }
  std::atomic<std::size_t> next{0};
  std::vector<std::thread> pool;
  pool.reserve(workers);
  for (unsigned w = 0; w < workers; ++w) {
    pool.emplace_back([&] {
      for (;;) {
        std::size_t i = next.fetch_add(1);
        if (i >= blocks) return;
        fn(i);
      }
    });
  }
  for (auto &t : pool) t.join();
}

}  // namespace

extern "C" {

// Gather n_out rows of row_bytes each: dst[i*row_bytes ..] =
// src[idx[i]*row_bytes ..]. Returns 0 on success, -1 if any index falls
// outside [0, n_src) — checked before any byte is written, so a failed call
// leaves dst untouched.
int dcnn_gather_rows(const std::uint8_t *src, const std::int64_t *idx,
                     std::uint8_t *dst, std::int64_t n_out,
                     std::int64_t row_bytes, std::int64_t n_src) {
  if (n_out < 0 || row_bytes <= 0) return -1;
  std::atomic<bool> ok{true};
  // validate first (cheap scan) so partial output can never alias a failure
  gather_parallel(static_cast<std::size_t>((n_out + 65535) / 65536),
                  [&](std::size_t b) {
    const std::int64_t lo = static_cast<std::int64_t>(b) << 16;
    const std::int64_t hi = std::min(n_out, lo + 65536);
    for (std::int64_t i = lo; i < hi; ++i)
      if (idx[i] < 0 || idx[i] >= n_src) { ok.store(false); return; }
  });
  if (!ok.load()) return -1;
  // block rows so each task moves ~1 MiB — enough to amortize thread
  // handoff, small enough to load-balance ragged index distributions
  std::int64_t rows_per_block = (1 << 20) / row_bytes;
  if (rows_per_block < 1) rows_per_block = 1;
  const std::int64_t blocks = (n_out + rows_per_block - 1) / rows_per_block;
  gather_parallel(static_cast<std::size_t>(blocks), [&](std::size_t b) {
    const std::int64_t lo = static_cast<std::int64_t>(b) * rows_per_block;
    const std::int64_t hi = std::min(n_out, lo + rows_per_block);
    for (std::int64_t i = lo; i < hi; ++i)
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                  static_cast<std::size_t>(row_bytes));
  });
  return 0;
}

}  // extern "C"
