#!/usr/bin/env bash
# ASan/UBSan build of the native host kernels + the sanitize test driver.
#
# The production .so is built by dcnn_tpu/native/__init__.py with -O3 and
# no instrumentation; this target is the "debug build" twin the reference
# framework got from ENABLE_DEBUG -> -fsanitize: every gather / shuffle /
# LZ4 / dataio round-trip in sanitize/main.cpp runs with AddressSanitizer
# and UndefinedBehaviorSanitizer aborting on the first violation.
#
# Usage:
#   native/build_sanitized.sh [output-binary]     # build only
#   native/build_sanitized.sh --run [output]      # build, then run
#
# Exit codes: 0 built (and, with --run, ran clean); 2 no usable compiler /
# sanitizer runtime (callers — the slow test — treat 2 as "skip").
set -euo pipefail
cd "$(dirname "$0")"

RUN=0
if [[ "${1:-}" == "--run" ]]; then
  RUN=1
  shift
fi
OUT="${1:-sanitize/dcnn_sanitize_test}"
CXX="${CXX:-g++}"

if ! command -v "$CXX" >/dev/null 2>&1; then
  echo "build_sanitized: no C++ compiler ($CXX) on PATH" >&2
  exit 2
fi

# probe: some minimal images ship g++ without libasan/libubsan — that is a
# skip, not a failure
probe="$(mktemp -d)"
trap 'rm -rf "$probe"' EXIT
echo 'int main(){return 0;}' > "$probe/p.cpp"
if ! "$CXX" -fsanitize=address,undefined "$probe/p.cpp" -o "$probe/p" \
    >/dev/null 2>&1; then
  echo "build_sanitized: $CXX cannot link the sanitizer runtimes" >&2
  exit 2
fi

mkdir -p "$(dirname "$OUT")"
"$CXX" -std=c++17 -g -O1 -fno-omit-frame-pointer \
  -fsanitize=address,undefined -fno-sanitize-recover=all \
  -pthread src/*.cpp sanitize/main.cpp -o "$OUT"
echo "built $OUT (ASan+UBSan)"

if [[ "$RUN" == 1 ]]; then
  case "$OUT" in
    /*) BIN="$OUT" ;;
    *) BIN="./$OUT" ;;
  esac
  ASAN_OPTIONS="${ASAN_OPTIONS:-detect_leaks=1}" \
  UBSAN_OPTIONS="${UBSAN_OPTIONS:-print_stacktrace=1}" \
  "$BIN"
fi
