"""Native (C++) host-side kernels with ctypes bindings.

Reference equivalent: the reference's entire data layer is native C++
(``include/data_loading/``); here native code accelerates the host input
pipeline that feeds the TPU — CSV parse, label-record decode, u8→f32
normalize — chunk-parallel over hardware threads (``src/dataio.cpp``).

``lib()`` returns the loaded library, building it with g++ on first use
(cached as ``libdcnn_native.so`` next to this file). Every consumer must
fall back to the numpy path when ``available()`` is False — the framework
never hard-requires the toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_DIR, "src")
_SO = os.path.join(_DIR, "libdcnn_native.so")


def _sources() -> list:
    try:
        return sorted(
            os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
            if f.endswith(".cpp"))
    except OSError:
        return []

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # Compile to a process-unique temp path and rename into place: rename is
    # atomic, so concurrent first-use builds (multihost spawns N identical
    # processes) can never CDLL a partially written .so.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           "-pthread", *_sources(), "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    srcs = _sources()
    have_src = bool(srcs)
    stale = (have_src and os.path.isfile(_SO)
             and os.path.getmtime(_SO) < max(os.path.getmtime(s) for s in srcs))
    if not os.path.isfile(_SO) or stale:
        if not have_src or not _build():
            _build_failed = True
            return None
    try:
        l = ctypes.CDLL(_SO)
    except OSError:
        _build_failed = True
        return None
    l.dcnn_u8_to_f32.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_float]
    l.dcnn_u8_to_f32.restype = None
    l.dcnn_decode_label_records.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32)]
    l.dcnn_decode_label_records.restype = ctypes.c_int
    l.dcnn_parse_label_csv.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_float, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32)]
    l.dcnn_parse_label_csv.restype = ctypes.c_int64
    # A prebuilt .so from before lz4codec.cpp existed may lack these symbols
    # (e.g. deployed without src/, defeating the mtime staleness check) —
    # degrade to "lz4 unavailable" rather than failing lib() entirely.
    if hasattr(l, "dcnn_lz4_compress"):
        for fn in ("dcnn_lz4_compress", "dcnn_lz4_decompress"):
            getattr(l, fn).argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
            getattr(l, fn).restype = ctypes.c_int64
        l.dcnn_lz4_compress_bound.argtypes = [ctypes.c_int64]
        l.dcnn_lz4_compress_bound.restype = ctypes.c_int64
    if hasattr(l, "dcnn_lz4_compress_hc"):
        l.dcnn_lz4_compress_hc.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int32]
        l.dcnn_lz4_compress_hc.restype = ctypes.c_int64
    if hasattr(l, "dcnn_byte_shuffle"):
        for fn in ("dcnn_byte_shuffle", "dcnn_byte_unshuffle"):
            getattr(l, fn).argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64, ctypes.c_int32]
            getattr(l, fn).restype = ctypes.c_int
    # gather.cpp postdates some deployed .so builds — same degrade-gracefully
    # treatment as the lz4 symbols
    if hasattr(l, "dcnn_gather_rows"):
        l.dcnn_gather_rows.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64]
        l.dcnn_gather_rows.restype = ctypes.c_int
    _lib = l
    return _lib


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def byte_shuffle(data: bytes, typesize: int,
                 inverse: bool = False) -> Optional[bytes]:
    """Blosc-style byte-plane (un)shuffle. None if the lib is unavailable;
    raises on length % typesize != 0."""
    l = lib()
    if l is None or not hasattr(l, "dcnn_byte_shuffle"):
        return None
    src = np.frombuffer(data, np.uint8)
    dst = np.empty(len(data), np.uint8)
    fn = l.dcnn_byte_unshuffle if inverse else l.dcnn_byte_shuffle
    if fn(_u8ptr(src), _u8ptr(dst), src.size, typesize) != 0:
        raise ValueError(f"byte_shuffle: {len(data)} % typesize {typesize}")
    return dst.tobytes()


def lz4_available() -> bool:
    l = lib()
    return l is not None and hasattr(l, "dcnn_lz4_compress")


def lz4_compress(data: bytes, level: int = 0) -> Optional[bytes]:
    """LZ4 block-format compress (native). ``level`` 0 = greedy single-probe
    matcher; >= 1 = HC hash-chain search (deeper with higher levels, same
    block format — the decoder cannot tell them apart). None if the lib is
    unavailable."""
    l = lib()
    if l is None or not hasattr(l, "dcnn_lz4_compress"):
        return None
    src = np.frombuffer(data, np.uint8)
    dst = np.empty(int(l.dcnn_lz4_compress_bound(len(data))), np.uint8)
    if level > 0:
        if not hasattr(l, "dcnn_lz4_compress_hc"):
            # never silently downgrade a requested HC level to greedy (a
            # prebuilt .so deployed without src/ can lack the symbol)
            raise RuntimeError(
                "lz4 HC level requested but libdcnn_native.so predates the "
                "HC encoder — rebuild it (delete the .so next to "
                "dcnn_tpu/native and re-import with src/ present)")
        n = l.dcnn_lz4_compress_hc(_u8ptr(src), src.size, _u8ptr(dst),
                                   dst.size, level)
    else:
        n = l.dcnn_lz4_compress(_u8ptr(src), src.size, _u8ptr(dst), dst.size)
    if n < 0:
        raise ValueError("lz4 compress: destination bound overflow")
    return dst[:n].tobytes()


def lz4_decompress(data: bytes, raw_size: int) -> Optional[bytes]:
    """LZ4 block-format decompress into exactly raw_size bytes (native).
    None if the lib is unavailable; raises on malformed input."""
    l = lib()
    if l is None or not hasattr(l, "dcnn_lz4_decompress"):
        return None
    src = np.frombuffer(data, np.uint8)
    dst = np.empty(raw_size, np.uint8)
    n = l.dcnn_lz4_decompress(_u8ptr(src), src.size, _u8ptr(dst), raw_size)
    if n != raw_size:
        raise ValueError(f"lz4 decompress: malformed stream (rc={n})")
    return dst.tobytes()


def available() -> bool:
    return lib() is not None


def gather_available() -> bool:
    l = lib()
    return l is not None and hasattr(l, "dcnn_gather_rows")


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Row gather ``src[idx]`` — chunk-parallel native memcpy when the
    library is available, numpy fancy indexing otherwise. Bit-identical to
    ``src[idx]`` either way (the kernel is a pure per-row memcpy), which the
    streaming feed's numerics-parity guarantee depends on. Indices must be
    in ``[0, len(src))`` — negatives raise IndexError on BOTH paths (the
    native kernel cannot wrap, and allowing numpy wrap-around only in the
    fallback would make behavior toolchain-dependent)."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, np.int64)
    if idx.ndim != 1:
        raise ValueError(f"gather_rows needs a 1-D index, got {idx.ndim}-D")
    if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= src.shape[0]):
        raise IndexError(
            f"gather_rows: index out of range [0, {src.shape[0]})")
    l = lib()
    if l is None or not hasattr(l, "dcnn_gather_rows") or src.ndim == 0:
        return src[idx]
    row_bytes = src.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if row_bytes == 0:  # zero-size trailing dims: nothing to copy natively
        return src[idx]
    dst = np.empty((idx.size, *src.shape[1:]), src.dtype)
    rc = l.dcnn_gather_rows(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        idx.size, row_bytes, src.shape[0])
    if rc != 0:
        raise IndexError(
            f"gather_rows: index out of range for axis 0 of size "
            f"{src.shape[0]}")
    return dst


def u8_to_f32(src: np.ndarray, scale: float = 1.0 / 255.0) -> np.ndarray:
    """Normalize a uint8 array to float32 (native if possible)."""
    src = np.ascontiguousarray(src, np.uint8)
    l = lib()
    if l is None:
        return src.astype(np.float32) * scale
    dst = np.empty(src.shape, np.float32)
    l.dcnn_u8_to_f32(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.size, scale)
    return dst


def decode_label_records(raw: np.ndarray, n: int, skip_bytes: int,
                         label_index: int, img_bytes: int):
    """Decode n ``[labels…][pixels…]`` records → (images f32 scaled 1/255,
    labels int32). Returns None if the native library is unavailable."""
    l = lib()
    if l is None:
        return None
    raw = np.ascontiguousarray(raw, np.uint8)
    images = np.empty((n, img_bytes), np.float32)
    labels = np.empty((n,), np.int32)
    rc = l.dcnn_decode_label_records(
        raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), raw.size, n,
        skip_bytes, label_index, img_bytes,
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        raise ValueError("record buffer too small for requested decode")
    return images, labels


def parse_label_csv(path: str, pixels_per_row: int, skip_header: bool = True,
                    scale: float = 1.0 / 255.0):
    """Parse a ``label,pix…`` CSV → (pixels f32 scaled, labels int32), or
    None if the native library is unavailable."""
    l = lib()
    if l is None:
        return None
    with open(path, "rb") as f:
        text = f.read()
    # upper bound on rows: number of newlines + 1
    max_rows = text.count(b"\n") + 1
    pixels = np.empty((max_rows, pixels_per_row), np.float32)
    labels = np.empty((max_rows,), np.int32)
    rows = l.dcnn_parse_label_csv(
        text, len(text), pixels_per_row, 1 if skip_header else 0, scale,
        max_rows,
        pixels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rows < 0:
        # The fast parser only accepts integer pixels (the MNIST CSV format);
        # anything else (float pixels, padded commas) defers to the tolerant
        # numpy fallback in the caller rather than rejecting the file.
        return None
    return pixels[:rows].copy(), labels[:rows].copy()
