// Sanitizer test driver for the native host kernels.
//
// Built by build_sanitized.sh with -fsanitize=address,undefined
// -fno-sanitize-recover=all: any heap overflow, use-after-free, misaligned
// access, or signed overflow in gather/shuffle/lz4/dataio aborts the
// binary, so "exit 0" means the round-trips below ran clean under both
// sanitizers. The Python test (tests/test_native_sanitized.py, slow tier)
// builds and runs this; it is deliberately a standalone C++ main rather
// than an LD_PRELOAD'd Python process — preloading libasan under CPython
// drowns the signal in interpreter-allocator noise.
//
// Coverage mirrors the ctypes surface dcnn_tpu/native/__init__.py binds:
//   - dcnn_gather_rows: round-trip vs a scalar reference gather, the
//     out-of-range-index reject path (dst must stay untouched), and the
//     ragged row_bytes > 1 MiB blocking path.
//   - dcnn_byte_shuffle / unshuffle: inverse round-trip for typesizes
//     1/2/4/8, reject path for misaligned n_bytes.
//   - dcnn_lz4_compress(+bound) / _hc / decompress: bit-exact round-trip
//     on compressible and incompressible payloads, every HC level edge,
//     and malformed/truncated streams (must return an error, not read
//     out of bounds).
//   - dcnn_u8_to_f32, dcnn_decode_label_records, dcnn_parse_label_csv:
//     value spot-checks + the short-buffer reject paths.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
int dcnn_gather_rows(const std::uint8_t *src, const std::int64_t *idx,
                     std::uint8_t *dst, std::int64_t n_out,
                     std::int64_t row_bytes, std::int64_t n_src);
int dcnn_byte_shuffle(const std::uint8_t *src, std::uint8_t *dst,
                      std::int64_t n_bytes, std::int32_t typesize);
int dcnn_byte_unshuffle(const std::uint8_t *src, std::uint8_t *dst,
                        std::int64_t n_bytes, std::int32_t typesize);
std::int64_t dcnn_lz4_compress_bound(std::int64_t n);
std::int64_t dcnn_lz4_compress(const std::uint8_t *src, std::int64_t n,
                               std::uint8_t *dst, std::int64_t cap);
std::int64_t dcnn_lz4_compress_hc(const std::uint8_t *src, std::int64_t n,
                                  std::uint8_t *dst, std::int64_t cap,
                                  std::int32_t level);
std::int64_t dcnn_lz4_decompress(const std::uint8_t *src, std::int64_t n,
                                 std::uint8_t *dst, std::int64_t raw_size);
void dcnn_u8_to_f32(const std::uint8_t *src, float *dst, std::int64_t n,
                    float scale);
int dcnn_decode_label_records(const std::uint8_t *raw, std::int64_t raw_len,
                              std::int64_t n, std::int32_t skip_bytes,
                              std::int32_t label_index, std::int64_t img_bytes,
                              float *out_images, std::int32_t *out_labels);
std::int64_t dcnn_parse_label_csv(const char *text, std::int64_t len,
                                  std::int32_t pixels_per_row,
                                  std::int32_t skip_header, float scale,
                                  std::int64_t max_rows, float *out_pixels,
                                  std::int32_t *out_labels);
}

namespace {

int failures = 0;

#define CHECK(cond, what)                                                 \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, what); \
      ++failures;                                                         \
    }                                                                     \
  } while (0)

// deterministic xorshift so runs are reproducible without <random> weight
std::uint64_t rng_state = 0x9e3779b97f4a7c15ull;
std::uint64_t next_u64() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return rng_state;
}

std::vector<std::uint8_t> random_bytes(std::size_t n) {
  std::vector<std::uint8_t> out(n);
  for (std::size_t i = 0; i < n; ++i)
    out[i] = static_cast<std::uint8_t>(next_u64());
  return out;
}

void test_gather() {
  const std::int64_t n_src = 513, row_bytes = 37, n_out = 257;
  auto src = random_bytes(static_cast<std::size_t>(n_src * row_bytes));
  std::vector<std::int64_t> idx(n_out);
  for (std::int64_t i = 0; i < n_out; ++i)
    idx[i] = static_cast<std::int64_t>(next_u64() % n_src);
  std::vector<std::uint8_t> dst(static_cast<std::size_t>(n_out * row_bytes));
  CHECK(dcnn_gather_rows(src.data(), idx.data(), dst.data(), n_out,
                         row_bytes, n_src) == 0, "gather rc");
  for (std::int64_t i = 0; i < n_out; ++i)
    CHECK(std::memcmp(dst.data() + i * row_bytes,
                      src.data() + idx[i] * row_bytes,
                      static_cast<std::size_t>(row_bytes)) == 0,
          "gather row mismatch");

  // out-of-range index: reject BEFORE writing anything
  std::vector<std::uint8_t> dst2(dst.size(), 0xAB);
  idx[n_out / 2] = n_src;  // one past the end
  CHECK(dcnn_gather_rows(src.data(), idx.data(), dst2.data(), n_out,
                         row_bytes, n_src) == -1, "gather oob rc");
  for (std::uint8_t b : dst2)
    CHECK(b == 0xAB, "gather oob wrote into dst");

  // row_bytes > the 1 MiB block target exercises rows_per_block == 1
  const std::int64_t big_row = (1 << 20) + 4097, big_n = 3;
  auto big_src = random_bytes(static_cast<std::size_t>(2 * big_row));
  std::int64_t big_idx[3] = {1, 0, 1};
  std::vector<std::uint8_t> big_dst(
      static_cast<std::size_t>(big_n * big_row));
  CHECK(dcnn_gather_rows(big_src.data(), big_idx, big_dst.data(), big_n,
                         big_row, 2) == 0, "gather big-row rc");
  CHECK(std::memcmp(big_dst.data(), big_src.data() + big_row,
                    static_cast<std::size_t>(big_row)) == 0,
        "gather big-row content");
}

void test_shuffle() {
  for (std::int32_t ts : {1, 2, 4, 8}) {
    const std::int64_t n = 64 * ts + 0;  // multiple of typesize
    auto src = random_bytes(static_cast<std::size_t>(n));
    std::vector<std::uint8_t> mid(src.size()), back(src.size());
    CHECK(dcnn_byte_shuffle(src.data(), mid.data(), n, ts) == 0,
          "shuffle rc");
    CHECK(dcnn_byte_unshuffle(mid.data(), back.data(), n, ts) == 0,
          "unshuffle rc");
    CHECK(std::memcmp(src.data(), back.data(),
                      static_cast<std::size_t>(n)) == 0,
          "shuffle round-trip");
  }
  std::uint8_t a[7] = {0}, b[7] = {0};
  CHECK(dcnn_byte_shuffle(a, b, 7, 4) == -1, "shuffle misaligned rc");
  CHECK(dcnn_byte_shuffle(a, b, 4, 0) == -1, "shuffle typesize 0 rc");
}

void lz4_round_trip(const std::vector<std::uint8_t> &raw, std::int32_t level,
                    const char *what) {
  const std::int64_t n = static_cast<std::int64_t>(raw.size());
  std::vector<std::uint8_t> comp(
      static_cast<std::size_t>(dcnn_lz4_compress_bound(n)));
  std::int64_t c = level > 0
      ? dcnn_lz4_compress_hc(raw.data(), n, comp.data(),
                             static_cast<std::int64_t>(comp.size()), level)
      : dcnn_lz4_compress(raw.data(), n, comp.data(),
                          static_cast<std::int64_t>(comp.size()));
  CHECK(c > 0, what);
  std::vector<std::uint8_t> back(raw.size());
  CHECK(dcnn_lz4_decompress(comp.data(), c, back.data(), n) == n, what);
  CHECK(std::memcmp(raw.data(), back.data(), raw.size()) == 0, what);

  // truncated stream: must error out, never read past the buffer (ASan
  // verifies the "never read past" half)
  if (c > 8) {
    std::vector<std::uint8_t> trunc(comp.begin(), comp.begin() + c / 2);
    std::int64_t rc = dcnn_lz4_decompress(trunc.data(),
                                          static_cast<std::int64_t>(
                                              trunc.size()),
                                          back.data(), n);
    CHECK(rc != n, "truncated stream decoded 'successfully'");
  }
}

void test_lz4() {
  // compressible: repeating structure with a sprinkle of noise
  std::vector<std::uint8_t> compressible(1 << 16);
  for (std::size_t i = 0; i < compressible.size(); ++i)
    compressible[i] = static_cast<std::uint8_t>((i / 64) & 0xFF);
  for (int lvl : {0, 1, 9, 12})
    lz4_round_trip(compressible, lvl, "lz4 compressible round-trip");
  // incompressible random payload (worst-case literal runs)
  lz4_round_trip(random_bytes(12345), 0, "lz4 random round-trip");
  lz4_round_trip(random_bytes(12345), 9, "lz4 hc random round-trip");
  // tiny payloads hit the min-match edge cases
  for (std::size_t n : {1u, 5u, 12u, 13u})
    lz4_round_trip(random_bytes(n), 0, "lz4 tiny round-trip");
  // n == 0: the canonical 1-byte empty block (stack buffers — an empty
  // std::vector's data() may be null, and memcpy(null, ..., 0) is the
  // exact UB class UBSan would pin on the DRIVER instead of the codec)
  std::uint8_t zin = 0, zout[16];
  std::int64_t zc = dcnn_lz4_compress(&zin, 0, zout, 16);
  CHECK(zc == 1, "empty block size");
  std::uint8_t zback = 0xCD;
  CHECK(dcnn_lz4_decompress(zout, zc, &zback, 0) == 0, "empty block decode");
  // garbage input to the decoder: error, not a crash
  auto junk = random_bytes(256);
  std::vector<std::uint8_t> out(1024);
  std::int64_t rc = dcnn_lz4_decompress(junk.data(), 256, out.data(), 1024);
  CHECK(rc != 1024 || true, "junk decode returned");  // no-crash is the test
}

void test_dataio() {
  auto src = random_bytes(4096 + 7);
  std::vector<float> dst(src.size());
  dcnn_u8_to_f32(src.data(), dst.data(),
                 static_cast<std::int64_t>(src.size()), 1.0f / 255.0f);
  for (std::size_t i = 0; i < src.size(); ++i)
    CHECK(dst[i] == static_cast<float>(src[i]) * (1.0f / 255.0f),
          "u8_to_f32 value");

  // CIFAR-style records: 2 label bytes (coarse, fine), label_index 1
  const std::int64_t n = 33, img = 3 * 8 * 8, rec = 2 + img;
  auto raw = random_bytes(static_cast<std::size_t>(n * rec));
  std::vector<float> images(static_cast<std::size_t>(n * img));
  std::vector<std::int32_t> labels(static_cast<std::size_t>(n));
  CHECK(dcnn_decode_label_records(raw.data(),
                                  static_cast<std::int64_t>(raw.size()), n,
                                  2, 1, img, images.data(),
                                  labels.data()) == 0, "decode rc");
  CHECK(labels[7] == raw[7 * rec + 1], "decode label");
  CHECK(images[img + 3] ==
        static_cast<float>(raw[rec + 2 + 3]) * (1.0f / 255.0f),
        "decode pixel");
  CHECK(dcnn_decode_label_records(raw.data(), n * rec - 1, n, 2, 1, img,
                                  images.data(), labels.data()) == 1,
        "decode short-buffer rc");

  // CSV parse: header + 3 rows of label,4 pixels (no trailing newline)
  std::string csv = "label,p0,p1,p2,p3\n7,0,128,255,1\n2,9,8,7,6\n1,1,2,3,4";
  std::vector<float> px(3 * 4);
  std::vector<std::int32_t> lab(3);
  std::int64_t rows = dcnn_parse_label_csv(
      csv.data(), static_cast<std::int64_t>(csv.size()), 4, 1, 1.0f / 255.0f,
      3, px.data(), lab.data());
  CHECK(rows == 3, "csv rows");
  CHECK(lab[0] == 7 && lab[1] == 2 && lab[2] == 1, "csv labels");
  CHECK(px[2] == 255.0f * (1.0f / 255.0f), "csv pixel");
}

}  // namespace

int main() {
  test_gather();
  test_shuffle();
  test_lz4();
  test_dataio();
  if (failures) {
    std::fprintf(stderr, "%d sanitize-driver failure(s)\n", failures);
    return 1;
  }
  std::puts("native sanitize driver: all round-trips clean");
  return 0;
}
