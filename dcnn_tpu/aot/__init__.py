"""Ahead-of-time executable cache — kill the compile wall.

BENCH_r05 measured the headline train step at 149.9 s of XLA compilation
against 3.1 s of 40-step work; every serve-replica spin-up, hot-swap
rejoin, and elastic reshard re-jit pays the same class of tax. The
reference framework never compiles (hand-written kernels dispatch
instantly); this subsystem gives the JAX reproduction the same
operational property the way Pathways-style systems do — compile once,
persist the lowered executable, and let every later process deserialize
it instead of retracing and recompiling (Barham et al., 2022).

Pieces (see each module's docstring for contracts):

- :mod:`~dcnn_tpu.aot.keys` — no-trace cache keys over (jaxlib/XLA
  version, device/topology fingerprint, input avals, precision mode,
  donation signature, closed-over-config digest);
- :mod:`~dcnn_tpu.aot.cache` — :class:`ExecutableCache`: checksum
  MANIFEST, atomic commits, cross-process locking, keep-K LRU GC,
  corrupt-entry quarantine;
- :mod:`~dcnn_tpu.aot.warm` — :func:`warm_or_compile`,
  :class:`WarmCallable`, env-gated :func:`maybe_warm`.

Wired into the four compile walls: ``Trainer`` train/multi steps
(``TrainingConfig.aot_cache_dir`` / ``AOT_CACHE``), ``serve/engine``
per-bucket sessions (replica fleets + hot-swap), ``parallel/elastic``
reshard re-jits, and the ``parallel/compiled_pipeline`` dispatchers.
CLI: ``python -m dcnn_tpu.aot`` (list / ``--gc`` / ``--prewarm``).
Everything is OFF unless ``AOT_CACHE`` (or an explicit dir) is set.
"""

from .cache import ExecutableCache
from .keys import backend_fingerprint, cache_key, digest, digest_arrays
from .warm import (WarmCallable, aot_dir, enabled_root, get_cache,
                   maybe_warm, warm_or_compile)

__all__ = [
    "ExecutableCache", "WarmCallable", "warm_or_compile", "maybe_warm",
    "get_cache", "enabled_root", "aot_dir", "cache_key", "digest",
    "digest_arrays", "backend_fingerprint",
]
