"""Warm-start compiled executables from the persistent cache.

The public surface of the subsystem:

- :func:`warm_or_compile` — given a jitted callable and its call avals,
  return a ready executable: **hit** = deserialize the cached bytes
  (sub-second, no trace, no XLA compile), **miss** = trace + compile as
  usual, then serialize and atomically commit for every later process.
  Backends whose executables can't (de)serialize fall back to plain
  compilation — the answer is always a working executable, the cache is
  only ever an accelerant.
- :class:`WarmCallable` — a drop-in wrapper around a jitted callable that
  runs :func:`warm_or_compile` once per argument signature and then
  dispatches straight to the loaded executable; unknown signatures fall
  through per-signature, so shape-polymorphic callers keep working.
- :func:`get_cache` / :func:`maybe_warm` — env-gated plumbing: the cache
  root rides the canonical compile-cache resolution
  (``utils.compile_cache.resolve_cache_root``: ``AOT_CACHE`` >
  ``DCNN_COMPILE_CACHE`` > default), with executables under
  ``<root>/aot``; the subsystem is OFF unless ``AOT_CACHE`` is set or a
  call site passes an explicit dir, so default runs and tier-1 behave
  exactly as before.

Hit/miss/deserialize-time accounting flows through
``obs.xla.record_aot`` (``aot_hits_total`` / ``aot_misses_total`` /
``aot_deserialize_seconds_total`` …) and compiles through the existing
``obs.xla.record_compile`` counters, so the 149.9 s wall this subsystem
kills stays a scrapeable series either way.
"""

from __future__ import annotations

import contextlib
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..resilience.faults import InjectedCrash
from .cache import ExecutableCache
from .keys import backend_fingerprint, cache_key, short_avals

_CACHES: Dict[str, ExecutableCache] = {}  # one instance (and sweep) per dir


def enabled_root(explicit: Optional[str] = None) -> Optional[str]:
    """The cache root when the subsystem is enabled, else ``None``.
    Explicit beats ``AOT_CACHE``; ``DCNN_COMPILE_CACHE`` alone does NOT
    enable AOT (it predates the subsystem and only places the XLA text
    cache), but once enabled both share one root — see
    ``utils.compile_cache``."""
    if explicit:
        return explicit
    return os.environ.get("AOT_CACHE", "").strip() or None


def aot_dir(root: str) -> str:
    """Executables live under ``<root>/aot`` — beside (never inside) the
    XLA persistent-cache files at the root itself."""
    return os.path.join(root, "aot")


def get_cache(explicit: Optional[str] = None, *,
              keep: Optional[int] = None,
              registry=None) -> Optional[ExecutableCache]:
    """The process-shared :class:`ExecutableCache` for the resolved root,
    or ``None`` when the subsystem is disabled."""
    root = enabled_root(explicit)
    if root is None:
        return None
    d = os.path.abspath(aot_dir(root))
    cache = _CACHES.get(d)
    if cache is None:
        cache = ExecutableCache(d, keep=keep, registry=registry)
        _CACHES[d] = cache
    return cache


def _serializer():
    from jax.experimental import serialize_executable as se
    return se


def _serialize_validated(compiled) -> Optional[bytes]:
    """Serialize ``compiled`` and prove the payload loads back, or
    ``None``. The load-back is not paranoia: XLA:CPU executables that
    were themselves *served from the persistent compilation cache*
    serialize to payloads missing their jitted symbols ("Symbols not
    found" at deserialize) — committing one would poison the cache for
    every later process, so nothing is committed until the bytes have
    deserialized once right here."""
    se = _serializer()
    try:
        payload = pickle.dumps(se.serialize(compiled))
        blob, in_tree, out_tree = pickle.loads(payload)
        se.deserialize_and_load(blob, in_tree, out_tree)
    except InjectedCrash:
        raise
    except Exception:
        return None
    return payload


@contextlib.contextmanager
def _persistent_cache_bypassed():
    """Force the next ``compile()`` to be a true cold compile (whose
    executable serializes completely — see :func:`_serialize_validated`):
    detach jax's persistent compilation cache AND drop the in-memory
    executable caches, which otherwise hand back the same
    incompletely-serializable executable in 10 ms. ``clear_caches`` makes
    other live jitted fns re-trace on their next call (served from the
    persistent text cache once it is re-attached) — a one-time cost paid
    only on this rare recovery path, never in steady state. The config
    toggle is a process global: a concurrent compile on another thread
    would at worst skip the text cache once or fail this retry's
    validation again (→ fallback, no commit) — never an incorrect
    commit."""
    import jax

    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.clear_caches()
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def warm_or_compile(jitted: Any, *args: Any,
                    cache: ExecutableCache,
                    what: str = "",
                    config: Optional[Any] = None,
                    donate: Tuple[int, ...] = (),
                    extra: Optional[Dict[str, Any]] = None,
                    registry=None) -> Tuple[Callable, Dict[str, Any]]:
    """Return ``(executable, info)`` for ``jitted`` at the avals of
    ``args`` (concrete arrays or ``jax.ShapeDtypeStruct`` specs).

    ``config`` must digest everything ``jitted`` closes over that shapes
    the compiled program (model config, optimizer hyperparameters, loss
    identity, weights for serving graphs — see ``keys.py``); ``donate``
    is the jit's donate_argnums. ``info`` carries ``key``, ``hit``,
    ``deserialize_s`` / ``compile_s``, and ``committed``."""
    from ..obs.xla import record_aot, record_compile

    fp = backend_fingerprint()
    key, material = cache_key(args, config=config, donate=donate,
                              extra=extra, fingerprint=fp)
    info: Dict[str, Any] = {"key": key, "hit": False, "committed": False}

    payload = None
    try:
        payload = cache.lookup(key, fingerprint=fp)
    except InjectedCrash:
        raise
    except Exception:
        payload = None  # unreadable cache == miss; compilation still works
    if payload is not None:
        t0 = time.perf_counter()
        try:
            se = _serializer()
            blob, in_tree, out_tree = pickle.loads(payload)
            exe = se.deserialize_and_load(blob, in_tree, out_tree)
        except InjectedCrash:
            raise
        except Exception as e:
            # checksum-valid bytes that won't load here: quarantine and
            # fall through to a fresh compile under the same key
            cache.quarantine(key, f"deserialize failed: {type(e).__name__}")
        else:
            dt = time.perf_counter() - t0
            record_aot("hit", dt, registry=registry)
            info.update({"hit": True, "deserialize_s": round(dt, 4)})
            return exe, info

    record_aot("miss", registry=registry)
    t0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    compile_s = time.perf_counter() - t0
    record_compile(compile_s, what=what, registry=registry)
    info["compile_s"] = round(compile_s, 4)
    payload = _serialize_validated(compiled)
    if payload is None:
        # most likely this compile was served from the persistent TEXT
        # cache, whose executables don't re-serialize completely on CPU
        # backends: pay one true cold compile to obtain committable
        # bytes (the whole point of being here is that every LATER
        # process skips this wall)
        try:
            with _persistent_cache_bypassed():
                t0 = time.perf_counter()
                compiled2 = jitted.lower(*args).compile()
                record_compile(time.perf_counter() - t0, what=what,
                               registry=registry)
            payload = _serialize_validated(compiled2)
            if payload is not None:
                compiled = compiled2
        except InjectedCrash:
            raise
        except Exception:
            payload = None
    if payload is None:
        # backend without executable serialization (or a full/odd disk):
        # the compiled executable is still perfectly usable, this process
        # just can't seed the cache
        record_aot("fallback", registry=registry)
    else:
        try:
            info["committed"] = cache.commit(key, payload, meta={
                "what": what, "avals": short_avals(material),
                "material": material})
        except InjectedCrash:
            raise
        except Exception:
            record_aot("fallback", registry=registry)
    return compiled, info


class WarmCallable:
    """AOT-warmed dispatch around one jitted callable.

    The first call at each argument signature runs
    :func:`warm_or_compile`; later calls dispatch straight to the loaded
    executable. Any failure in the warm path (a backend that can't
    deserialize, a cache dir that vanished) permanently falls back to the
    wrapped jit for that signature — the wrapper can slow down, never
    break. Execution errors from the chosen executable propagate
    untouched."""

    def __init__(self, jitted: Any, cache: ExecutableCache, *,
                 what: str = "", config: Optional[Any] = None,
                 donate: Tuple[int, ...] = (),
                 extra: Optional[Dict[str, Any]] = None, registry=None):
        self._jitted = jitted
        self._cache = cache
        self._what = what
        self._config = config
        self._donate = tuple(donate)
        self._extra = extra
        self._registry = registry
        self._exes: Dict[Any, Any] = {}     # sig tuple -> executable
        self.last_info: Optional[Dict[str, Any]] = None
        self.__wrapped__ = jitted

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    @staticmethod
    def _sig(args: Tuple[Any, ...]) -> Any:
        """Hashable per-call dispatch signature. This runs on EVERY call
        of the wrapped step (once per training batch), so it must stay
        cheap: direct ``.shape``/``.dtype`` attribute reads for array
        leaves (no ShapedArray construction, no JSON) with
        ``shaped_abstractify`` only for the rare non-array leaf (Python
        scalars like lr). The full ``aval_signature`` JSON form is only
        computed on the once-per-signature warm path (inside
        ``warm_or_compile``'s key derivation)."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                a = jax.api_util.shaped_abstractify(leaf)
                shape, dtype = a.shape, a.dtype
                weak = bool(getattr(a, "weak_type", False))
            else:
                weak = bool(getattr(leaf, "weak_type", False))
            sig.append((tuple(shape), str(dtype), weak))
        return treedef, tuple(sig)

    def __call__(self, *args):
        try:
            sig = self._sig(args)
        except Exception:
            return self._jitted(*args)
        exe = self._exes.get(sig)
        if exe is None:
            try:
                exe, self.last_info = warm_or_compile(
                    self._jitted, *args, cache=self._cache, what=self._what,
                    config=self._config, donate=self._donate,
                    extra=self._extra, registry=self._registry)
            except InjectedCrash:
                raise
            except Exception:
                exe = self._jitted
            self._exes[sig] = exe
        return exe(*args)

    def __repr__(self) -> str:
        return (f"WarmCallable({self._what or 'jit'}, "
                f"signatures={len(self._exes)}, cache={self._cache.root!r})")


def maybe_warm(jitted: Any, *, what: str = "",
               config: Optional[Any] = None,
               donate: Tuple[int, ...] = (),
               extra: Optional[Dict[str, Any]] = None,
               cache_dir: Optional[str] = None,
               registry=None) -> Any:
    """Wrap ``jitted`` in a :class:`WarmCallable` when the subsystem is
    enabled (``AOT_CACHE`` env or an explicit ``cache_dir``); otherwise
    return it unchanged. The zero-risk wiring helper the pipeline
    dispatchers use."""
    try:
        cache = get_cache(cache_dir, registry=registry)
    except Exception:
        return jitted
    if cache is None:
        return jitted
    return WarmCallable(jitted, cache, what=what, config=config,
                        donate=donate, extra=extra, registry=registry)
