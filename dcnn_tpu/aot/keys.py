"""Cache-key derivation for persisted XLA executables.

A cached executable is only reusable when *everything* that shaped its
compilation matches: the compiler (jax/jaxlib carry the XLA revision), the
hardware it was compiled for, the input avals (shapes/dtypes/weak-types),
the donation signature, the precision mode, and — because jit closes over
model structure, optimizer hyperparameters, and (for serving graphs) the
weights themselves as constants — a digest of that closed-over
configuration. The key is a SHA-256 over the canonical JSON of all of
those components, so *derivation never traces or lowers anything*: a
cache hit goes from process start to a loaded executable without paying
the trace wall, which is the whole point (ROADMAP item 4 targets
cold-start-to-first-step <10 s against a 149.9 s compile).

The flip side of a no-trace key is that the ``config`` digest is a
*contract*: a call site must fold in every value its jitted function
closes over that can change the compiled program (the in-repo call sites
— ``Trainer``, ``serve/engine``, ``parallel/elastic``,
``parallel/compiled_pipeline`` — each document what they fold in).
Under-keying serves a stale executable silently; when in doubt, fold it
in — an extra miss costs one compile, a collision costs correctness.

jax is imported lazily (this package must be importable before backend
selection, same promise as ``dcnn_tpu.obs``).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Sequence, Tuple

# bump when the key layout itself changes: old entries become misses, not
# deserialization errors
KEY_SCHEMA = 1


def backend_fingerprint() -> Dict[str, Any]:
    """The compiler + hardware identity an executable is only valid for:
    jax/jaxlib versions (they pin the XLA revision), backend platform,
    device kind, and the device/process topology counts."""
    import jax
    import jaxlib

    devs = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": devs[0].device_kind if devs else "none",
        "device_count": len(devs),
        "process_count": jax.process_count(),
    }


def aval_signature(args: Sequence[Any]) -> Dict[str, Any]:
    """Structure + per-leaf ``(shape, dtype, weak_type)`` of a call's
    arguments — concrete arrays and ``jax.ShapeDtypeStruct`` specs
    describe the same executable, so both abstract to the same signature.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tuple(args))
    sig = []
    for leaf in leaves:
        a = jax.api_util.shaped_abstractify(leaf)
        sig.append([list(a.shape), str(a.dtype),
                    bool(getattr(a, "weak_type", False))])
    return {"treedef": str(treedef), "leaves": sig}


def _precision_mode() -> str:
    try:
        from ..core.precision import get_precision_mode
        return get_precision_mode()
    except Exception:
        return "unknown"


def callable_id(fn: Any) -> str:
    """Process-stable identity for a closed-over callable:
    ``module.qualname`` (plus frozen args for ``functools.partial``) —
    never ``repr``, whose ``0x…`` address would change the key every
    process and turn the cache into a miss machine.

    A *bound method* additionally folds in its instance's
    ``get_config()`` digest when it has one: ``stack.stage_fn`` has the
    same qualname for every ``SequentialStageStack``, but two stacks
    built from different blocks compile to different programs — the
    qualname alone would collide and silently serve the wrong
    architecture."""
    import functools

    if isinstance(fn, functools.partial):
        inner = callable_id(fn.func)
        return (f"partial({inner}, args={fn.args!r}, "
                f"kw={sorted((fn.keywords or {}).items())!r})")
    mod = getattr(fn, "__module__", None) or type(fn).__module__
    qn = (getattr(fn, "__qualname__", None)
          or type(fn).__qualname__)
    base = f"{mod}.{qn}"
    owner = getattr(fn, "__self__", None)
    if owner is not None and not isinstance(owner, type):
        get_config = getattr(owner, "get_config", None)
        if callable(get_config):
            try:
                return f"{base}<{digest(get_config())}>"
            except Exception:
                pass
    return base


def optimizer_id(optimizer: Any) -> Any:
    """Stable key material for an optimizer: its config dict **minus
    ``learning_rate``** (lr rides into every jitted step as a runtime
    argument, so it never shapes the compiled program — keeping it in
    the key would miss across lr variants and silently defeat prewarm),
    falling back to the type identity when there is no config."""
    try:
        cfg = dict(optimizer.get_config())
        cfg.pop("learning_rate", None)
        return cfg
    except Exception:
        t = type(optimizer)
        return f"{t.__module__}.{t.__qualname__}"


def train_step_key_material(model: Any, optimizer: Any, loss_fn: Any, *,
                            num_microbatches: int = 1, guard: bool = False,
                            kind: str = "train_step") -> Dict[str, Any]:
    """The one canonical key-material dict for a ``Trainer``-shaped train
    step — everything ``make_train_step``/``make_multi_step`` close over
    that shapes the compiled program. ``Trainer._wire_aot``, the bench
    ``aot`` phase, and the CLI ``--prewarm`` all call this, so a prewarmed
    entry is guaranteed to hit for the real trainer (three hand-rolled
    copies of this dict would silently desynchronize)."""
    return {
        "model": model.get_config(),
        "optimizer": optimizer_id(optimizer),
        "loss": callable_id(loss_fn),
        "num_microbatches": int(num_microbatches),
        "guard": bool(guard),
        "kind": kind,
    }


def decode_step_key_material(model: Any, *, page_size: int,
                             num_pages: int, weights: str,
                             kind: str = "decode_step") -> Dict[str, Any]:
    """Canonical key material for a paged decode step
    (``serve/decode.py``): model config (layer count/dims shape the
    program), the page geometry (page size and pool size are baked into
    the scatter/gather shapes), and the **weights digest**
    (:func:`digest_arrays` — the step closes over the checkpoint as
    constants, exactly like the serving graphs in ``serve/engine``).
    The batch/page buckets ride the aval signature, not this dict."""
    return {
        "model": model.get_config(),
        "page_size": int(page_size),
        "num_pages": int(num_pages),
        "weights": weights,
        "kind": kind,
    }


def digest(obj: Any) -> str:
    """Stable SHA-256 of any JSON-able structure (non-JSON leaves fall
    back to ``repr``, which is stable for the repo's config objects)."""
    blob = json.dumps(obj, sort_keys=True, default=repr,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def digest_arrays(tree: Any) -> str:
    """SHA-256 over every leaf's bytes + shape/dtype in tree-flatten
    order — the weights digest serving graphs need (jit bakes closed-over
    arrays into the program as constants, so two checkpoints of the same
    architecture compile to *different* executables)."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.ascontiguousarray(jax.device_get(leaf))
        h.update(str((a.shape, str(a.dtype))).encode("utf-8"))
        h.update(a.tobytes())
    return h.hexdigest()


def cache_key(args: Sequence[Any], *,
              config: Optional[Any] = None,
              donate: Tuple[int, ...] = (),
              extra: Optional[Dict[str, Any]] = None,
              fingerprint: Optional[Dict[str, Any]] = None
              ) -> Tuple[str, Dict[str, Any]]:
    """Derive ``(key_hex, material)`` for one executable. ``material`` is
    the pre-hash component dict — it lands in the entry MANIFEST so a
    human (or the CLI) can see *why* two keys differ."""
    material = {
        "schema": KEY_SCHEMA,
        "fingerprint": fingerprint if fingerprint is not None
        else backend_fingerprint(),
        "avals": aval_signature(args),
        "donate": sorted(int(i) for i in donate),
        "precision": _precision_mode(),
        "config": config if isinstance(config, str) else digest(config),
        "extra": extra or {},
    }
    return digest(material), material


def short_avals(material: Dict[str, Any], limit: int = 4) -> str:
    """Compact human-readable aval summary for listings:
    ``f32[8,64,64,3], f32[8,200], …(+7)``."""
    leaves = material.get("avals", {}).get("leaves", [])
    parts = []
    for shape, dtype, _weak in leaves[:limit]:
        dt = {"float32": "f32", "float64": "f64", "bfloat16": "bf16",
              "int32": "i32", "int64": "i64", "uint32": "u32",
              "uint8": "u8", "int8": "i8", "bool": "pred"}.get(dtype, dtype)
        parts.append(f"{dt}[{','.join(str(d) for d in shape)}]")
    if len(leaves) > limit:
        parts.append(f"…(+{len(leaves) - limit})")
    return ", ".join(parts)
