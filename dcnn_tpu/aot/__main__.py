"""CLI: ``python -m dcnn_tpu.aot``.

Operational surface for the executable cache:

- default: list committed entries (key, label, avals, size, age, hits);
- ``--gc [--keep K]``: keep-K LRU sweep;
- ``--prewarm SRC``: populate a cache before deploy — build an
  :class:`~dcnn_tpu.serve.engine.InferenceEngine` (every serve bucket
  compiles and commits) from ``SRC`` = a ``save_checkpoint`` directory
  or a model-zoo name (``resnet18_tiny_imagenet`` …), optionally plus a
  train-step executable with ``--train-batch``. A router fleet spun up
  against the same cache dir then starts in seconds (docs/deployment.md
  §5).

Exit codes (the ``dcnn_tpu.analysis`` convention): 0 = success, 1 = the
requested operation failed, 2 = usage/internal error. ``--json`` emits
machine-readable reports.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .warm import aot_dir, enabled_root


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dcnn_tpu.aot",
        description="AOT executable cache: list / gc / prewarm")
    p.add_argument("--dir", default=None,
                   help="cache ROOT (executables under <dir>/aot); "
                        "default: AOT_CACHE, then DCNN_COMPILE_CACHE, "
                        "then /tmp/jax_cache")
    p.add_argument("--json", action="store_true",
                   help="emit JSON instead of a table")
    p.add_argument("--gc", action="store_true",
                   help="remove all but the --keep most-recently-used "
                        "entries")
    p.add_argument("--keep", type=int, default=None,
                   help="retention for --gc (default: AOT_CACHE_KEEP "
                        "env or 64)")
    p.add_argument("--prewarm", metavar="SRC", default=None,
                   help="populate the cache: SRC is a checkpoint dir "
                        "(train.save_checkpoint layout) or a model-zoo "
                        "name")
    p.add_argument("--max-batch", type=int, default=32,
                   help="serve bucket cap for --prewarm (default 32)")
    p.add_argument("--no-fold", action="store_true",
                   help="skip BN folding in the prewarmed serve graph")
    p.add_argument("--train-batch", type=int, default=0,
                   help="also prewarm a train-step executable at this "
                        "batch size (0 = serve buckets only)")
    p.add_argument("--seed", type=int, default=0,
                   help="init seed for zoo models (default 0)")
    return p


def _resolve_root(arg_dir):
    explicit = enabled_root(arg_dir)
    if explicit is not None:
        return explicit
    from ..utils.compile_cache import resolve_cache_root
    return resolve_cache_root()


def _load_source(src: str, seed: int):
    """(model, params, state) from a checkpoint dir or a zoo name."""
    import jax

    if os.path.isdir(src):
        from ..train.checkpoint import load_checkpoint
        model, params, state, _, _, _ = load_checkpoint(src, seed=seed)
        return model, params, state
    from ..models import MODEL_ZOO, create_model
    if src not in MODEL_ZOO:
        known = ", ".join(sorted(MODEL_ZOO))
        raise ValueError(f"{src!r} is neither a checkpoint dir nor a "
                         f"zoo model (known: {known})")
    model = create_model(src)
    params, state = model.init(jax.random.PRNGKey(seed))
    return model, params, state


def _prewarm(cache, args) -> dict:
    import jax

    from ..serve.engine import InferenceEngine
    model, params, state = _load_source(args.prewarm, args.seed)
    engine = InferenceEngine.from_model(
        model, params, state, fold=not args.no_fold,
        max_batch=args.max_batch, warmup=False, aot_cache=cache)
    report = {
        "source": args.prewarm,
        "buckets": engine.bucket_sizes,
        "bucket_stats": {str(b): s for b, s in
                         engine.compile_stats.items()},
    }
    if args.train_batch > 0:
        from ..optim import Adam
        from ..ops.losses import softmax_cross_entropy
        from ..train import make_train_step
        from ..train.trainer import create_train_state
        from .keys import digest, train_step_key_material
        from .warm import warm_or_compile
        import jax.numpy as jnp

        opt = Adam(1e-3)
        ts = create_train_state(model, opt, jax.random.PRNGKey(args.seed))
        step = make_train_step(model, softmax_cross_entropy, opt)
        b = args.train_batch
        n_out = model.output_shape()[-1]
        xx = jax.ShapeDtypeStruct((b, *model.input_shape), jnp.float32)
        yy = jax.ShapeDtypeStruct((b, n_out), jnp.float32)
        rr = jax.ShapeDtypeStruct((2,), jnp.uint32)
        # the canonical Trainer key material (lr is stripped inside, so
        # the prewarmed entry hits for ANY base learning rate)
        cfg = digest(train_step_key_material(model, opt,
                                             softmax_cross_entropy))
        _, info = warm_or_compile(step, ts, xx, yy, rr, 1e-3, cache=cache,
                                  what="train", config=cfg, donate=(0,))
        report["train_step"] = info
    return report


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        root = _resolve_root(args.dir)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    from .cache import ExecutableCache
    try:
        cache = ExecutableCache(aot_dir(root), keep=args.keep)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.prewarm:
        try:
            report = _prewarm(cache, args)
        except Exception as e:
            print(f"prewarm failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps({"dir": cache.root, "prewarm": report},
                             indent=2))
        else:
            hits = sum(1 for s in report["bucket_stats"].values()
                       if s.get("aot_hit"))
            print(f"prewarmed {args.prewarm}: serve buckets "
                  f"{report['buckets']} ({hits} already cached) "
                  f"-> {cache.root}")
            if "train_step" in report:
                ti = report["train_step"]
                state = "hit" if ti["hit"] else "compiled+committed"
                print(f"train step @ batch {args.train_batch}: {state}")
        return 0

    if args.gc:
        removed = cache.gc(args.keep)
        if args.json:
            print(json.dumps({"dir": cache.root, "removed": removed,
                              "kept": len(cache.entries())}))
        else:
            print(f"gc: removed {removed}, kept {len(cache.entries())} "
                  f"({cache.root})")
        return 0

    rows = cache.entries()
    if args.json:
        print(json.dumps({"dir": cache.root, "entries": rows}, indent=2))
        return 0
    if not rows:
        print(f"{cache.root}: empty")
        return 0
    print(f"{cache.root}: {len(rows)} entries")
    print(f"{'key':16}  {'what':10} {'size':>10}  {'age':>8}  "
          f"{'hits':>5}  avals")
    for r in rows:
        if "error" in r:
            print(f"{r['key'][:16]:16}  {r['error']}")
            continue
        size = r.get("size") or 0
        mb = f"{size / 1e6:.1f}MB"
        age = r.get("age_s") or 0.0
        age_h = f"{age / 3600:.1f}h" if age >= 3600 else f"{age:.0f}s"
        print(f"{r['key'][:16]:16}  {r.get('what', ''):10} {mb:>10}  "
              f"{age_h:>8}  {r.get('hits', 0):>5}  {r.get('avals', '')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
