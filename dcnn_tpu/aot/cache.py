"""Persistent on-disk store for serialized XLA executables.

Layout — one immutable directory per executable under the cache root::

    <root>/
      .lock                     cross-process advisory lock (fcntl.flock)
      <key>/                    key = keys.cache_key sha256 hex
        payload.bin             pickled (payload, in_tree, out_tree) from
                                jax.experimental.serialize_executable
        MANIFEST.json           sha256 + size of payload, creation time,
                                backend fingerprint, key material, label
        hits                    load counter sidecar (not checksummed —
                                MANIFEST integrity covers the payload only)
      tmp-<uuid>/               in-flight staging (resilience.atomic)
      corrupt-<key>-<uuid>/     quarantined entries awaiting the age sweep

Durability and sharing contracts:

- **Atomic commits.** An entry is staged complete under ``tmp-<uuid>``,
  fsynced, and published by one ``os.replace`` (the
  ``resilience.atomic`` stage→fsync→rename protocol, with the data
  flushes done before the cache lock is taken), so a reader — or a
  process resuming after preemption — sees either no entry or a whole
  entry, never a torn one.
- **Checksum MANIFEST.** ``lookup`` verifies the payload's SHA-256 before
  returning it; a mismatch (bit rot, torn copy, hostile edit) quarantines
  the entry (renamed ``corrupt-*``, counted) and reports a miss so the
  caller transparently recompiles — the same
  quarantine-don't-crash contract as ``CheckpointManager``.
- **Version staleness is a miss, not a crash.** The backend fingerprint
  is part of the key, so a jaxlib bump naturally misses; entries whose
  MANIFEST fingerprint disagrees anyway (hand-copied caches, key-schema
  changes) are skipped and left for GC.
- **Cross-process locking.** All mutations (commit, GC, quarantine, hit
  bump) run under an exclusive ``flock`` on ``<root>/.lock``; reads take
  it shared. N serve replicas / trainer processes share one cache dir
  safely; on platforms without ``fcntl`` the lock degrades to a no-op
  (single-process use stays correct via the atomic renames).
- **Keep-K GC.** After each commit the oldest entries (directory mtime —
  bumped by every hit via the sidecar write, so this is LRU) beyond
  ``keep`` are removed. Stale ``tmp-``/``corrupt-`` dirs older than an
  hour are swept at construction; young ones are left alone because they
  may belong to a live sibling process.

Fault points (``resilience.faults``): ``aot.commit`` fires before a
commit's staging, ``aot.load`` before a lookup's read — the harness for
the torn/corrupt/crash tests in ``tests/test_aot.py``.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import time
import uuid
from typing import Any, Dict, List, Optional

try:
    import fcntl
except ImportError:  # non-POSIX: advisory locking degrades to a no-op
    fcntl = None

from ..resilience import faults as _faults
from ..resilience.atomic import fsync_path, stage_dir, write_file_atomic

_PAYLOAD = "payload.bin"
_MANIFEST = "MANIFEST.json"
_HITS = "hits"
_DEFAULT_KEEP = 64
_SWEEP_AGE_S = 3600.0


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class ExecutableCache:
    """Shared persistent executable store rooted at ``root`` (typically
    ``<compile-cache-root>/aot`` — see ``dcnn_tpu.aot.warm``)."""

    def __init__(self, root: str, *, keep: Optional[int] = None,
                 registry=None, clock=time.time):
        self.root = os.path.abspath(root)
        if keep is None:
            keep = int(os.environ.get("AOT_CACHE_KEEP", _DEFAULT_KEEP))
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.keep = keep
        self.registry = registry
        self._clock = clock
        os.makedirs(self.root, mode=0o700, exist_ok=True)
        self._check_root_trusted()
        self._sweep_stale()

    def _check_root_trusted(self) -> None:
        """Refuse a cache root another user could have planted or can
        swap out. Hits deserialize through ``pickle.loads`` — executing
        bytes from a directory an attacker controls is arbitrary code
        execution, and the checksum MANIFEST is no defense (they sit in
        the same directory). The ssh strict-modes walk: the root AND
        every ancestor must be owned by us (or root) and not
        world-writable — except sticky world-writable dirs (``/tmp``
        itself, 1777), where the kernel already forbids other users
        renaming entries they don't own, so a 0700 root under ``/tmp``
        stays trusted. Every refusal degrades to uncached compilation
        via the callers' guards."""
        if not hasattr(os, "getuid"):
            return  # non-POSIX: no uid/mode semantics to check
        uid = os.getuid()
        path = os.path.realpath(self.root)
        while True:
            st = os.stat(path)
            sticky_shared = (st.st_mode & 0o1000) and (st.st_mode & 0o002)
            if not sticky_shared:
                # sticky world-writable dirs (/tmp, 1777 — whatever their
                # owner, which varies across container images) are the
                # platform's shared-tmp contract: the kernel forbids
                # non-owners renaming entries they don't own, so our 0700
                # entry beneath them is safe. Everything else must be
                # ours (or root's) and not world-writable.
                if st.st_uid not in (uid, 0):
                    raise ValueError(
                        f"aot cache path {path!r} is owned by uid "
                        f"{st.st_uid}, not us (uid {uid}) — refusing to "
                        f"load executables through a directory another "
                        f"user controls")
                if st.st_mode & 0o002:
                    raise ValueError(
                        f"aot cache path {path!r} is world-writable "
                        f"without the sticky bit (mode "
                        f"{oct(st.st_mode & 0o7777)}) — any user could "
                        f"swap a payload in; chmod o-w it or point "
                        f"AOT_CACHE at a private directory")
            parent = os.path.dirname(path)
            if parent == path:
                return
            path = parent

    # -- locking -----------------------------------------------------------
    @contextlib.contextmanager
    def _lock(self, *, exclusive: bool):
        """Advisory cross-process lock over the whole cache dir. Each
        acquisition opens its own fd, so in-process threads serialize
        against each other too (flock is per open-file-description)."""
        if fcntl is None:
            yield
            return
        fd = os.open(os.path.join(self.root, ".lock"),
                     os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX if exclusive else fcntl.LOCK_SH)
            yield
        finally:
            os.close(fd)  # closing releases the flock

    # -- observability -----------------------------------------------------
    def _count(self, event: str, seconds: float = 0.0) -> None:
        from ..obs.xla import record_aot
        record_aot(event, seconds, registry=self.registry)

    # -- entry paths -------------------------------------------------------
    def _entry_dir(self, key: str) -> str:
        if not key or os.sep in key or key.startswith((".", "tmp-",
                                                       "corrupt-")):
            raise ValueError(f"malformed cache key {key!r}")
        return os.path.join(self.root, key)

    def has(self, key: str) -> bool:
        return os.path.isfile(os.path.join(self._entry_dir(key), _MANIFEST))

    # -- core operations ---------------------------------------------------
    def lookup(self, key: str,
               fingerprint: Optional[Dict[str, Any]] = None
               ) -> Optional[bytes]:
        """Checksum-verified payload bytes for ``key``, or ``None`` on a
        miss. A corrupt entry (torn/bit-flipped payload, unreadable
        MANIFEST) is quarantined and reported as a miss; an entry whose
        recorded fingerprint disagrees with ``fingerprint`` (stale
        version) is skipped — present but not loadable here."""
        _faults.trip("aot.load", key=key)
        d = self._entry_dir(key)
        corrupt_reason = None
        with self._lock(exclusive=False):
            try:
                with open(os.path.join(d, _MANIFEST), "r",
                          encoding="utf-8") as f:
                    manifest = json.load(f)
            except FileNotFoundError:
                return None
            except (OSError, ValueError) as e:
                corrupt_reason = f"unreadable MANIFEST: {e}"
                manifest = None
            payload = None
            if manifest is not None:
                if fingerprint is not None:
                    rec = (manifest.get("material") or {}).get(
                        "fingerprint") or {}
                    for field in ("jax", "jaxlib", "backend", "device_kind"):
                        if field in rec and rec[field] != fingerprint.get(
                                field):
                            self._count("stale")
                            return None
                try:
                    with open(os.path.join(d, _PAYLOAD), "rb") as f:
                        payload = f.read()
                except OSError as e:
                    corrupt_reason = f"unreadable payload: {e}"
                else:
                    if _sha256(payload) != manifest.get("sha256"):
                        corrupt_reason = "payload checksum mismatch"
                        payload = None
        if corrupt_reason is not None:
            self.quarantine(key, corrupt_reason)
            return None
        self._record_hit(key)
        return payload

    def commit(self, key: str, payload: bytes,
               meta: Optional[Dict[str, Any]] = None) -> bool:
        """Atomically publish ``payload`` under ``key``; ``False`` when a
        sibling process already committed it (their bytes are equivalent
        by key construction — first writer wins). Runs keep-K GC after a
        successful publish."""
        _faults.trip("aot.commit", key=key)
        final = self._entry_dir(key)
        if os.path.isdir(final):
            return False
        manifest = dict(meta or {})
        manifest.update({
            "key": key,
            "sha256": _sha256(payload),
            "size": len(payload),
            "created_unix": self._clock(),
        })
        # Stage AND fsync the (potentially multi-hundred-MB) payload
        # UNLOCKED — the uuid tmp name is collision-free, and holding the
        # fleet-wide exclusive flock through the write+flush would block
        # every sibling replica's lookup for the whole copy, during
        # exactly the spin-up window the cache exists to accelerate. The
        # protocol is resilience.atomic's stage→fsync→os.replace, with
        # the data flushes hoisted out of the lock: it covers only the
        # publish decision (exists-check, rename, parent fsync, GC).
        tmp = stage_dir(self.root)
        try:
            with open(os.path.join(tmp, _PAYLOAD), "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            with open(os.path.join(tmp, _MANIFEST), "w",
                      encoding="utf-8") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            fsync_path(tmp)
            with self._lock(exclusive=True):
                if os.path.isdir(final):  # a sibling published first
                    shutil.rmtree(tmp, ignore_errors=True)
                    return False
                os.replace(tmp, final)
                fsync_path(self.root)
                self._gc_locked(self.keep)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._count("commit")
        return True

    def quarantine(self, key: str, reason: str = "") -> bool:
        """Move a corrupt entry aside (``corrupt-<key>-<uuid>``) so the
        caller can recompile and recommit under the same key. Quarantined
        dirs are swept by age at construction time."""
        d = self._entry_dir(key)
        with self._lock(exclusive=True):
            if not os.path.isdir(d):
                return False
            dst = os.path.join(self.root,
                               f"corrupt-{key[:16]}-{uuid.uuid4().hex[:8]}")
            try:
                os.replace(d, dst)
            except OSError:
                return False
        import warnings
        warnings.warn(f"aot cache: quarantined corrupt entry {key[:16]}… "
                      f"({reason or 'integrity failure'}); it will be "
                      f"recompiled", stacklevel=2)
        self._count("quarantined")
        return True

    def _record_hit(self, key: str) -> None:
        """Bump the hit sidecar (best-effort — a lost bump only skews the
        listing, never correctness). The write also touches the entry
        dir's mtime, which is what keep-K GC orders by (LRU)."""
        d = self._entry_dir(key)
        with self._lock(exclusive=True):
            try:
                try:
                    with open(os.path.join(d, _HITS), "r",
                              encoding="utf-8") as f:
                        n = int(f.read().strip() or 0)
                except (OSError, ValueError):
                    n = 0
                write_file_atomic(os.path.join(d, _HITS),
                                  str(n + 1).encode("utf-8"))
            except OSError:
                pass

    # -- retention ---------------------------------------------------------
    def _entry_names(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return [n for n in names
                if not n.startswith((".", "tmp-", "corrupt-"))
                and os.path.isdir(os.path.join(self.root, n))]

    def _gc_locked(self, keep: int) -> int:
        entries = []
        for name in self._entry_names():
            try:
                mtime = os.path.getmtime(os.path.join(self.root, name))
            except OSError:
                continue
            entries.append((mtime, name))
        entries.sort(reverse=True)  # newest-used first
        removed = 0
        for _, name in entries[keep:]:
            shutil.rmtree(os.path.join(self.root, name),
                          ignore_errors=True)
            removed += 1
        return removed

    def gc(self, keep: Optional[int] = None) -> int:
        """Keep the ``keep`` most-recently-used entries; returns how many
        were removed."""
        k = self.keep if keep is None else keep
        if k < 1:
            raise ValueError(f"keep must be >= 1, got {k}")
        with self._lock(exclusive=True):
            return self._gc_locked(k)

    def _sweep_stale(self) -> int:
        """Remove ``tmp-``/``corrupt-`` dirs older than an hour. Young
        ones are left alone: a ``tmp-`` may be a sibling process's
        in-flight commit."""
        removed = 0
        with self._lock(exclusive=True):
            try:
                names = os.listdir(self.root)
            except OSError:
                return 0
            now = self._clock()
            for name in names:
                if not name.startswith(("tmp-", "corrupt-")):
                    continue
                p = os.path.join(self.root, name)
                try:
                    age = now - os.path.getmtime(p)
                except OSError:
                    continue
                if age > _SWEEP_AGE_S:
                    shutil.rmtree(p, ignore_errors=True)
                    removed += 1
        return removed

    # -- introspection (the CLI's data source) -----------------------------
    def entries(self) -> List[Dict[str, Any]]:
        """One summary dict per committed entry, newest-used first."""
        out = []
        with self._lock(exclusive=False):
            for name in self._entry_names():
                d = os.path.join(self.root, name)
                row: Dict[str, Any] = {"key": name}
                try:
                    with open(os.path.join(d, _MANIFEST), "r",
                              encoding="utf-8") as f:
                        m = json.load(f)
                except (OSError, ValueError):
                    row["error"] = "unreadable MANIFEST"
                    out.append(row)
                    continue
                row.update({
                    "what": m.get("what", ""),
                    "avals": m.get("avals", ""),
                    "size": m.get("size"),
                    "age_s": round(max(
                        self._clock() - m.get("created_unix", 0.0), 0.0), 1),
                    "jaxlib": (m.get("material") or {}).get(
                        "fingerprint", {}).get("jaxlib"),
                })
                try:
                    with open(os.path.join(d, _HITS), "r",
                              encoding="utf-8") as f:
                        row["hits"] = int(f.read().strip() or 0)
                except (OSError, ValueError):
                    row["hits"] = 0
                try:
                    row["last_used_s"] = round(max(
                        self._clock() - os.path.getmtime(d), 0.0), 1)
                except OSError:
                    pass
                out.append(row)
        out.sort(key=lambda r: r.get("last_used_s", float("inf")))
        return out

    def __repr__(self) -> str:
        return (f"ExecutableCache({self.root!r}, keep={self.keep}, "
                f"entries={len(self._entry_names())})")
