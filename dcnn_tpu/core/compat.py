"""Version-compat shims for fast-moving jax APIs.

The repo targets current jax (``jax.shard_map`` with ``check_vma=``), but
CI hosts and the CPU test container may carry an older release where the
same functionality lives at ``jax.experimental.shard_map.shard_map`` with
the kwarg spelled ``check_rep=``. Every internal ``shard_map`` call goes
through this module so the whole parallel/data stack imports (and runs)
on both — one shim instead of four scattered try/excepts.
"""

from __future__ import annotations

try:  # current jax
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the ``check_vma``/``check_rep`` kwarg rename
    papered over. ``check_vma=None`` leaves the library default."""
    kw = {} if check_vma is None else {_CHECK_KW: check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)
