"""Device discovery and selection.

Reference equivalent: ``DeviceManager`` singleton that discovers CPU + CUDA
devices at startup and serves ``getCPU()/getGPU(i)`` lookups
(``/root/reference/src/device/device_manager.cpp:22-61``,
``include/device/device_manager.hpp:74-76``).

On TPU the platform runtime (PJRT) already owns discovery; this module is a
thin, dependency-free façade so the rest of the framework never touches
``jax.devices()`` directly and tests can force the CPU backend.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import List, Optional

import jax


@dataclass(frozen=True)
class DeviceInfo:
    """Summary of one accelerator chip (reference: ``Device`` facade,
    ``include/device/device.hpp:12-43``)."""

    id: str           # e.g. "TPU:0", "CPU:0"
    platform: str     # "tpu" | "cpu" | "gpu" | experimental plugin names
    index: int
    device: jax.Device

    @property
    def is_accelerator(self) -> bool:
        return self.platform not in ("cpu",)


class DeviceManager:
    """Process-wide device registry (reference:
    ``DeviceManager::getInstance()``, ``device_manager.hpp:9``).

    Unlike the reference there is no allocation API here: array placement is
    expressed with ``jax.device_put`` / shardings, and HBM allocation is owned
    by PJRT.
    """

    _instance: Optional["DeviceManager"] = None

    def __init__(self) -> None:
        self._devices: List[DeviceInfo] = []
        self._discover()

    @classmethod
    def instance(cls) -> "DeviceManager":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def _discover(self) -> None:
        for d in jax.devices():
            plat = d.platform
            self._devices.append(
                DeviceInfo(id=f"{plat.upper()}:{d.id}", platform=plat, index=d.id, device=d)
            )
        # CPU host devices are always reachable even when an accelerator is the
        # default backend (reference always registers "CPU:0",
        # device_manager.cpp:27-33).
        if all(info.platform != "cpu" for info in self._devices):
            try:
                for d in jax.devices("cpu"):
                    self._devices.append(
                        DeviceInfo(id=f"CPU:{d.id}", platform="cpu", index=d.id, device=d)
                    )
            except RuntimeError:
                pass

    # -- lookups (reference: getCPU()/getGPU(i), device_manager.hpp:74-76) --
    def all(self) -> List[DeviceInfo]:
        return list(self._devices)

    def accelerators(self) -> List[DeviceInfo]:
        return [d for d in self._devices if d.is_accelerator]

    def cpu(self, index: int = 0) -> DeviceInfo:
        cpus = [d for d in self._devices if d.platform == "cpu"]
        if not cpus:
            raise RuntimeError("no CPU device registered")
        return cpus[index]

    def get(self, device_id: str) -> DeviceInfo:
        for d in self._devices:
            if d.id == device_id:
                return d
        raise KeyError(f"unknown device id {device_id!r}")

    def default(self) -> DeviceInfo:
        accs = self.accelerators()
        return accs[0] if accs else self._devices[0]


def local_devices() -> List[jax.Device]:
    return jax.local_devices()


def device_count() -> int:
    return jax.device_count()


@functools.lru_cache(maxsize=None)
def default_device() -> jax.Device:
    return DeviceManager.instance().default().device
