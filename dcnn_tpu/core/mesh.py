"""Device-mesh construction helpers.

The reference's only multi-device topology is a linear chain of pipeline
stages over TCP (``include/pipeline/coordinator.hpp:517-555``). The TPU-native
equivalent is a ``jax.sharding.Mesh`` whose axes name the parallelism
dimensions; collectives then ride ICI. Canonical axes used across this
framework:

- ``"data"``  — batch (data parallel) axis; gradient psum rides ICI.
- ``"stage"`` — pipeline-stage axis (the analog of the reference's worker
  chain); activations move with ``ppermute``.
- ``"model"`` — reserved for tensor parallelism of wide layers.
- ``"seq"``   — sequence/context parallelism; ring attention rotates K/V
  shards over this axis with ``ppermute`` (``dcnn_tpu/parallel/sequence.py``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh


DATA_AXIS = "data"
STAGE_AXIS = "stage"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def mesh_axes() -> Tuple[str, ...]:
    return (DATA_AXIS, STAGE_AXIS, MODEL_AXIS, SEQ_AXIS)


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all local devices).

    ``make_mesh()`` → 1-D data mesh over every device.
    ``make_mesh((4, 2), ("data", "stage"))`` → 4-way DP × 2-stage pipeline.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),)
    if int(np.prod(shape)) != len(devs):
        raise ValueError(f"mesh shape {tuple(shape)} does not cover {len(devs)} devices")
    if len(shape) != len(axis_names):
        raise ValueError("shape and axis_names rank mismatch")
    arr = np.asarray(devs, dtype=object).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def single_device_mesh(axis_name: str = DATA_AXIS) -> Mesh:
    """1-device mesh — lets sharded code paths run unmodified on one chip."""
    return make_mesh((1,), (axis_name,), devices=jax.devices()[:1])
