"""Training configuration.

Reference equivalent: ``TrainingConfig`` + ``load_from_env``
(``/root/reference/include/nn/train.hpp:46-101``), which maps EPOCHS /
BATCH_SIZE / LR_DECAY_* / NUM_MICROBATCHES / DEVICE_TYPE / PROFILER_TYPE
environment variables into the trainer. Same variable names are honored here.
"""

from __future__ import annotations

from dataclasses import dataclass, asdict
from enum import Enum
from typing import Optional

from ..utils.env import get_env


class ProfilerType(Enum):
    """Per-layer profiling mode (reference ``train.hpp:37``)."""

    NONE = "none"
    NORMAL = "normal"          # cleared every batch
    CUMULATIVE = "cumulative"  # accumulated across the epoch


@dataclass
class TrainingConfig:
    epochs: int = 10
    batch_size: int = 64
    learning_rate: float = 1e-3
    lr_decay_factor: float = 1.0      # multiplicative per-epoch decay (train.hpp:282-288)
    lr_decay_interval: int = 1
    num_microbatches: int = 1
    device_type: str = "tpu"          # "tpu" | "cpu"
    profiler: ProfilerType = ProfilerType.NONE
    seed: int = 42
    snapshot_dir: Optional[str] = "model_snapshots"
    progress_interval: int = 100      # batches between progress prints (train.hpp:149-162)
    dtype: str = "float32"            # "float32" parity mode | "bfloat16" fast mode
    debug: bool = False               # numeric sanitizers (reference ENABLE_DEBUG
                                      # ASan build, CMakeLists.txt:22; core/debug.py)
    scheduler_step: str = "epoch"     # "epoch" (reference cadence, train.hpp:282-288)
                                      # | "batch" (what OneCycleLR/WarmupCosine are
                                      # usually sized for: total_steps = epochs*batches)
    steps_per_dispatch: int = 1       # >1: expect [K,B,...] chunks (PrefetchLoader
                                      # stage_batches=K) and run K train steps per
                                      # device dispatch (train.make_multi_step) —
                                      # the remote/tunnelled-TPU fast path
    feed_workers: int = 0             # >0: parallel host input pipeline — a
                                      # FeedWorkerPool of this many worker
                                      # processes does gather/augment/collate
                                      # into shared-memory slots for the
                                      # prefetch/streaming feeds
                                      # (data/workers.py; docs/performance.md)

    # -- fault tolerance (dcnn_tpu/resilience; docs/reliability.md) --
    checkpoint_dir: Optional[str] = None  # root for periodic atomic checkpoints
                                      # (CheckpointManager; separate from the
                                      # best-val snapshot_dir)
    checkpoint_every: int = 0         # epochs between periodic checkpoints
                                      # (0 = off; needs checkpoint_dir)
    checkpoint_keep: int = 3          # keep-last-K retention
    checkpoint_async: bool = True     # background saver thread: the step loop
                                      # pays only the device_get snapshot
    resume: str = "never"             # "auto": restore the newest valid
                                      # checkpoint from checkpoint_dir at
                                      # fit() and continue | "never"
    nonfinite_policy: str = "off"     # "off" (exact pre-guard graph) | "raise"
                                      # | "skip_step" | "rollback" — see
                                      # resilience.StepGuard
    rollback_after: int = 3           # consecutive bad steps before a
                                      # "rollback" policy restores the last
                                      # checkpoint
    stall_timeout_s: float = 0.0      # >0: StallWatchdog flags a hung
                                      # step/data fetch on the obs registry

    # -- elastic data-parallel training (dcnn_tpu/parallel/elastic.py;
    #    docs/reliability.md §"Elastic training") --
    elastic: bool = False             # fit() runs the elastic DP controller:
                                      # generation-stamped membership over
                                      # the peer mesh, survives host loss
                                      # mid-epoch via checkpoint-restore +
                                      # batch-plan reshard
    elastic_peers: str = ""           # "host:port,host:port,..." — one per
                                      # host, rank = position (empty: solo)
    elastic_rank: int = -1            # this host's rank (-1: PROCESS_ID env)
    elastic_microbatches: int = 0     # global grad-accumulation grid K,
                                      # fixed for the run; batch_size/K rows
                                      # per microbatch (0: initial world
                                      # size). The grid is re-partitioned —
                                      # never re-gridded — across survivors,
                                      # holding the global batch constant
    elastic_heartbeat_s: float = 1.0  # background beat period (0: beats
                                      # only ride the step loop)
    elastic_timeout_s: float = 30.0   # peer silence before it is declared
                                      # dead; also the frame-wait deadline
    elastic_ckpt_steps: int = 0       # mid-epoch checkpoint cadence in
                                      # optimizer steps (0: epoch boundaries
                                      # only — a loss re-runs the epoch)
    elastic_min_world: int = 1        # fewer survivors than this aborts
                                      # (WorldCollapsedError) instead of
                                      # limping on
    elastic_compress: str = ""        # frame codec for the grad-exchange
                                      # mesh: "" = raw, or a name from
                                      # utils/compression.resolve_codec
                                      # ("lz4", "shuffle-lz4", "zstd",
                                      # "shuffle-zstd", "zlib"). Per-frame
                                      # codec ids keep mixed fleets interop

    # -- gray-failure (fail-slow) detection (resilience/slowness.py;
    #    docs/reliability.md §11). slow_detect gates the training-side
    #    mitigations (elastic straggler eviction, feed-worker recycle);
    #    the thresholds seed the shared SlownessConfig, with DCNN_SLOW_*
    #    env overrides layered on top by SlownessConfig.from_env --
    slow_detect: bool = False         # convict-and-mitigate on sustained
                                      # relative slowness (off = observe
                                      # nothing; fail-stop paths unchanged)
    slow_dwell_s: float = 1.0         # sustained outlier-hood before convict
    slow_ratio: float = 2.0           # conviction floor: EWMA > ratio*median
    slow_mad_k: float = 4.0           # MAD multiplier of the outlier test
    slow_min_samples: int = 3         # samples before a component is scored

    # -- AOT executable cache (dcnn_tpu/aot; docs/performance.md) --
    aot_cache_dir: Optional[str] = None  # cache ROOT: warm-start the
                                      # train/multi step from persisted
                                      # executables under <root>/aot and
                                      # commit fresh compiles there
                                      # (shareable across processes and
                                      # hosts). None: AOT_CACHE env, else
                                      # off.

    # -- external telemetry (dcnn_tpu/obs/server.py; docs/observability.md)
    metrics_port: int = -1            # >=0: serve /metrics + /healthz +
                                      # /snapshot over HTTP for the whole
                                      # fit() (0 = ephemeral port; -1 = off).
                                      # healthz wires the stall watchdog and
                                      # checkpoint health automatically
    flight_dir: Optional[str] = None  # failure flight recorder root
                                      # (obs/flight.py): degradation edges
                                      # (healthz 503, watchdog stall,
                                      # non-finite guard) dump atomic
                                      # keep-K postmortem bundles here.
                                      # Configures the process-global
                                      # recorder; None: DCNN_FLIGHT_DIR
                                      # env, else off

    @classmethod
    def load_from_env(cls) -> "TrainingConfig":
        """Environment-variable mapping mirroring ``train.hpp:80-100``."""
        base = cls()
        return cls(
            epochs=get_env("EPOCHS", base.epochs),
            batch_size=get_env("BATCH_SIZE", base.batch_size),
            learning_rate=get_env("LEARNING_RATE", base.learning_rate),
            lr_decay_factor=get_env("LR_DECAY_FACTOR", base.lr_decay_factor),
            lr_decay_interval=get_env("LR_DECAY_INTERVAL", base.lr_decay_interval),
            num_microbatches=get_env("NUM_MICROBATCHES", base.num_microbatches),
            device_type=get_env("DEVICE_TYPE", base.device_type),
            profiler=ProfilerType(get_env("PROFILER_TYPE", base.profiler.value).lower()),
            seed=get_env("SEED", base.seed),
            snapshot_dir=get_env("SNAPSHOT_DIR", base.snapshot_dir or "model_snapshots"),
            progress_interval=get_env("PROGRESS_INTERVAL", base.progress_interval),
            dtype=get_env("DTYPE", base.dtype),
            debug=get_env("DCNN_DEBUG", base.debug),
            scheduler_step=get_env("SCHEDULER_STEP", base.scheduler_step),
            steps_per_dispatch=get_env("STEPS_PER_DISPATCH",
                                       base.steps_per_dispatch),
            feed_workers=get_env("FEED_WORKERS", base.feed_workers),
            checkpoint_dir=get_env("CKPT_DIR", base.checkpoint_dir or "") or None,
            checkpoint_every=get_env("CKPT_EVERY", base.checkpoint_every),
            checkpoint_keep=get_env("CKPT_KEEP", base.checkpoint_keep),
            checkpoint_async=get_env("CKPT_ASYNC", base.checkpoint_async),
            resume=get_env("CKPT_RESUME", base.resume),
            nonfinite_policy=get_env("NONFINITE_POLICY", base.nonfinite_policy),
            rollback_after=get_env("ROLLBACK_AFTER", base.rollback_after),
            stall_timeout_s=get_env("STALL_TIMEOUT_S", base.stall_timeout_s),
            elastic=get_env("ELASTIC", base.elastic),
            elastic_peers=get_env("ELASTIC_PEERS", base.elastic_peers),
            elastic_rank=get_env("ELASTIC_RANK", base.elastic_rank),
            elastic_microbatches=get_env("ELASTIC_MICROBATCHES",
                                         base.elastic_microbatches),
            elastic_heartbeat_s=get_env("ELASTIC_HEARTBEAT_S",
                                        base.elastic_heartbeat_s),
            elastic_timeout_s=get_env("ELASTIC_TIMEOUT_S",
                                      base.elastic_timeout_s),
            elastic_ckpt_steps=get_env("ELASTIC_CKPT_STEPS",
                                       base.elastic_ckpt_steps),
            elastic_min_world=get_env("ELASTIC_MIN_WORLD",
                                      base.elastic_min_world),
            elastic_compress=get_env("ELASTIC_COMPRESS",
                                     base.elastic_compress),
            slow_detect=get_env("DCNN_SLOW_DETECT", base.slow_detect),
            slow_dwell_s=get_env("DCNN_SLOW_DWELL_S", base.slow_dwell_s),
            slow_ratio=get_env("DCNN_SLOW_RATIO", base.slow_ratio),
            slow_mad_k=get_env("DCNN_SLOW_MAD_K", base.slow_mad_k),
            slow_min_samples=get_env("DCNN_SLOW_MIN_SAMPLES",
                                     base.slow_min_samples),
            aot_cache_dir=get_env("AOT_CACHE",
                                  base.aot_cache_dir or "") or None,
            metrics_port=get_env("METRICS_PORT", base.metrics_port),
            flight_dir=get_env("DCNN_FLIGHT_DIR",
                               base.flight_dir or "") or None,
        )

    def to_dict(self) -> dict:
        d = asdict(self)
        d["profiler"] = self.profiler.value
        return d
