"""Hard device fences for wall-clock measurement.

``jax.block_until_ready`` is the documented way to await async dispatch, but
on proxied/tunnelled PJRT backends (e.g. the experimental ``axon`` TPU
tunnel in this environment) it can return before the device has actually
finished executing — we measured a chained 8192^3 bf16 matmul at an
impossible 51,000 TFLOP/s (260x the v5e peak) when fenced that way, vs a
sane 135 TFLOP/s (69% MFU) when fenced by a real device-to-host transfer.

The only fence that cannot lie is materializing device bytes on the host:
``jax.device_get`` must wait for the data to exist before it can copy it.
``hard_fence`` pulls a single element of every array leaf — O(leaves) tiny
transfers, negligible next to any workload worth timing.

Use this (never ``block_until_ready``) anywhere a wall-clock number is
derived: ``bench.py``, ``benchmarks/``, ``train/profiling.py``.

Reference equivalent: the reference times kernels around explicit
``cudaDeviceSynchronize`` (e.g. ``benchmarks/gemm_benchmark.cpp``); this is
the TPU-tunnel-safe analog.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.tree_util as jtu


@jax.jit
def _probe(leaves):
    """One scalar depending on one element of EVERY leaf: reading it back
    fences all of them with a single device->host round trip."""
    return sum((l.ravel()[0].astype(jnp.float32) for l in leaves),
               jnp.float32(0.0))


def hard_fence(tree) -> None:
    """Block until every array leaf in ``tree`` has finished computing.

    Implemented as a device->host transfer, which — unlike
    ``block_until_ready`` on proxied backends — is a true fence: the bytes
    cannot be produced before the producing computation completes.

    Multi-leaf trees are fenced through ONE jitted scalar that consumes an
    element of every leaf, then ONE readback. The per-leaf device_get loop
    this replaces cost a full tunnel round trip per leaf (~94 ms each on
    the axon backend — 7.9 s to fence a ResNet-18 param tree, which
    silently dominated any wall-clock it was part of). The probe executable
    is cached per tree structure/shapes, so steady-state cost is one
    dispatch + one RTT regardless of leaf count.
    """
    leaves = [l for l in jtu.tree_leaves(tree)
              if hasattr(l, "shape") and getattr(l, "size", 1) != 0]
    if not leaves:
        return

    def get_first(leaf):
        jax.device_get(leaf if leaf.ndim == 0 else leaf.ravel()[0])

    if len(leaves) == 1:
        get_first(leaves[0])
        return
    # one probe per device group: jit refuses mixed-device argument lists
    # (e.g. PipelineCoordinator.join fencing per-stage trees placed
    # round-robin across devices)
    groups = {}
    for leaf in leaves:
        try:
            # extended dtypes (typed PRNG keys) can't astype to f32 inside
            # the probe — keep them on the per-leaf path
            if not (jnp.issubdtype(leaf.dtype, jnp.number)
                    or jnp.issubdtype(leaf.dtype, jnp.bool_)):
                key = None
            else:
                key = frozenset(leaf.devices())
        except Exception:
            key = None
        groups.setdefault(key, []).append(leaf)
    for key, group in groups.items():
        if key is None or len(key) != 1 or len(group) == 1:
            # unknown placement or sharded across devices: the safe
            # per-leaf path (still one RTT per leaf, but only for these)
            for leaf in group:
                get_first(leaf)
        else:
            jax.device_get(_probe(group))
