"""Hard device fences for wall-clock measurement.

``jax.block_until_ready`` is the documented way to await async dispatch, but
on proxied/tunnelled PJRT backends (e.g. the experimental ``axon`` TPU
tunnel in this environment) it can return before the device has actually
finished executing — we measured a chained 8192^3 bf16 matmul at an
impossible 51,000 TFLOP/s (260x the v5e peak) when fenced that way, vs a
sane 135 TFLOP/s (69% MFU) when fenced by a real device-to-host transfer.

The only fence that cannot lie is materializing device bytes on the host:
``jax.device_get`` must wait for the data to exist before it can copy it.
``hard_fence`` pulls a single element of every array leaf — O(leaves) tiny
transfers, negligible next to any workload worth timing.

Use this (never ``block_until_ready``) anywhere a wall-clock number is
derived: ``bench.py``, ``benchmarks/``, ``train/profiling.py``.

Reference equivalent: the reference times kernels around explicit
``cudaDeviceSynchronize`` (e.g. ``benchmarks/gemm_benchmark.cpp``); this is
the TPU-tunnel-safe analog.
"""

from __future__ import annotations

import jax
import jax.tree_util as jtu


def hard_fence(tree) -> None:
    """Block until every array leaf in ``tree`` has finished computing.

    Implemented as a device->host transfer of one element per leaf, which —
    unlike ``block_until_ready`` on proxied backends — is a true fence: the
    bytes cannot be produced before the producing computation completes.
    """
    for leaf in jtu.tree_leaves(tree):
        if hasattr(leaf, "shape"):
            if getattr(leaf, "size", 1) == 0:
                continue
            first = leaf if leaf.ndim == 0 else leaf.ravel()[0]
            jax.device_get(first)
