"""Matmul/conv precision policy.

The reference computes in pure fp32 (SURVEY.md §7 hard part 6). On TPU the
MXU natively multiplies in bf16; XLA's *default* precision uses that fast
path, while ``HIGHEST`` runs fp32-equivalent multi-pass matmuls. Policy:

- ``"parity"`` (default): ``Precision.HIGHEST`` — numerics match the
  reference/torch to ~1e-5, used by tests and parity runs.
- ``"fast"``: ``Precision.DEFAULT`` — bf16 MXU passes, the TPU-idiomatic
  training mode used by benchmarks (top-1 parity for CNNs, ~2-8× matmul
  throughput).

Set globally via ``set_precision`` or the ``DCNN_PRECISION`` env var; ops read
it at trace time so a jit cache key change (re-trace) applies it.
"""

from __future__ import annotations

import os

from jax import lax

_MODES = {
    "parity": lax.Precision.HIGHEST,
    "highest": lax.Precision.HIGHEST,
    "fast": lax.Precision.DEFAULT,
    "default": lax.Precision.DEFAULT,
}

_current = os.environ.get("DCNN_PRECISION", "parity").lower()
if _current not in _MODES:
    _current = "parity"


def set_precision(mode: str) -> None:
    global _current
    mode = mode.lower()
    if mode not in _MODES:
        raise ValueError(f"unknown precision mode {mode!r}; known: {sorted(_MODES)}")
    _current = mode


def get_precision() -> lax.Precision:
    return _MODES[_current]


def get_precision_mode() -> str:
    return _current
