"""Matmul/conv precision policy.

The reference computes in pure fp32 (SURVEY.md §7 hard part 6). On TPU the
MXU natively multiplies in bf16; XLA's *default* precision uses that fast
path, while ``HIGHEST`` runs fp32-equivalent multi-pass matmuls. Policy:

- ``"parity"`` (default): ``Precision.HIGHEST`` — numerics match the
  reference/torch to ~1e-5, used by tests and parity runs.
- ``"fast"``: ``Precision.DEFAULT`` — bf16 MXU passes, fp32 activation
  storage (top-1 parity for CNNs, ~2-8× matmul throughput).
- ``"bf16"``: full mixed precision — activations and params-at-use are cast
  to bfloat16 (halving HBM traffic, the usual CNN bottleneck at 64×64), while
  master params, optimizer state, BN statistics and the loss stay fp32 (the
  standard mixed-precision recipe). Profiling showed the round-1 train step
  was dominated by fp32 elementwise/BN chains over [B,64,64,C] tensors, not
  by MXU time — this mode targets exactly that.

Set globally via ``set_precision`` or the ``DCNN_PRECISION`` env var; ops read
it at trace time so a jit cache key change (re-trace) applies it.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

_MODES = {
    "parity": lax.Precision.HIGHEST,
    "highest": lax.Precision.HIGHEST,
    "fast": lax.Precision.DEFAULT,
    "default": lax.Precision.DEFAULT,
    "bf16": lax.Precision.DEFAULT,
    # fp64: the reference's double-kernel path (dgemm.cpp, dkernels.cpp).
    # Enables jax_enable_x64, so layer init and Python-scalar promotion
    # produce float64 and every op computes in double (TPUs emulate fp64 in
    # software — this mode is for numerics auditing, not throughput).
    "fp64": lax.Precision.HIGHEST,
}

_current = os.environ.get("DCNN_PRECISION", "parity").lower()
if _current not in _MODES:
    _current = "parity"


def _sync_x64(mode: str) -> None:
    jax.config.update("jax_enable_x64", mode == "fp64")


if _current == "fp64":  # env-selected: enable x64 before any array exists
    _sync_x64(_current)


def set_precision(mode: str) -> None:
    global _current
    mode = mode.lower()
    if mode not in _MODES:
        raise ValueError(f"unknown precision mode {mode!r}; known: {sorted(_MODES)}")
    if (mode == "fp64") != (_current == "fp64"):
        _sync_x64(mode)
    _current = mode


def get_precision() -> lax.Precision:
    return _MODES[_current]


def get_precision_mode() -> str:
    return _current


def get_compute_dtype() -> Optional[Any]:
    """Activation/param compute dtype for the current mode, or None when the
    mode computes in the storage dtype (parity/fast)."""
    if _current == "bf16":
        return jnp.bfloat16
    if _current == "fp64":
        return jnp.float64
    return None


def precision_keyed_jit(f, **jit_kwargs):
    """``jax.jit`` with the global precision mode added to the cache key.

    Ops read the mode at trace time, so a ``set_precision`` switch must force
    a re-trace — fp32 inputs alone hash identically and would keep serving
    the previously-traced executable (ADVICE r2 #4). Any module-level jit
    whose trace reads :func:`get_precision` / :func:`get_compute_dtype` must
    use this instead of ``jax.jit``. Extra ``static_argnames`` compose (pass
    those arguments by keyword). The underlying jitted function is exposed as
    ``wrapped._jitted`` (e.g. for cache-size introspection in tests).
    """
    import functools

    def g(*args, _precision_mode=None, **kwargs):
        del _precision_mode  # cache key only
        return f(*args, **kwargs)

    extra = jit_kwargs.pop("static_argnames", ())
    if isinstance(extra, str):   # jax.jit accepts a bare string; match it
        extra = (extra,)
    static = tuple(extra) + ("_precision_mode",)
    jg = jax.jit(g, static_argnames=static, **jit_kwargs)

    @functools.wraps(f)
    def wrapped(*args, **kwargs):
        return jg(*args, _precision_mode=get_precision_mode(), **kwargs)

    wrapped._jitted = jg
    return wrapped


def cast_to_compute(tree: Any) -> Any:
    """Cast every floating leaf of ``tree`` to the compute dtype (no-op unless
    mode is bf16). Used on params *at point of use* — master copies stay fp32,
    and autodiff through the cast delivers fp32 gradients."""
    cdt = get_compute_dtype()
    if cdt is None:
        return tree
    return jax.tree_util.tree_map(
        lambda a: a.astype(cdt)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != cdt
        else a,
        tree)
