"""Debug mode: numeric sanitizers for training runs.

Reference equivalent: the ``ENABLE_DEBUG`` build flag, which produces a
Debug + AddressSanitizer build (``/root/reference/CMakeLists.txt:22,30-32``,
``cmake/CompilerFlags.cmake``, ``build.sh --debug``). Memory errors are not a
failure class for JAX programs (no manual buffers to overrun), so the
TPU-native analog sanitizes the failure class that *does* exist here:
silent numeric corruption (NaN/Inf propagation, out-of-bounds gathers
clamping silently, div-by-zero producing Inf).

Two tiers, both opt-in (like the reference's debug build):

- :func:`enable_debug_mode` / :func:`debug_mode` — flips ``jax_debug_nans``
  (every jitted computation re-checked; on NaN the op is re-run un-jitted to
  pinpoint the producing primitive) and optionally ``jax_enable_checks``
  (internal invariant checks). Process-global, like a sanitizer build.
- :func:`checked` — wraps a jitted step with ``jax.experimental.checkify``
  (float + index + div checks): the returned step raises a located error
  (primitive + source line) instead of training on garbage. Works under jit
  on any backend, including inside scans where jax_debug_nans cannot look.

Env var ``DCNN_DEBUG=1`` (reference ``.env`` style, ``env.hpp:41``) enables
the global mode at ``import dcnn_tpu``; ``TrainingConfig(debug=True)`` does
the same per-trainer.
"""

from __future__ import annotations

import contextlib
from typing import Callable

import jax


def enable_debug_mode(nans: bool = True, checks: bool = False) -> None:
    """Process-global numeric sanitizer (the 'debug build')."""
    jax.config.update("jax_debug_nans", bool(nans))
    if checks:
        jax.config.update("jax_enable_checks", True)


def disable_debug_mode() -> None:
    jax.config.update("jax_debug_nans", False)
    jax.config.update("jax_enable_checks", False)


@contextlib.contextmanager
def debug_mode(nans: bool = True, checks: bool = False):
    """Scoped debug mode; restores previous flags on exit."""
    prev_nans = jax.config.jax_debug_nans
    prev_checks = jax.config.jax_enable_checks
    try:
        enable_debug_mode(nans=nans, checks=checks)
        yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_enable_checks", prev_checks)


def checked(step_fn: Callable, jit: bool = True) -> Callable:
    """Wrap a (possibly jitted) step function with checkify float/index/div
    checks. The wrapper raises ``jax.experimental.checkify.JaxRuntimeError``
    with the failing primitive and source location the first time a NaN/Inf,
    out-of-bounds index, or div-by-zero is produced — instead of training on
    silently corrupted numbers.

    ``step = checked(make_train_step(model, loss, opt, jit=False))``
    """
    from jax.experimental import checkify

    errors = (checkify.float_checks | checkify.index_checks
              | checkify.div_checks)
    cf = checkify.checkify(step_fn, errors=errors)
    if jit:
        cf = jax.jit(cf)

    def wrapper(*args, **kwargs):
        err, out = cf(*args, **kwargs)
        err.throw()
        return out

    return wrapper
