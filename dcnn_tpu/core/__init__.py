"""Core runtime: device discovery, mesh helpers, configuration.

TPU-native analog of the reference's device runtime layer
(``include/device/device_manager.hpp``, ``include/device/context.hpp``): where
the reference discovers CPU + CUDA devices and hands out contexts/streams, we
discover JAX backends (TPU/CPU) and hand out devices and ``jax.sharding.Mesh``
objects. There is no Task/Flow analog — XLA's async dispatch already provides
the "every op returns an async handle" model the reference built by hand
(SURVEY.md §1, "Async task model").
"""

from .device import DeviceManager, default_device, device_count, local_devices
from .fence import hard_fence
from .mesh import make_mesh, mesh_axes
from .config import TrainingConfig

__all__ = [
    "DeviceManager",
    "default_device",
    "device_count",
    "local_devices",
    "hard_fence",
    "make_mesh",
    "mesh_axes",
    "TrainingConfig",
]
