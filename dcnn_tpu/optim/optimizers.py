"""Optimizers as pure pytree transforms.

Reference equivalent: ``Optimizer/SGD/Adam`` + fused CPU/CUDA update kernels
(``include/nn/optimizers.hpp:89-306``,
``src/nn/optimizers_impl/cpu/{sgd,adam}_kernels.cpp``). Update rules are
reproduced exactly:

- SGD: ``p -= lr·g``; momentum: ``v = μ·v − lr·g; p += v``
  (sgd_kernels.cpp:16-30 — note velocity carries the lr, PyTorch-style
  "dampened" form is NOT used).
- Adam: m/v moments with bias correction ``m̂ = m/(1−β₁ᵗ)``; non-decoupled
  weight decay is added to the *update* (not the gradient), decoupled (AdamW)
  multiplies params by ``(1 − wd·lr)`` — both exactly as
  adam_kernels.cpp:29-56.

TPU-native shape: instead of mutating attached tensors, each optimizer is
``init(params) -> opt_state`` + ``update(grads, opt_state, params, lr) ->
(new_params, new_opt_state)``, jit-safe and pipeline-shardable. ``lr`` is a
traced argument so LR schedules don't trigger recompilation. Opt state is a
pytree → it checkpoints (the reference drops Adam moments on save,
SURVEY.md §5.4; we do not).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


OptState = Dict[str, Any]


class Optimizer:
    """Base: stateless spec; all state is in the opt_state pytree."""

    def __init__(self, learning_rate: float = 0.01):
        self.learning_rate = float(learning_rate)

    def init(self, params) -> OptState:
        raise NotImplementedError

    def update(self, grads, opt_state: OptState, params, lr: Optional[jax.Array] = None,
               ) -> Tuple[Any, OptState]:
        raise NotImplementedError

    # -- config round-trip (reference OptimizerConfig JSON, optimizers.hpp:25-87) --
    def get_config(self) -> Dict[str, Any]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    # -- pipeline split/merge (parallel/distributed_pipeline.py recovery) --
    #
    # Structural contract shared by the built-in optimizers: ``opt_state``
    # is a dict whose values are either *per-layer* sequences shaped like
    # the params tuple (SGD velocity, Adam m/v — split/concatenated along
    # layer ranges) or *whole-run* leaves identical on every stage (Adam's
    # step counter t — replicated on split, taken from the first stage on
    # merge). A custom optimizer whose state breaks this convention must
    # override both methods; the pipeline recovery path round-trips
    # optimizer state through them so a repartition preserves momentum.

    def split_state(self, opt_state: OptState,
                    partitions) -> "list[OptState]":
        """Partition a full-model optimizer state alongside
        ``Sequential.split_params`` into one state per layer-range."""
        total = max(end for _, end in partitions)
        out = []
        for start, end in partitions:
            st: OptState = {}
            for k, v in opt_state.items():
                if isinstance(v, (tuple, list)) and len(v) == total:
                    st[k] = tuple(v[start:end])
                else:
                    st[k] = v
            out.append(st)
        return out

    def merge_state(self, states, partitions) -> OptState:
        """Inverse of :meth:`split_state`: concatenate per-layer sequences
        across the stage states (given in partition order), keep the first
        stage's copy of whole-run leaves (identical by construction — the
        stages apply updates in lockstep)."""
        merged: OptState = {}
        for k, v0 in states[0].items():
            if isinstance(v0, (tuple, list)):
                seq: list = []
                for st in states:
                    seq.extend(st[k])
                merged[k] = tuple(seq)
            else:
                merged[k] = v0
        return merged


class SGD(Optimizer):
    def __init__(self, learning_rate: float = 0.01, momentum: float = 0.0):
        super().__init__(learning_rate)
        self.momentum = float(momentum)

    def init(self, params) -> OptState:
        if self.momentum > 0.0:
            return {"velocity": jax.tree_util.tree_map(jnp.zeros_like, params)}
        return {}

    def update(self, grads, opt_state, params, lr=None):
        lr = self.learning_rate if lr is None else lr
        if self.momentum > 0.0:
            mu = self.momentum
            new_v = jax.tree_util.tree_map(
                lambda v, g: mu * v - lr * g, opt_state["velocity"], grads)
            new_params = jax.tree_util.tree_map(lambda p, v: p + v, params, new_v)
            return new_params, {"velocity": new_v}
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new_params, {}

    def get_config(self):
        return {"type": "sgd", "learning_rate": self.learning_rate, "momentum": self.momentum}


class Adam(Optimizer):
    def __init__(self, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 weight_decay: float = 0.0, decouple_weight_decay: bool = False):
        super().__init__(learning_rate)
        self.beta1, self.beta2 = float(beta1), float(beta2)
        self.epsilon = float(epsilon)
        self.weight_decay = float(weight_decay)
        self.decouple_weight_decay = bool(decouple_weight_decay)

    def init(self, params) -> OptState:
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"m": zeros(), "v": zeros(), "t": jnp.zeros((), jnp.int32)}

    def update(self, grads, opt_state, params, lr=None):
        lr = self.learning_rate if lr is None else lr
        b1, b2, eps, wd = self.beta1, self.beta2, self.epsilon, self.weight_decay
        t = opt_state["t"] + 1
        bc1 = 1.0 - b1 ** t.astype(jnp.float32)
        bc2 = 1.0 - b2 ** t.astype(jnp.float32)

        new_m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                       opt_state["m"], grads)
        new_v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                                       opt_state["v"], grads)

        def step(p, m, v):
            update = lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if wd > 0.0:
                if self.decouple_weight_decay:
                    p = p - wd * lr * p            # AdamW (adam_kernels.cpp:48)
                else:
                    update = update + wd * lr * p  # L2-in-update (adam_kernels.cpp:51)
            return p - update

        new_params = jax.tree_util.tree_map(step, params, new_m, new_v)
        return new_params, {"m": new_m, "v": new_v, "t": t}

    def name(self):
        return "AdamW" if self.decouple_weight_decay else "Adam"

    def get_config(self):
        return {"type": "adamw" if self.decouple_weight_decay else "adam",
                "learning_rate": self.learning_rate, "beta1": self.beta1,
                "beta2": self.beta2, "epsilon": self.epsilon,
                "weight_decay": self.weight_decay,
                "decouple_weight_decay": self.decouple_weight_decay}


def AdamW(learning_rate: float = 0.001, beta1: float = 0.9, beta2: float = 0.999,
          epsilon: float = 1e-8, weight_decay: float = 0.01) -> Adam:
    """AdamW = Adam with decoupled decay (reference names it the same way,
    optimizers.hpp:241)."""
    return Adam(learning_rate, beta1, beta2, epsilon, weight_decay,
                decouple_weight_decay=True)


class OptimizerFactory:
    """String/JSON-keyed construction (reference
    ``OptimizerFactory::create_from_config``, optimizers.hpp:285-306)."""

    @staticmethod
    def create_from_config(cfg: Dict[str, Any]) -> Optimizer:
        ty = cfg.get("type", "sgd").lower()
        kw = {k: v for k, v in cfg.items() if k != "type"}
        if ty == "sgd":
            return SGD(**kw)
        if ty == "adam":
            return Adam(**kw)
        if ty == "adamw":
            kw.pop("decouple_weight_decay", None)
            return AdamW(**kw)
        raise ValueError(f"unknown optimizer type {ty!r}")
