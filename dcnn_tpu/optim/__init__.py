"""Optimizers and LR schedulers.

Reference equivalent: ``include/nn/optimizers.hpp`` (SGD/Adam/AdamW with fused
update kernels) and ``include/nn/schedulers.hpp`` (10 scheduler families).
"""

from .optimizers import SGD, Adam, AdamW, Optimizer, OptimizerFactory
from .schedulers import (
    StepLR, MultiStepLR, ExponentialLR, CosineAnnealingLR,
    CosineAnnealingWarmRestarts, LinearWarmup, WarmupCosineAnnealing,
    ReduceLROnPlateau, PolynomialLR, OneCycleLR, SchedulerFactory,
)

__all__ = [
    "Optimizer", "SGD", "Adam", "AdamW", "OptimizerFactory",
    "StepLR", "MultiStepLR", "ExponentialLR", "CosineAnnealingLR",
    "CosineAnnealingWarmRestarts", "LinearWarmup", "WarmupCosineAnnealing",
    "ReduceLROnPlateau", "PolynomialLR", "OneCycleLR", "SchedulerFactory",
]
