"""Learning-rate schedulers.

Reference equivalent: the 10 scheduler classes + factory in
``include/nn/schedulers.hpp:42-698``. Formulas reproduced exactly, including
quirks: StepLR multiplies the *current* lr every ``step_size`` steps
(:66-68), CosineAnnealingLR wraps with ``step % T_max`` (:183), OneCycleLR's
down phase is cosine (:553-561).

Each scheduler is a small stateful object (``step() -> lr``), mirroring the
reference's per-epoch ``step()`` driven by the trainer; the returned lr is fed
into the jitted train step as a traced scalar, so changing lr never
recompiles.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence


class Scheduler:
    """Base (reference ``Scheduler<T>``, schedulers.hpp:42): tracks a step
    counter and the current lr derived from ``base_lr``."""

    def __init__(self, base_lr: float):
        self.base_lr = float(base_lr)
        self.lr = float(base_lr)
        self.current_step = 0

    def step(self, metric: Optional[float] = None) -> float:
        self.current_step += 1
        self.lr = self._compute_lr(metric)
        return self.lr

    def _compute_lr(self, metric: Optional[float]) -> float:
        return self.lr

    def get_lr(self) -> float:
        return self.lr

    def reset(self) -> None:
        self.current_step = 0
        self.lr = self.base_lr

    def name(self) -> str:
        return type(self).__name__

    def get_config(self) -> Dict[str, Any]:
        return {"type": "scheduler", "base_lr": self.base_lr}


class StepLR(Scheduler):
    """Multiply lr by gamma every ``step_size`` steps (schedulers.hpp:59-90)."""

    def __init__(self, base_lr: float, step_size: int, gamma: float = 0.1):
        super().__init__(base_lr)
        self.step_size = int(step_size)
        self.gamma = float(gamma)

    def _compute_lr(self, metric):
        if self.current_step % self.step_size == 0:
            return self.lr * self.gamma
        return self.lr

    def get_config(self):
        return {"type": "step_lr", "base_lr": self.base_lr,
                "step_size": self.step_size, "gamma": self.gamma}


class MultiStepLR(Scheduler):
    """Multiply lr by gamma at each milestone (schedulers.hpp:96-137)."""

    def __init__(self, base_lr: float, milestones: Sequence[int], gamma: float = 0.1):
        super().__init__(base_lr)
        self.milestones: List[int] = sorted(int(m) for m in milestones)
        self.gamma = float(gamma)
        self._idx = 0

    def _compute_lr(self, metric):
        if self._idx < len(self.milestones) and self.current_step >= self.milestones[self._idx]:
            self._idx += 1
            return self.lr * self.gamma
        return self.lr

    def reset(self):
        super().reset()
        self._idx = 0

    def get_config(self):
        return {"type": "multi_step_lr", "base_lr": self.base_lr,
                "milestones": self.milestones, "gamma": self.gamma}


class ExponentialLR(Scheduler):
    """lr *= gamma every step (schedulers.hpp:143-170)."""

    def __init__(self, base_lr: float, gamma: float = 0.95):
        super().__init__(base_lr)
        self.gamma = float(gamma)

    def _compute_lr(self, metric):
        return self.lr * self.gamma

    def get_config(self):
        return {"type": "exponential_lr", "base_lr": self.base_lr, "gamma": self.gamma}


class CosineAnnealingLR(Scheduler):
    """Cosine from base_lr to eta_min over T_max, wrapping (schedulers.hpp:176-208)."""

    def __init__(self, base_lr: float, T_max: int, eta_min: float = 0.0):
        super().__init__(base_lr)
        self.T_max = int(T_max)
        self.eta_min = float(eta_min)

    def _compute_lr(self, metric):
        step = self.current_step % self.T_max
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1.0 + math.cos(math.pi * step / self.T_max)) / 2.0

    def get_config(self):
        return {"type": "cosine_annealing_lr", "base_lr": self.base_lr,
                "T_max": self.T_max, "eta_min": self.eta_min}


class CosineAnnealingWarmRestarts(Scheduler):
    """SGDR restarts: cycle length T_i starts at T_0 and multiplies by T_mult
    (schedulers.hpp:214-263)."""

    def __init__(self, base_lr: float, T_0: int, T_mult: int = 1, eta_min: float = 0.0):
        super().__init__(base_lr)
        self.T_0 = int(T_0)
        self.T_mult = int(T_mult)
        self.eta_min = float(eta_min)
        self.T_cur = 0
        self.T_i = self.T_0

    def _compute_lr(self, metric):
        self.T_cur += 1
        if self.T_cur >= self.T_i:
            self.T_cur = 0
            self.T_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1.0 + math.cos(math.pi * self.T_cur / self.T_i)) / 2.0

    def reset(self):
        super().reset()
        self.T_cur = 0
        self.T_i = self.T_0

    def get_config(self):
        return {"type": "cosine_annealing_warm_restarts", "base_lr": self.base_lr,
                "T_0": self.T_0, "T_mult": self.T_mult, "eta_min": self.eta_min}


class LinearWarmup(Scheduler):
    """Linear start_lr → base_lr over warmup_steps (schedulers.hpp:270-307)."""

    def __init__(self, base_lr: float, warmup_steps: int, start_lr: float = 0.0):
        super().__init__(base_lr)
        self.warmup_steps = int(warmup_steps)
        self.start_lr = float(start_lr)
        self.lr = self.start_lr

    def _compute_lr(self, metric):
        if self.current_step <= self.warmup_steps:
            progress = self.current_step / self.warmup_steps
            return self.start_lr + progress * (self.base_lr - self.start_lr)
        return self.lr

    def reset(self):
        super().reset()
        self.lr = self.start_lr

    def get_config(self):
        return {"type": "linear_warmup", "base_lr": self.base_lr,
                "warmup_steps": self.warmup_steps, "start_lr": self.start_lr}


class WarmupCosineAnnealing(Scheduler):
    """Linear warmup then cosine decay to eta_min (schedulers.hpp:313-410)."""

    def __init__(self, base_lr: float, warmup_steps: int, total_steps: int,
                 start_lr: float = 0.0, eta_min: float = 0.0):
        super().__init__(base_lr)
        self.warmup_steps = int(warmup_steps)
        self.total_steps = int(total_steps)
        self.start_lr = float(start_lr)
        self.eta_min = float(eta_min)
        self.lr = self.start_lr

    def _compute_lr(self, metric):
        if self.current_step <= self.warmup_steps:
            progress = self.current_step / max(self.warmup_steps, 1)
            return self.start_lr + progress * (self.base_lr - self.start_lr)
        decay_steps = max(self.total_steps - self.warmup_steps, 1)
        cur = min(self.current_step - self.warmup_steps, decay_steps)
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1.0 + math.cos(math.pi * cur / decay_steps)) / 2.0

    def reset(self):
        super().reset()
        self.lr = self.start_lr

    def get_config(self):
        return {"type": "warmup_cosine_annealing", "base_lr": self.base_lr,
                "warmup_steps": self.warmup_steps, "total_steps": self.total_steps,
                "start_lr": self.start_lr, "eta_min": self.eta_min}


class ReduceLROnPlateau(Scheduler):
    """Multiply lr by ``factor`` after ``patience`` steps without metric
    improvement beyond ``threshold`` (schedulers.hpp:412-489)."""

    def __init__(self, base_lr: float, mode: str = "min", factor: float = 0.1,
                 patience: int = 10, threshold: float = 1e-4, min_lr: float = 0.0):
        super().__init__(base_lr)
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.mode = mode
        self.factor = float(factor)
        self.patience = int(patience)
        self.threshold = float(threshold)
        self.min_lr = float(min_lr)
        self.best = math.inf if mode == "min" else -math.inf
        self.bad_epochs = 0

    def _compute_lr(self, metric):
        if metric is None:
            return self.lr
        improved = (metric < self.best - self.threshold) if self.mode == "min" \
            else (metric > self.best + self.threshold)
        if improved:
            self.best = metric
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
            if self.bad_epochs > self.patience:
                self.bad_epochs = 0
                return max(self.lr * self.factor, self.min_lr)
        return self.lr

    def reset(self):
        super().reset()
        self.best = math.inf if self.mode == "min" else -math.inf
        self.bad_epochs = 0

    def get_config(self):
        return {"type": "reduce_lr_on_plateau", "base_lr": self.base_lr,
                "mode": self.mode, "factor": self.factor, "patience": self.patience,
                "threshold": self.threshold, "min_lr": self.min_lr}


class PolynomialLR(Scheduler):
    """(base−end)·(1−t/T)^power + end (schedulers.hpp:494-529)."""

    def __init__(self, base_lr: float, total_steps: int, power: float = 1.0,
                 end_lr: float = 0.0):
        super().__init__(base_lr)
        self.total_steps = int(total_steps)
        self.power = float(power)
        self.end_lr = float(end_lr)

    def _compute_lr(self, metric):
        progress = min(self.current_step / self.total_steps, 1.0)
        return (self.base_lr - self.end_lr) * (1.0 - progress) ** self.power + self.end_lr

    def get_config(self):
        return {"type": "polynomial_lr", "base_lr": self.base_lr,
                "total_steps": self.total_steps, "power": self.power,
                "end_lr": self.end_lr}


class OneCycleLR(Scheduler):
    """1cycle: linear up to max_lr for pct_start, cosine down to
    max_lr/div_factor/final_div_factor (schedulers.hpp:533-596)."""

    def __init__(self, max_lr: float, total_steps: int, pct_start: float = 0.3,
                 div_factor: float = 25.0, final_div_factor: float = 1e4):
        self.max_lr = float(max_lr)
        self.total_steps = int(total_steps)
        self.pct_start = float(pct_start)
        self.div_factor = float(div_factor)
        self.final_div_factor = float(final_div_factor)
        self.initial_lr = self.max_lr / self.div_factor
        self.min_lr = self.initial_lr / self.final_div_factor
        self.step_up = int(self.total_steps * self.pct_start)
        self.step_down = self.total_steps - self.step_up
        super().__init__(self.initial_lr)

    def _compute_lr(self, metric):
        if self.current_step <= self.step_up:
            progress = self.current_step / max(self.step_up, 1)
            return self.initial_lr + progress * (self.max_lr - self.initial_lr)
        progress = (self.current_step - self.step_up) / max(self.step_down, 1)
        return self.min_lr + (self.max_lr - self.min_lr) * \
            (1.0 + math.cos(math.pi * progress)) / 2.0

    def get_config(self):
        return {"type": "one_cycle_lr", "max_lr": self.max_lr,
                "total_steps": self.total_steps, "pct_start": self.pct_start,
                "div_factor": self.div_factor, "final_div_factor": self.final_div_factor}


class SchedulerFactory:
    """String/JSON construction (reference ``SchedulerFactory``,
    schedulers.hpp:598-698)."""

    _TYPES = {
        "step_lr": StepLR,
        "multi_step_lr": MultiStepLR,
        "exponential_lr": ExponentialLR,
        "cosine_annealing_lr": CosineAnnealingLR,
        "cosine_annealing_warm_restarts": CosineAnnealingWarmRestarts,
        "linear_warmup": LinearWarmup,
        "warmup_cosine_annealing": WarmupCosineAnnealing,
        "reduce_lr_on_plateau": ReduceLROnPlateau,
        "polynomial_lr": PolynomialLR,
        "one_cycle_lr": OneCycleLR,
    }

    @classmethod
    def create(cls, name: str, base_lr: float, **params) -> Scheduler:
        if name not in cls._TYPES:
            raise ValueError(f"Unknown scheduler type: {name}")
        if name == "one_cycle_lr":
            params.setdefault("max_lr", base_lr)
            return OneCycleLR(**params)
        return cls._TYPES[name](base_lr, **params)

    @classmethod
    def create_from_config(cls, cfg: Dict[str, Any]) -> Scheduler:
        cfg = dict(cfg)
        ty = cfg.pop("type")
        base_lr = cfg.pop("base_lr", None)
        if ty == "one_cycle_lr":
            return OneCycleLR(**cfg)
        return cls._TYPES[ty](base_lr, **cfg)
