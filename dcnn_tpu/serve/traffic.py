"""Open-loop traffic generation for serving measurement.

One pacing loop, shared by every measurement surface
(``examples/serve_snapshot.py``, ``examples/serve_autoscale.py``,
``bench.py`` serve/autoscale sections, the soak tests) so the load they
report is generated identically.

Open-loop means arrivals follow the offered rate regardless of
completions — the honest way to measure an overloaded server: a closed
loop self-throttles to whatever the server sustains and hides exactly the
queue growth that load shedding exists to bound. When the generator falls
behind schedule (a slow ``submit`` or scheduler hiccup) it does not sleep
until it has caught back up, preserving the offered average rate.

``offered_rps`` may be a constant (the PR-2 contract, unchanged) or a
**rate schedule** — any ``f(t_rel) -> rps`` over seconds since the run
started. The schedule constructors below (:func:`diurnal`,
:func:`spike`, :func:`step`) are the shared vocabulary of the autoscaler
example, the ``BENCH_AUTOSCALE`` bench block, and the diurnal soak test,
so all three offer byte-identical load for the same parameters. Pacing
under a schedule integrates arrival-by-arrival: the gap after an arrival
at ``t`` is ``1 / rate(t)``, so the instantaneous offered rate tracks
the schedule exactly (not a stair-step average over the run).
"""

from __future__ import annotations

import math
import time
from typing import Callable, List, Sequence, Tuple, Union

from .batcher import DynamicBatcher, QueueFullError

#: A time-varying offered rate: seconds since the run started -> rps.
RateFn = Callable[[float], float]


def diurnal(peak_rps: float, trough_rps: float, period_s: float, *,
            phase_s: float = 0.0) -> RateFn:
    """Sinusoidal day/night curve between ``trough_rps`` and ``peak_rps``
    with period ``period_s``; the run starts at the trough (shift with
    ``phase_s``). ``peak/trough`` is the peak-to-trough ratio the
    autoscale soak gates on (10x in the acceptance run)."""
    if not 0 < trough_rps <= peak_rps:
        raise ValueError(f"need 0 < trough <= peak, got "
                         f"{trough_rps}/{peak_rps}")
    if period_s <= 0:
        raise ValueError(f"period_s must be > 0, got {period_s}")
    mid = (peak_rps + trough_rps) / 2.0
    amp = (peak_rps - trough_rps) / 2.0

    def rate(t: float) -> float:
        # cos starts at the trough: -cos(0) = -1
        return mid - amp * math.cos(2.0 * math.pi * (t + phase_s)
                                    / period_s)
    return rate


def spike(base_rps: float, spike_rps: float, at_s: float,
          width_s: float) -> RateFn:
    """Flat ``base_rps`` with a rectangular burst to ``spike_rps`` over
    ``[at_s, at_s + width_s)`` — the traffic-surge fixture the device
    lease handoff test drives."""
    if base_rps <= 0 or spike_rps <= 0:
        raise ValueError("rates must be > 0")
    if width_s <= 0:
        raise ValueError(f"width_s must be > 0, got {width_s}")

    def rate(t: float) -> float:
        return spike_rps if at_s <= t < at_s + width_s else base_rps
    return rate


def step(levels: Sequence[Tuple[float, float]]) -> RateFn:
    """Piecewise-constant schedule from ``(from_s, rps)`` pairs: the rate
    holds each level from its start time until the next level's. The
    first level must start at 0 so the rate is defined everywhere."""
    lv = sorted((float(t), float(r)) for t, r in levels)
    if not lv or lv[0][0] != 0.0:
        raise ValueError("levels must be non-empty and start at t=0")
    if any(r <= 0 for _, r in lv):
        raise ValueError("every level's rps must be > 0")

    def rate(t: float) -> float:
        cur = lv[0][1]
        for start, r in lv:
            if t < start:
                break
            cur = r
        return cur
    return rate


def open_loop(batcher: DynamicBatcher, samples: Sequence,
              offered_rps: Union[float, RateFn], seconds: float, *,
              clock: Callable[[], float] = time.monotonic,
              sleep: Callable[[float], None] = time.sleep
              ) -> List[Tuple[int, "object"]]:
    """Submit single-sample requests from ``samples`` (cycled) at the
    offered rate (constant or a :data:`RateFn` schedule) for ``seconds``.
    Returns ``[(sample_index, future), ...]`` for every accepted request;
    shed requests are counted by the batcher's metrics. ``clock``/
    ``sleep`` are injectable like everywhere else in the serve stack."""
    if callable(offered_rps):
        rate: RateFn = offered_rps
        if rate(0.0) <= 0:
            raise ValueError("rate schedule must be > 0 at t=0")
    else:
        if offered_rps <= 0:
            raise ValueError(f"offered_rps must be > 0, got {offered_rps}")
        rate = lambda t, r=float(offered_rps): r  # noqa: E731
    futs: List[Tuple[int, object]] = []
    t0 = clock()
    # schedule time accumulates on a nanosecond grid: without the
    # rounding, fifty 0.1s gaps land at 4.999999999999998 and a schedule
    # breakpoint at t=5.0 is evaluated one full slow-rate gap late
    t_rel, i = 0.0, 0
    while t_rel < seconds:
        dt = (t0 + t_rel) - clock()
        if dt > 0:
            sleep(dt)
        k = i % len(samples)
        try:
            futs.append((k, batcher.submit(samples[k])))
        except QueueFullError:
            pass  # shed — the valve working as designed
        i += 1
        r = rate(t_rel)
        if not (r > 0):          # also catches NaN
            raise ValueError(f"rate schedule returned {r} at "
                             f"t={t_rel:.3f}; rates must stay > 0")
        nxt = round(t_rel + 1.0 / r, 9)
        if nxt <= t_rel:
            # inf or >~2e9 rps: the per-arrival gap rounds to zero on
            # the nanosecond grid — raising beats spinning forever
            raise ValueError(
                f"rate schedule returned {r} rps at t={t_rel:.3f}; "
                f"the per-arrival gap rounds to zero on the nanosecond "
                f"grid")
        t_rel = nxt
    return futs
