"""Open-loop traffic generation for serving measurement.

One pacing loop, shared by every measurement surface
(``examples/serve_snapshot.py``, ``bench.py serve_section``, the real-time
soak test) so the load they report is generated identically.

Open-loop means arrivals follow the offered rate regardless of
completions — the honest way to measure an overloaded server: a closed
loop self-throttles to whatever the server sustains and hides exactly the
queue growth that load shedding exists to bound. When the generator falls
behind schedule (a slow ``submit`` or scheduler hiccup) it does not sleep
until it has caught back up, preserving the offered average rate.
"""

from __future__ import annotations

import time
from typing import Callable, List, Sequence, Tuple

from .batcher import DynamicBatcher, QueueFullError


def open_loop(batcher: DynamicBatcher, samples: Sequence, offered_rps: float,
              seconds: float, *, clock: Callable[[], float] = time.monotonic,
              sleep: Callable[[float], None] = time.sleep
              ) -> List[Tuple[int, "object"]]:
    """Submit single-sample requests from ``samples`` (cycled) at a fixed
    offered rate for ``seconds``. Returns ``[(sample_index, future), ...]``
    for every accepted request; shed requests are counted by the batcher's
    metrics. ``clock``/``sleep`` are injectable like everywhere else in
    the serve stack."""
    if offered_rps <= 0:
        raise ValueError(f"offered_rps must be > 0, got {offered_rps}")
    futs: List[Tuple[int, object]] = []
    t0 = clock()
    t_next, i = t0, 0
    while t_next < t0 + seconds:
        dt = t_next - clock()
        if dt > 0:
            sleep(dt)
        k = i % len(samples)
        try:
            futs.append((k, batcher.submit(samples[k])))
        except QueueFullError:
            pass  # shed — the valve working as designed
        i += 1
        t_next += 1.0 / offered_rps
    return futs
