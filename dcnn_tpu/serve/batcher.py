"""Dynamic batcher: the online half of the serving stack.

Single requests arrive asynchronously; TPU throughput lives at large
batches. The classic reconciliation (Clipper NSDI'17; TF-Serving's batching
scheduler) is a **batching window**: hold the first request at most
``max_wait_ms``, group everything that arrives meanwhile up to
``max_batch``, run once, scatter results. This module implements that with

- a **bounded queue** (capacity in samples) — the load-shedding valve:
  beyond capacity, :meth:`DynamicBatcher.submit` raises
  :class:`QueueFullError` *immediately* instead of letting latency grow
  without bound (an overloaded server that queues forever serves nobody;
  one that sheds keeps its p99 for the traffic it accepts);
- a dispatcher thread that pops a batch when it is **due** — queue holds
  ``max_batch`` samples, or the oldest request has waited ``max_wait_ms``,
  or the batcher is draining — pads it to the engine's nearest bucket,
  runs the pre-compiled session, and resolves per-request futures;
- graceful teardown with a **no-orphan guarantee**: :meth:`drain` stops
  intake and completes everything already accepted; :meth:`shutdown`
  with ``drain=False`` fails still-queued requests with
  :class:`ShutdownError`; and a :meth:`drain` that trips its ``timeout``
  fails every still-pending future the same way before raising — a caller
  blocked on ``future.result()`` is *always* released, never left parked
  on a future nobody will resolve.

Determinism for tests: with ``start=False`` no thread runs and
:meth:`step` dispatches synchronously; combined with an injectable
``clock`` the whole submit → deadline → dispatch → latency pipeline is
exercised sleep-free (``tests/test_serve.py``). The threaded mode uses the
same ``_pop_due`` core, so the sleep-free tests cover the real dispatch
logic, not a test-only twin.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Callable, List, Optional

import numpy as np

from ..obs import get_tracer
from ..obs.xla import sample_hbm
from .engine import InferenceEngine
from .metrics import ServeMetrics


class QueueFullError(RuntimeError):
    """Backpressure: the bounded request queue is at capacity."""


class DrainingError(RuntimeError):
    """Intake refused because the batcher is draining or shut down — a
    *typed* rejection, so a router can tell "replica temporarily not
    accepting (swap/drain in progress; fail over and maybe come back)"
    apart from a programming error. Subclasses ``RuntimeError`` so the
    pre-router contract (``submit`` raises ``RuntimeError`` after
    ``drain``/``shutdown``) is unchanged."""


class ShutdownError(RuntimeError):
    """The batcher shut down (or a timed drain gave up) before this
    request could be served. Raised from the request's future — never
    left forever-pending."""


class _Request:
    __slots__ = ("x", "n", "single", "future", "t_submit", "span")

    def __init__(self, x, n, single, future, t_submit, span=None):
        self.x, self.n, self.single = x, n, single
        self.future, self.t_submit = future, t_submit
        # cross-thread obs span: begun on the submitter thread, ended on
        # whichever thread dispatches (its length = queue+window residency)
        self.span = span


class DynamicBatcher:
    """Thread-safe request queue + batching dispatcher over an
    :class:`~dcnn_tpu.serve.engine.InferenceEngine`.

    ``max_wait_ms`` trades tail latency for occupancy: 0 dispatches
    whatever is queued the moment the dispatcher is free (lowest latency,
    small batches at low load); a few ms lets concurrent arrivals coalesce
    into fuller buckets. ``queue_capacity`` is in samples.
    """

    def __init__(self, engine: InferenceEngine, *,
                 max_batch: Optional[int] = None, max_wait_ms: float = 2.0,
                 queue_capacity: int = 128,
                 metrics: Optional[ServeMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if queue_capacity < 1:
            raise ValueError(f"queue_capacity must be >= 1, "
                             f"got {queue_capacity}")
        self.engine = engine
        self.max_batch = min(max_batch or engine.max_batch, engine.max_batch)
        self.max_wait_s = max_wait_ms / 1e3
        self.queue_capacity = queue_capacity
        self.metrics = metrics if metrics is not None else ServeMetrics(
            clock=clock)
        # open the slot-goodput clock: the dispatch slot exists (and is
        # idle) from construction, so occupied/idle/draining seconds sum
        # to the replica's lifetime (serve/metrics.py record_slot_state)
        self.metrics.record_slot_state("idle")
        self._clock = clock
        self._q: deque = deque()  # dcnn: guarded_by=_cond
        self._rows = 0  # dcnn: guarded_by=_cond
        # every accepted, not-yet-resolved future: the no-orphan guarantee's
        # ledger
        self._accepted: set = set()  # dcnn: guarded_by=_cond
        self._cond = threading.Condition()
        self._closing = False
        self._thread: Optional[threading.Thread] = None
        self._telemetry = None  # TelemetryServer from start_telemetry()
        self._tsdb = None  # TsdbSampler riding the telemetry lifecycle
        self._compile_mirrored = False  # engine compile counters copied
        # onto the scrape registry at most once
        if start:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"dcnn-serve-batcher-{engine.name}")
            self._thread.start()

    # -- intake --
    def submit(self, x) -> Future:
        """Enqueue one request — a single sample ``input_shape`` (future
        resolves to ``(classes,)`` logits) or a small batch
        ``(n, *input_shape)``, ``n <= max_batch`` (future resolves to
        ``(n, classes)``). Raises :class:`QueueFullError` when the queue is
        at capacity and ``RuntimeError`` after :meth:`drain`/
        :meth:`shutdown`."""
        x = np.asarray(x)
        shp = self.engine.input_shape
        single = x.shape == shp
        if single:
            x = x[None]
        if x.ndim != len(shp) + 1 or x.shape[1:] != shp:
            raise ValueError(f"expected {shp} or (n, *{shp}), "
                             f"got shape {x.shape}")
        n = x.shape[0]
        if not 1 <= n <= self.max_batch:
            raise ValueError(f"request batch {n} outside [1, "
                             f"{self.max_batch}]; chunk it or use "
                             f"engine.infer")
        fut: Future = Future()
        tracer = get_tracer()
        with self._cond:
            if self._closing:
                raise DrainingError("batcher is draining or shut down")
            if self._rows + n > self.queue_capacity:
                self.metrics.record_shed(n)
                tracer.instant("serve.shed", track="serve.queue", n=n)
                raise QueueFullError(
                    f"queue at capacity ({self._rows}/{self.queue_capacity}"
                    f" samples); request of {n} shed")
            self._q.append(_Request(
                x, n, single, fut, self._clock(),
                span=tracer.begin("serve.queue", track="serve.queue", n=n)))
            self._accepted.add(fut)
            self._rows += n
            self.metrics.record_submit(n)
            self.metrics.record_queue_depth(self._rows)
            self._cond.notify_all()
        return fut

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return self._rows

    # -- telemetry (the per-replica scrape surface a router reads) ---------
    def health_reason(self) -> Optional[str]:
        """``None`` while this batcher can accept traffic; otherwise the
        machine-readable reason it can't. This is the ``/healthz``
        contract for the planned replica router (ROADMAP item 2): a
        draining or dead replica must fail health BEFORE requests error,
        so the router stops routing to it."""
        if self._closing:
            return "draining or shut down: not accepting requests"
        if self._thread is not None and not self._thread.is_alive():
            return "dispatcher thread dead"
        return None

    def start_telemetry(self, port: int = 0, host: str = "127.0.0.1"):
        """Expose THIS batcher over HTTP
        (:class:`~dcnn_tpu.obs.server.TelemetryServer`): ``/metrics`` is
        ``ServeMetrics.prometheus()`` (registry instruments + exact
        windowed percentile gauges), ``/healthz`` follows
        :meth:`health_reason`, ``/snapshot`` adds the live serve snapshot
        and engine compile/cost stats. ``port=0`` binds an ephemeral port
        (read ``.port`` back). The server survives :meth:`drain` — final
        stats stay scrapeable, with ``/healthz`` already 503 — and stops
        at :meth:`shutdown`. Calling it again replaces the previous
        server (stopped first — never a leaked bound port). Returns the
        started server."""
        from ..obs.server import TelemetryServer
        from ..obs.tsdb import TimeSeriesStore, TsdbSampler

        self._stop_telemetry()
        srv = TelemetryServer(registry=self.metrics.registry,
                              metrics_text=self.metrics.prometheus,
                              host=host, port=port)
        # merged-trace attribution + flight bundles on this replica's
        # own scrape surface (the recorder is a no-op until enabled)
        from ..obs.flight import get_flight_recorder
        srv.set_identity(component="replica", name=self.engine.name)
        srv.attach_flight(get_flight_recorder())
        # mirror the engine's per-sample cost gauges, HBM watermark, and
        # per-bucket compile accounting onto THIS scrape registry:
        # ServeMetrics' default registry is private, and the startup
        # allocation spike / roofline / compile-wall numbers must appear
        # on the surface the router actually reads. Counters are bumped
        # once (flag-guarded — a second start_telemetry must not
        # double-count).
        reg = self.metrics.registry
        # attribute-guarded: the batcher contract is duck-typed and a
        # custom engine (e.g. the soak's SyntheticEngine) has no cost
        # gauges or compile accounting to mirror — the scrape surface
        # must still come up
        if hasattr(self.engine, "_export_cost_gauges"):
            self.engine._export_cost_gauges(reg)
        sample_hbm(reg)
        compile_stats = getattr(self.engine, "compile_stats", None)
        if compile_stats and reg is not getattr(
                self.engine, "registry", None) \
                and not self._compile_mirrored:
            self._compile_mirrored = True
            secs = sum(st.get("compile_s", 0.0)
                       for st in compile_stats.values())
            reg.counter("compile_total",
                        "XLA executables compiled").inc(len(compile_stats))
            reg.counter("compile_seconds_total",
                        "wall seconds spent compiling").inc(secs)
            reg.counter("compile_serve_seconds_total",
                        "wall seconds compiling serve executables").inc(
                secs)
        srv.add_check("batcher", self.health_reason)
        srv.add_snapshot("serve", self.metrics.snapshot)
        srv.add_snapshot("engine", lambda: {
            "name": self.engine.name,
            "version": getattr(self.engine, "version", None),
            "buckets": self.engine.bucket_sizes,
            "batch_invariant": self.engine.batch_invariant,
            "compile_stats": getattr(self.engine, "compile_stats", {}),
        })
        # per-replica monitoring-plane history (obs/tsdb.py): THIS
        # surface's own /metrics text sampled at a cadence for as long
        # as it is up, so flight bundles carry the time-resolved serve
        # series — text (not registry) sampling, because the windowed
        # p99/shed-fraction gauges a postmortem wants exist only in
        # ServeMetrics' rendered exposition
        store = TimeSeriesStore()
        self._tsdb = TsdbSampler(
            store, registry=self.metrics.registry,
            text_fn=self.metrics.prometheus,
            interval_s=float(os.environ.get(
                "DCNN_TSDB_INTERVAL", "1.0"))).start()
        srv.add_snapshot("tsdb", store.summary)
        # flight bundles from this process now carry the pre-trigger
        # window (newest surface wins when several replicas share the
        # process-global recorder; detach below is identity-guarded)
        get_flight_recorder().attach_tsdb(store)
        self._telemetry = srv.start()
        return srv

    def _stop_telemetry(self) -> None:
        """Stop the scrape server AND its history sampler (idempotent —
        called from every shutdown path and on re-start)."""
        if self._tsdb is not None:
            from ..obs.flight import get_flight_recorder
            rec = get_flight_recorder()
            # detach only OUR store: another replica's attachment (it
            # started later, it wins) must survive this shutdown
            if getattr(rec, "_tsdb", None) is self._tsdb.store:
                rec.attach_tsdb(None)
            self._tsdb.stop()
            self._tsdb = None
        if self._telemetry is not None:
            self._telemetry.stop()
            self._telemetry = None

    # -- dispatch core (shared by the thread and the synchronous step) --
    def _pop_due(self, force: bool) -> List[_Request]:
        """Pop up to ``max_batch`` samples' worth of whole requests, but
        only if a dispatch is due — queue full enough, oldest request past
        its deadline, draining, or ``force``. Never splits a request."""
        with self._cond:
            if not self._q:
                return []
            due = (force or self._closing
                   or self._rows >= self.max_batch
                   or self._clock() >= self._q[0].t_submit + self.max_wait_s)
            if not due:
                return []
            tracer = get_tracer()
            batch, rows = [], 0
            while self._q and rows + self._q[0].n <= self.max_batch:
                req = self._q.popleft()
                self._rows -= req.n
                # canonical Future handoff: claims the request for this
                # batch, and drops one the caller cancelled while queued
                # (set_result on it would otherwise poison the scatter)
                if not req.future.set_running_or_notify_cancel():
                    tracer.end(req.span, cancelled=True)
                    self._accepted.discard(req.future)
                    continue
                tracer.end(req.span)  # queue residency: enqueue -> dispatch
                rows += req.n
                batch.append(req)
            self.metrics.record_queue_depth(self._rows)
            return batch

    def _run(self, batch: List[_Request]) -> None:
        tracer = get_tracer()
        self.metrics.record_slot_state("occupied")
        try:
            x = (batch[0].x if len(batch) == 1
                 else np.concatenate([r.x for r in batch]))
            rows = x.shape[0]
            # distributed-trace parentage: the dispatch covers every
            # request in the batch, and a batch may mix traces. A
            # single-trace batch parents the dispatch/infer spans under
            # that trace (the cross-process correlation the router soak
            # asserts); a mixed batch records the trace-id list instead
            # — one span cannot honestly claim several parents. Guarded
            # on `enabled` so the disabled dispatch path does zero
            # context work (the null spans carry no contexts anyway).
            parent, extra = None, {}
            if tracer.enabled:
                ctxs = [c for c in (r.span.context() if r.span is not None
                                    else None for r in batch) if c]
                tids = {c["trace_id"] for c in ctxs}
                parent = ctxs[0] if len(tids) == 1 else None
                if len(tids) > 1:
                    extra = {"trace_ids": sorted(tids)[:8]}
            with tracer.span("serve.dispatch", track="serve", parent=parent,
                             requests=len(batch), rows=rows,
                             **extra) as dspan:
                padded, _ = self.engine.pad_to_bucket(x)
                dspan.set(bucket=int(padded.shape[0]))
                # np.asarray materializes on host — a hard fence, so
                # recorded latency covers the full compute, and scatter is
                # cheap views; the infer span is therefore device-true
                with tracer.span("serve.infer", track="serve",
                                 bucket=int(padded.shape[0]), rows=rows):
                    y = np.asarray(self.engine.run_padded(padded))
            t_done = self._clock()
            off = 0
            for r in batch:
                try:
                    r.future.set_result(y[off] if r.single
                                        else y[off:off + r.n])
                    self.metrics.record_done(t_done - r.t_submit, r.n)
                except InvalidStateError:
                    pass  # failed by a timed-out drain racing this dispatch
                off += r.n
            self.metrics.record_batch(rows, padded.shape[0])
            # dispatch-boundary HBM watermark (obs/xla): latched no-op on
            # backends without memory stats, so the hot path stays clean
            sample_hbm(self.metrics.registry)
        except Exception as e:  # scatter the failure, don't kill the thread
            for r in batch:
                if not r.future.done():
                    try:
                        r.future.set_exception(e)
                    except InvalidStateError:
                        pass
        finally:
            with self._cond:
                for r in batch:
                    self._accepted.discard(r.future)
                closing = self._closing
            self.metrics.record_slot_state(
                "draining" if closing else "idle")

    def step(self, force: bool = True) -> int:
        """Synchronously dispatch one batch (``start=False`` mode and
        :meth:`drain`). ``force=False`` dispatches only if due — the hook
        the fake-clock deadline tests drive. Returns requests served."""
        batch = self._pop_due(force)
        if batch:
            self._run(batch)
        return len(batch)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._closing:
                    self._cond.wait()
                if not self._q:  # closing and fully drained
                    return
                # hold for the batching window: until full, the oldest
                # request's deadline, or drain (re-check the queue each
                # wakeup — a concurrent step() call may have emptied it)
                while (self._q and self._rows < self.max_batch
                       and not self._closing):
                    remaining = (self._q[0].t_submit + self.max_wait_s
                                 - self._clock())
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            batch = self._pop_due(force=True)
            if batch:
                self._run(batch)

    # -- teardown --
    def _fail_pending(self, exc: Exception) -> int:
        """Resolve every still-pending accepted future with ``exc`` —
        the no-orphan guarantee's last resort. Safe against races with a
        dispatcher concurrently resolving the same futures (whoever sets
        first wins; the loser's ``InvalidStateError`` is absorbed).
        Returns how many futures this call actually failed."""
        with self._cond:
            queued = list(self._q)
            self._q.clear()
            self._rows = 0
            pending = set(self._accepted)
            self._accepted.clear()
            self.metrics.record_queue_depth(0)
        tracer = get_tracer()
        failed = 0
        for r in queued:
            tracer.end(r.span, failed=type(exc).__name__)
        for fut in pending:
            try:
                fut.set_exception(exc)
                failed += 1
            except InvalidStateError:
                pass  # resolved (or cancelled) while we swept
        return failed

    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop accepting new requests; complete everything accepted.
        Threaded mode joins the dispatcher (it exits once empty);
        ``start=False`` mode dispatches the backlog inline. If ``timeout``
        trips, every still-pending future is failed with
        :class:`ShutdownError` (never orphaned) and ``TimeoutError``
        raises."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        self.metrics.record_slot_state("draining")
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                n = self._fail_pending(ShutdownError(
                    f"drain timed out after {timeout}s with requests "
                    f"pending; the batcher is shutting down"))
                raise TimeoutError(
                    f"drain did not finish in {timeout}s "
                    f"({n} pending request(s) failed with ShutdownError)")
            self._thread = None
        else:
            while self.step(force=True):
                pass

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """``drain=True``: :meth:`drain`. ``drain=False``: reject further
        intake and fail queued requests — their futures raise
        :class:`ShutdownError` (a request someone is blocked on must
        resolve, not vanish with the batcher)."""
        if drain:
            try:
                self.drain(timeout)
            finally:
                # even an expired drain (TimeoutError) must release the
                # scrape port — a leaked server blocks the replica restart
                self._stop_telemetry()
            return
        exc = ShutdownError("batcher shut down without drain")
        with self._cond:
            self._closing = True
            # pop the backlog under the lock so the dispatcher can't drain
            # it; in-flight work (already popped) completes during join
            queued = list(self._q)
            self._q.clear()
            self._rows = 0
            for r in queued:
                self._accepted.discard(r.future)
            self.metrics.record_queue_depth(0)
            self._cond.notify_all()
        self.metrics.record_slot_state("draining")
        tracer = get_tracer()
        for r in queued:
            try:
                r.future.set_exception(exc)
            except InvalidStateError:
                pass  # caller cancelled it while queued
            tracer.end(r.span, failed="ShutdownError")
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self._fail_pending(exc)  # sweep any remainder: no future orphaned
        self._stop_telemetry()

    def __enter__(self) -> "DynamicBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def __repr__(self) -> str:
        return (f"DynamicBatcher(engine={self.engine.name!r}, "
                f"max_batch={self.max_batch}, "
                f"max_wait_ms={self.max_wait_s * 1e3:g}, "
                f"capacity={self.queue_capacity})")
