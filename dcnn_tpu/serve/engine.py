"""Bucketed inference engine: the compiled half of the serving stack.

The reference's deployment story ends at binary weight files readable only
by its own C++ runtime (``sequential.hpp:832-915``); our export chain
(fold → int8 → StableHLO) already ships a portable *program*. This module
turns either source — a checkpoint dir or an exported artifact — into an
**online-servable** unit: one ahead-of-time compiled session per batch
bucket (powers of two up to ``max_batch``), pre-warmed so the first real
request never pays a compile, with zero-pad-to-bucket dispatch.

Why buckets instead of one batch-polymorphic callable: XLA compiles per
concrete shape anyway, so an unconstrained batcher would accumulate one
executable per distinct arrival count (and pay a fresh compile — seconds —
mid-traffic for each new one). Power-of-two buckets cap the executable
count at ``log2(max_batch)+1`` and bound padding waste at <2x, the same
trade TensorFlow-Serving's batching scheduler makes with
``allowed_batch_sizes``.

Numerics contract (asserted in ``tests/test_serve.py``):

- padding is row-exact *within* a session — zero rows ride along and are
  sliced off; the real rows' logits are bit-identical to the same batch
  unpadded at the same bucket;
- **int8 engines are bit-identical across buckets too**
  (``batch_invariant=True``): every cross-row-shape reduction in the
  quantized graph is an exact int8×int8→int32 integer accumulation, which
  is reduction-order-free, so a request's logits don't depend on which
  bucket served it. Float graphs are only allclose across buckets — XLA
  retiles fp32 conv/GEMM reductions per shape — which is exactly why the
  int8 graph is the serving graph of record.

Sessions are compiled with buffer donation on accelerator backends: the
padded input batch is a fresh per-dispatch buffer the caller never reuses,
so donating it lets XLA overwrite it in place instead of allocating output
alongside input (CPU ignores donation, so it is skipped there to keep logs
clean).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import get_registry, get_tracer
from ..obs.xla import executable_cost, record_compile, sample_hbm


def serve_buckets(max_batch: int) -> List[int]:
    """Batch buckets: powers of two up to ``max_batch``, with ``max_batch``
    itself always the last bucket (so a non-power-of-two cap costs one
    extra session instead of silently over-padding): 32 → [1,2,4,8,16,32],
    6 → [1,2,4,6]."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


class InferenceEngine:
    """Pre-compiled, bucketed, warm inference sessions over one model.

    ``apply_fn(x) -> logits`` is the already-transformed eval-mode forward
    (weights closed over); use the classmethods to build one from a
    checkpoint dir, a live model, or a StableHLO artifact — they apply the
    deployment transforms (fold / int8) and set ``batch_invariant``
    accordingly.
    """

    def __init__(self, apply_fn: Callable, input_shape: Sequence[int], *,
                 max_batch: int = 32, input_dtype: Any = jnp.float32,
                 donate: Optional[bool] = None, warmup: bool = True,
                 batch_invariant: bool = False, name: str = "engine",
                 version: Optional[Any] = None, registry=None,
                 aot_cache: Any = None, aot_config: Optional[str] = None):
        self.name = name
        # model-version identity (the CheckpointManager step for engines
        # built by serve/swap.py's EngineFactory; None for ad-hoc engines).
        # The router tier and ModelVersionManager route/report on it.
        self.version = version
        # cost/HBM gauges land here (default: the process-global registry);
        # a batcher's start_telemetry additionally mirrors them onto its
        # own scrape registry so a private-registry replica still exposes
        # them on /metrics
        self.registry = registry if registry is not None else get_registry()
        self.input_shape = tuple(int(d) for d in input_shape)
        self.input_dtype = jnp.dtype(input_dtype)
        self.bucket_sizes = serve_buckets(max_batch)
        self.max_batch = self.bucket_sizes[-1]
        self.batch_invariant = bool(batch_invariant)
        if donate is None:
            # donation is a no-op (plus a warning per compile) on CPU
            donate = jax.default_backend() in ("tpu", "gpu")
        jitted = jax.jit(apply_fn, donate_argnums=(0,) if donate else ())
        # AOT executable cache (dcnn_tpu/aot): per-bucket sessions are
        # deserialized from a shared cache dir instead of recompiled, so
        # replica fleet spin-up and hot-swap drain→load→rejoin stop
        # paying one compile per bucket. The key MUST cover the weights
        # (jit bakes the closed-over params into the program), which is
        # why the constructors compute ``aot_config`` — an engine handed
        # a cache without that digest refuses to cache rather than risk
        # serving another checkpoint's executable.
        aot = self._resolve_aot(aot_cache, aot_config)
        self._sessions: Dict[int, Any] = {}
        self.compile_stats: Dict[int, Dict[str, float]] = {}
        tracer = get_tracer()
        for b in self.bucket_sizes:
            spec = jax.ShapeDtypeStruct((b, *self.input_shape),
                                        self.input_dtype)
            aot_info = None
            t0 = time.perf_counter()
            with tracer.span("serve.compile", track="serve",
                             engine=name, bucket=b):
                if aot is not None:
                    from ..aot import warm_or_compile
                    session, aot_info = warm_or_compile(
                        jitted, spec, cache=aot, what="serve",
                        config=aot_config,
                        donate=(0,) if donate else (),
                        registry=self.registry)
                else:
                    session = jitted.lower(spec).compile()
            compile_s = time.perf_counter() - t0
            if aot_info is None:
                record_compile(compile_s, what="serve",
                               registry=self.registry)
            t0 = time.perf_counter()
            if warmup:
                with tracer.span("serve.warmup", track="serve",
                                 engine=name, bucket=b):
                    jax.block_until_ready(session(jnp.zeros(
                        (b, *self.input_shape), self.input_dtype)))
            self._sessions[b] = session
            self.compile_stats[b] = {
                "compile_s": round(compile_s, 4),
                "warmup_s": round(time.perf_counter() - t0, 4)}
            if aot_info is not None:
                self.compile_stats[b]["aot_hit"] = aot_info["hit"]
                if aot_info.get("deserialize_s") is not None:
                    self.compile_stats[b]["deserialize_s"] = \
                        aot_info["deserialize_s"]
            # XLA's own accounting for this bucket's executable (obs/xla):
            # FLOPs + bytes-accessed feed the serve roofline and the
            # analytic per-sample cost the bench/router read
            cost = executable_cost(session)
            if cost is not None:
                self.compile_stats[b].update(
                    {k: cost[k] for k in ("flops", "bytes_accessed",
                                          "bytes_per_flop") if k in cost})
        self._export_cost_gauges(self.registry)
        # post-compile HBM watermark: engine startup is the serve-side
        # allocation spike (every bucket's weights + workspace); no-op on
        # backends without memory stats
        sample_hbm(self.registry)

    @staticmethod
    def _resolve_aot(aot_cache: Any, aot_config: Optional[str]):
        """``aot_cache``: ``None`` = follow the ``AOT_CACHE`` env,
        ``False`` = force off, a dir string or ``ExecutableCache`` =
        explicit. Returns the cache instance or ``None``; a cache
        without a weights digest is refused (see ``__init__``)."""
        if aot_cache is False:
            return None
        try:
            from ..aot import ExecutableCache, get_cache
            if isinstance(aot_cache, ExecutableCache):
                aot = aot_cache
            else:
                aot = get_cache(aot_cache if isinstance(aot_cache, str)
                                else None)
        except Exception:
            return None
        if aot is not None and not aot_config:
            import warnings
            warnings.warn(
                "InferenceEngine: aot_cache set but no aot_config digest "
                "— executable caching disabled for this engine (a key "
                "that does not cover the closed-over weights could serve "
                "another checkpoint's executable). Build engines through "
                "from_model/from_checkpoint/from_artifact to get the "
                "digest computed automatically.", stacklevel=3)
            return None
        return aot

    def _export_cost_gauges(self, registry) -> None:
        """Set the per-sample XLA cost gauges on ``registry`` (engine
        startup does it for :attr:`registry`; ``start_telemetry`` repeats
        it for the batcher's scrape registry)."""
        top = self.compile_stats.get(self.max_batch, {})
        if top.get("flops"):
            registry.gauge(
                "serve_flops_per_sample",
                "XLA cost-analysis FLOPs per sample at the largest "
                "serve bucket").set(top["flops"] / self.max_batch)
            if top.get("bytes_per_flop") is not None:
                registry.gauge(
                    "serve_bytes_per_flop",
                    "roofline byte/FLOP ratio of the largest serve "
                    "bucket executable").set(top["bytes_per_flop"])

    # -- constructors --
    @classmethod
    def from_model(cls, model, params, state, *, fold: bool = True,
                   int8_calib: Optional[Any] = None,
                   act_quantile: Optional[float] = None, **kw
                   ) -> "InferenceEngine":
        """Engine over a live :class:`~dcnn_tpu.nn.Sequential`.

        ``fold=True`` runs :func:`~dcnn_tpu.nn.fold.fold_batchnorm`;
        passing a calibration batch as ``int8_calib`` additionally runs
        :func:`~dcnn_tpu.nn.quantize.quantize_model` (which folds first) —
        the int8 engine gets the cross-bucket ``batch_invariant``
        guarantee (module docstring)."""
        from ..nn import fold_batchnorm, quantize_model

        if model.input_shape is None:
            raise ValueError("model has no input_shape; build it through "
                             "SequentialBuilder.input or set input_shape")
        invariant = False
        if int8_calib is not None:
            model, params, state = quantize_model(
                model, params, state, int8_calib, fold_bn=fold,
                act_quantile=act_quantile)
            invariant = True
        elif fold:
            model, params, state = fold_batchnorm(model, params, state)

        def apply_fn(x):
            return model.apply(params, state, x, training=False)[0]

        kw.setdefault("name", model.name)
        if kw.get("aot_cache") is not False and "aot_config" not in kw:
            # post-transform digest: the folded/quantized model + ITS
            # weights are what the jitted graph closes over. Computed
            # only when the AOT cache is actually on (hashing ~50 MB of
            # weights is cheap next to a compile, pointless next to
            # nothing).
            try:
                from ..aot import digest, digest_arrays, enabled_root
                ac = kw.get("aot_cache")
                if (enabled_root(ac if isinstance(ac, str) else None)
                        is not None or (ac is not None
                                        and not isinstance(ac, str))):
                    kw["aot_config"] = digest({
                        "model": model.get_config(),
                        "weights": digest_arrays({"p": params, "s": state}),
                    })
            except Exception:
                pass
        return cls(apply_fn, model.input_shape,
                   batch_invariant=invariant, **kw)

    @classmethod
    def from_checkpoint(cls, path: str, *, seed: int = 0, **kw
                        ) -> "InferenceEngine":
        """Engine from a ``save_checkpoint`` dir (the committed
        ``model_snapshots/mnist_cnn_model`` layout). Transform kwargs as in
        :meth:`from_model`."""
        from ..train.checkpoint import load_checkpoint

        model, params, state, _, _, _ = load_checkpoint(path, seed=seed)
        return cls.from_model(model, params, state, **kw)

    @classmethod
    def from_artifact(cls, blob_or_path, **kw) -> "InferenceEngine":
        """Engine from a serialized StableHLO artifact
        (:func:`~dcnn_tpu.nn.export.export_inference` bytes or a file
        path). Needs a batch-polymorphic artifact — a pinned-batch export
        can only ever run its one shape, which defeats bucketing."""
        from jax import export as jax_export

        if isinstance(blob_or_path, (str, os.PathLike)):
            with open(blob_or_path, "rb") as f:
                blob = f.read()
        else:
            blob = bytes(blob_or_path)
        exported = jax_export.deserialize(blob)
        aval = exported.in_avals[0]
        lead = aval.shape[0]
        if isinstance(lead, int):
            raise ValueError(
                f"artifact has a pinned batch dimension ({lead}); serve "
                "needs a batch-polymorphic export (export_inference with "
                "batch_size=None, the default)")
        kw.setdefault("name", "artifact")
        if "aot_config" not in kw:
            # the serialized artifact IS the complete program (weights
            # included as StableHLO constants): its hash is the digest
            import hashlib
            kw["aot_config"] = "artifact-" + hashlib.sha256(
                blob).hexdigest()
        return cls(exported.call, tuple(int(d) for d in aval.shape[1:]),
                   input_dtype=aval.dtype, **kw)

    # -- bucket math --
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n."""
        if not 1 <= n <= self.max_batch:
            raise ValueError(f"batch of {n} outside [1, {self.max_batch}]")
        for b in self.bucket_sizes:
            if b >= n:
                return b
        raise AssertionError("unreachable: last bucket is max_batch")

    def pad_to_bucket(self, x: np.ndarray) -> Tuple[jnp.ndarray, int]:
        """Zero-pad ``(n, *input_shape)`` rows up to the nearest bucket.
        Returns ``(padded, n)``. The result is always a FRESH device
        buffer (host round-trip if ``x`` was a device array), so handing
        it to :meth:`run_padded` can never donate a buffer the caller
        still holds."""
        x = np.asarray(x, dtype=self.input_dtype)
        n = x.shape[0]
        b = self.bucket_for(n)
        if b > n:
            pad = np.zeros((b - n, *self.input_shape),
                           dtype=self.input_dtype)
            x = np.concatenate([x, pad])
        return jnp.asarray(x), n

    def run_padded(self, x) -> jnp.ndarray:
        """Run one pre-compiled session; ``x.shape[0]`` must be a bucket.

        On accelerator backends the session donates its input: a device
        array passed here is CONSUMED (standard ``jax.jit`` donation
        semantics) — prepare per-dispatch buffers with
        :meth:`pad_to_bucket`, which never aliases caller memory."""
        b = x.shape[0]
        session = self._sessions.get(b)
        if session is None:
            raise ValueError(f"no session for batch {b}; buckets are "
                             f"{self.bucket_sizes}")
        return session(jnp.asarray(x, dtype=self.input_dtype))

    # -- synchronous convenience path (the batcher uses the pieces above) --
    def infer(self, x) -> jnp.ndarray:
        """Run ``x`` — one sample ``input_shape`` or a batch
        ``(n, *input_shape)`` of any size — through the bucketed sessions;
        batches beyond ``max_batch`` are chunked. Returns logits with the
        same leading-dim convention as the input."""
        x = np.asarray(x)
        single = x.shape == self.input_shape
        if single:
            x = x[None]
        if x.shape[1:] != self.input_shape:
            raise ValueError(f"expected trailing dims {self.input_shape}, "
                             f"got array of shape {x.shape}")
        outs = []
        for lo in range(0, x.shape[0], self.max_batch):
            chunk = x[lo:lo + self.max_batch]
            padded, n = self.pad_to_bucket(chunk)
            outs.append(self.run_padded(padded)[:n])
        y = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
        return y[0] if single else y

    def __repr__(self) -> str:
        ver = f", version={self.version!r}" if self.version is not None else ""
        return (f"InferenceEngine({self.name!r}, input={self.input_shape}, "
                f"buckets={self.bucket_sizes}, "
                f"batch_invariant={self.batch_invariant}{ver})")
