"""Serving replicas: the router-facing unit of the multi-replica tier.

A *replica* is one ``InferenceEngine`` + ``DynamicBatcher`` pair with a
version identity, a health verdict, and a hot-swap protocol. The router
(``serve/router.py``) speaks one small interface to every replica —
``submit`` / ``health`` / ``is_dead`` / ``swap`` / ``queue_capacity`` /
``version`` — so an in-process replica and one living behind a TCP host
are interchangeable:

- :class:`LocalReplica` — engine + batcher in this process. Hot-swap is
  **drain → load → rejoin**: intake is refused (typed
  :class:`~dcnn_tpu.serve.batcher.DrainingError`) while the old batcher
  completes everything it accepted, the new version's engine is built by
  the replica's ``factory(version)``, and a fresh batcher rejoins with
  continuous metrics. A failed load **rejoins on the old version**
  (never a dead replica because a canary checkpoint was bad).
- :class:`ReplicaServer` / :class:`TcpReplica` — the same unit behind
  ``parallel/comm.py`` framing: ``infer``/``result``/``error`` frames
  with per-request ids, ``ping``/``pong`` liveness carrying the remote
  health verdict + version, and a remote ``swap`` command. The client
  detects replica death **both** ways the elastic mesh does —
  immediately via connection close (reader thread ``on_close``) and via
  a last-heard timeout for the partitioned-but-open case — never by
  hanging on a recv; pending request futures are failed with
  :class:`ReplicaDeadError` so the router can re-admit them, and sends
  ride a kernel ``SO_SNDTIMEO`` deadline
  (:meth:`~dcnn_tpu.parallel.comm.Channel.set_send_timeout`).

Fault injection (``resilience/faults.py``): every dispatch passes the
``serve.replica_infer`` trip point — armed with ``InjectedFault`` it is a
per-request replica error (the canary-degradation fixture: the router
re-admits the request elsewhere and counts the failure against this
replica/version); armed with ``InjectedCrash`` it is the
kill-this-replica simulation (the replica marks itself dead, in-flight
requests fail, the router ejects it). ``serve.swap`` fires in the swap
load path. Replicas accept a per-instance
:class:`~dcnn_tpu.resilience.faults.FaultPlan` so multi-replica tests can
kill exactly one victim.
"""

from __future__ import annotations

import socket
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import get_tracer
from ..parallel.comm import Channel, ChannelClosed, connect, listen
from ..resilience import faults as _faults
from ..resilience.faults import InjectedCrash
from .batcher import (
    DrainingError, DynamicBatcher, QueueFullError, ShutdownError,
)
from .metrics import ServeMetrics


class ReplicaError(RuntimeError):
    """A request failed for a replica-attributable reason (remote engine
    error, protocol error). The router counts it against the replica and
    re-admits the request elsewhere."""


class ReplicaDeadError(ReplicaError):
    """The replica is gone — crashed, killed, or unreachable. Requests it
    had accepted but not answered surface this (or ``ShutdownError``) so
    the router can re-admit them to survivors."""


class SwapError(ReplicaError):
    """A version swap failed; the replica rejoined on its old version."""


#: Exception classes the router treats as "the replica died" (re-admit,
#: eject) rather than "this one request failed" (re-admit, count error).
DEATH_ERRORS = (ReplicaDeadError, ShutdownError, InjectedCrash,
                ConnectionError, BrokenPipeError, OSError)


class _TrippedEngine:
    """Engine proxy inserting the ``serve.replica_infer`` fault trip in
    front of every dispatch. An ``InjectedCrash`` marks the owning
    replica dead before surfacing (the batcher scatters it to the batch's
    futures — exactly what a process death does to in-flight requests);
    an ``InjectedFault`` surfaces as a plain per-request engine error."""

    def __init__(self, engine, replica: "LocalReplica"):
        self._engine = engine
        self._replica = replica

    def run_padded(self, x):
        try:
            self._replica._trip("serve.replica_infer")
        except InjectedCrash:
            self._replica._note_crash("injected crash mid-infer")
            raise
        t0 = self._replica._clock()
        out = self._engine.run_padded(x)
        # gray-failure injection (FaultPlan.slow): stretch this batch's
        # engine wall INSIDE the dispatch — the replica stays alive and
        # healthy-looking while every completion latency it reports grows
        self._replica._slowdown("serve.slow_replica",
                                self._replica._clock() - t0)
        return out

    def __getattr__(self, name):
        return getattr(self._engine, name)


class LocalReplica:
    """One in-process serving replica with versioned hot-swap.

    ``factory(version) -> engine`` builds an engine for a model version
    (see :class:`~dcnn_tpu.serve.swap.EngineFactory`); passing an engine
    *instance* instead pins the replica to it (``swap`` then raises
    :class:`SwapError` — there is nothing to load versions from).

    ``start=False`` propagates to every batcher this replica ever owns:
    no dispatcher thread runs and tests pump dispatch with :meth:`step`,
    so the whole death/swap/canary protocol is exercised sleep-free.
    """

    def __init__(self, factory: Any, version: Any = None, *,
                 name: str = "replica", max_batch: Optional[int] = None,
                 max_wait_ms: float = 2.0, queue_capacity: int = 128,
                 metrics: Optional[ServeMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 drain_timeout_s: Optional[float] = 60.0,
                 fault_plan=None, start: bool = True):
        self.name = name
        self._clock = clock
        self._plan = fault_plan
        self._start = start
        self._max_batch = max_batch
        self._max_wait_ms = max_wait_ms
        self._queue_capacity = queue_capacity
        self.drain_timeout_s = drain_timeout_s
        if callable(factory) and not hasattr(factory, "run_padded"):
            self._factory: Optional[Callable[[Any], Any]] = factory
            engine = factory(version)
        else:
            self._factory = None
            engine = factory
            if version is None:
                version = getattr(engine, "version", None)
        self.metrics = metrics if metrics is not None else ServeMetrics(
            clock=clock)
        self._lock = threading.Lock()
        self._state = "up"                 # dcnn: guarded_by=_lock
        self._dead_reason: Optional[str] = None  # dcnn: guarded_by=_lock
        self._version = version            # dcnn: guarded_by=_lock
        self._engine = engine              # dcnn: guarded_by=_lock
        self._batcher = self._make_batcher(engine)  # dcnn: guarded_by=_lock

    # -- internals ---------------------------------------------------------
    def _make_batcher(self, engine) -> DynamicBatcher:
        return DynamicBatcher(
            _TrippedEngine(engine, self), max_batch=self._max_batch,
            max_wait_ms=self._max_wait_ms,
            queue_capacity=self._queue_capacity, metrics=self.metrics,
            clock=self._clock, start=self._start)

    def _trip(self, point: str, **ctx) -> None:
        _faults.trip(point, replica=self.name, **ctx)
        if self._plan is not None:
            self._plan.trip(point, replica=self.name, **ctx)

    def _slowdown(self, point: str, base_s: float, **ctx) -> float:
        """Delay-injection twin of :meth:`_trip` (``FaultPlan.slow``):
        sleeps the armed extra inside the dispatch, so the latency the
        router observes — and judges probation/hedging on — actually
        grows."""
        extra = _faults.slowdown(point, base_s, replica=self.name, **ctx)
        if self._plan is not None:
            extra += self._plan.slowdown(point, base_s,
                                         replica=self.name, **ctx)
        if extra > 0.0:
            time.sleep(extra)
        return extra

    def _note_crash(self, reason: str) -> None:
        """Mark this replica dead without tearing anything down — called
        from the dispatcher thread mid-crash, where joining ourselves
        would deadlock. :meth:`kill` (the router's eject sweep, or the
        test's top-level crash handler) does the actual teardown."""
        with self._lock:
            if self._state != "dead":
                self._state = "dead"
                self._dead_reason = reason

    # -- the router-facing interface ---------------------------------------
    @property
    def version(self):
        with self._lock:
            return self._version

    @property
    def input_shape(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(self._engine.input_shape)

    @property
    def queue_capacity(self) -> int:
        return self._queue_capacity

    @property
    def outstanding_rows(self) -> int:
        with self._lock:
            batcher = self._batcher
        return batcher.queue_depth if batcher is not None else 0

    def submit(self, x) -> Future:
        """Enqueue one request (batcher conventions). Raises
        :class:`ReplicaDeadError` when dead, ``DrainingError`` mid-swap,
        ``QueueFullError`` on shed."""
        with self._lock:
            state, batcher = self._state, self._batcher
            reason = self._dead_reason
        if state == "dead":
            raise ReplicaDeadError(f"replica {self.name} is dead: {reason}")
        if state != "up":
            raise DrainingError(f"replica {self.name} is {state}")
        return batcher.submit(x)

    def health(self) -> Optional[str]:
        """``None`` while routable; otherwise the machine-readable reason
        (the same contract as ``DynamicBatcher.health_reason`` — a
        degraded replica must fail health BEFORE requests error)."""
        with self._lock:
            state, reason, batcher = (self._state, self._dead_reason,
                                      self._batcher)
        if state in ("dead", "closed"):
            return f"dead: {reason}"
        if state != "up":
            return f"{state}: version swap in progress"
        return batcher.health_reason()

    def is_dead(self) -> bool:
        with self._lock:
            return self._state in ("dead", "closed")

    def ping(self) -> None:
        """Liveness probe — a no-op in process (health() is authoritative
        and always fresh); the TCP twin sends a real PING frame."""

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            state, version = self._state, self._version
        return {"name": self.name, "state": state, "version": version,
                "queue_depth": self.outstanding_rows,
                "metrics": self.metrics.snapshot()}

    def step(self, force: bool = True) -> int:
        """Pump one synchronous dispatch (``start=False`` test mode)."""
        with self._lock:
            batcher = self._batcher
        return batcher.step(force) if batcher is not None else 0

    def start_telemetry(self, port: int = 0, host: str = "127.0.0.1"):
        """Per-replica HTTP scrape surface (see
        :meth:`DynamicBatcher.start_telemetry`)."""
        with self._lock:
            batcher = self._batcher
        srv = batcher.start_telemetry(port=port, host=host)
        srv.add_check("replica", self.health)
        return srv

    # -- hot-swap ----------------------------------------------------------
    def swap(self, version) -> None:
        """Drain → load ``version`` → rejoin.

        The old batcher completes everything it accepted (new intake gets
        ``DrainingError`` — the router fails over), the factory builds the
        new engine (``serve.swap`` fault point), and a fresh batcher
        rejoins. On a load failure the replica **rejoins on the old
        engine** and raises :class:`SwapError`; an ``InjectedCrash`` at
        the swap point kills the replica instead (crash-mid-swap
        simulation)."""
        with self._lock:
            if self._state == "dead":
                raise ReplicaDeadError(
                    f"replica {self.name} is dead: {self._dead_reason}")
            if self._factory is None:
                raise SwapError(
                    f"replica {self.name} wraps a fixed engine; construct "
                    f"it with a factory (serve/swap.py EngineFactory) to "
                    f"hot-swap versions")
            if self._state != "up":
                raise SwapError(f"replica {self.name} already swapping")
            self._state = "loading"
            old_batcher = self._batcher
            old_engine = self._engine
        try:
            old_batcher.drain(timeout=self.drain_timeout_s)
        except TimeoutError:
            pass  # pending futures were failed (ShutdownError) — the
            # router re-admits them; the swap itself proceeds
        try:
            self._trip("serve.swap", version=version)
            engine = self._factory(version)
        except InjectedCrash:
            self._note_crash("injected crash mid-swap")
            raise
        except Exception as e:
            with self._lock:
                self._batcher = self._make_batcher(old_engine)
                self._state = "up"
            raise SwapError(
                f"replica {self.name}: loading version {version!r} failed "
                f"({type(e).__name__}: {e}); rejoined on old version "
                f"{self.version!r}") from e
        with self._lock:
            self._engine = engine
            self._batcher = self._make_batcher(engine)
            self._version = version
            self._state = "up"

    # -- lifecycle ---------------------------------------------------------
    def kill(self) -> None:
        """Simulate (or finish, after :meth:`_note_crash`) replica death:
        refuse intake, fail everything queued with ``ShutdownError`` so
        the router's ledger re-admits it, stop the dispatcher. Idempotent."""
        with self._lock:
            if self._state == "dead" and self._batcher is None:
                return
            self._state = "dead"
            if self._dead_reason is None:
                self._dead_reason = "killed"
            batcher, self._batcher = self._batcher, None
        if batcher is not None:
            batcher.shutdown(drain=False)

    def restart(self) -> None:
        """Rejoin after :meth:`kill`: a fresh batcher over the current
        engine (the restarted process re-loads the same version)."""
        with self._lock:
            if self._state != "dead":
                raise RuntimeError(
                    f"replica {self.name} is {self._state}, not dead")
            self._batcher = self._make_batcher(self._engine)
            self._state = "up"
            self._dead_reason = None

    def close(self) -> None:
        """Graceful teardown: drain accepted work, then stop."""
        with self._lock:
            if self._state == "closed":
                return
            batcher, self._batcher = self._batcher, None
            self._state = "closed"
            self._dead_reason = "closed"
        if batcher is not None:
            batcher.shutdown(drain=True, timeout=self.drain_timeout_s)

    def __enter__(self) -> "LocalReplica":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        with self._lock:
            return (f"LocalReplica({self.name!r}, state={self._state!r}, "
                    f"version={self._version!r})")


# --------------------------------------------------------------- TCP tier

class ReplicaServer:
    """Serves one :class:`LocalReplica` over ``parallel/comm.py`` framing.

    Frames (client → server): ``infer {id} + payload``, ``ping``,
    ``swap {id, version}``, ``stats {id}``. Replies: ``result {id} +
    payload`` / ``error {id, etype, emsg, dead}`` / ``pong {health,
    version, queue_depth, queue_capacity, input_shape}`` / ``swapped
    {id, version}`` / ``stats {id, ...}``. Multiple router connections
    are accepted; each gets its own reader thread. ``close()`` joins
    every thread it spawned."""

    def __init__(self, replica: LocalReplica, *, port: int = 0,
                 host: str = "127.0.0.1", own_replica: bool = False):
        self.replica = replica
        self._own = own_replica
        self._listener = listen(port, host)
        self.host = host
        self.port = self._listener.getsockname()[1]
        self._lock = threading.Lock()
        self._closed = False                      # dcnn: guarded_by=_lock
        self._channels: List[Channel] = []        # dcnn: guarded_by=_lock
        self._threads: List[threading.Thread] = []  # dcnn: guarded_by=_lock
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"dcnn-replica-srv-{self.port}")
        with self._lock:
            self._threads.append(t)
        t.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            ch = Channel(sock)
            t = threading.Thread(target=self._serve, args=(ch,),
                                 daemon=True,
                                 name=f"dcnn-replica-conn-{self.port}")
            with self._lock:
                if self._closed:
                    ch.close()
                    return
                self._channels.append(ch)
                self._threads.append(t)
            t.start()

    def _serve(self, ch: Channel) -> None:
        try:
            while True:
                cmd, meta, payload = ch.recv()
                try:
                    self._handle(ch, cmd, meta, payload)
                except (ChannelClosed, ConnectionError, OSError):
                    raise
                except Exception as e:
                    # a handler exception is ONE request's failure, not
                    # the channel's: reply typed (the error frame exists
                    # for exactly this) instead of unwinding the reader
                    # and failing every in-flight request on this
                    # connection (found annotating the replica.c2s map —
                    # only the infer arm replied error before)
                    self._send(ch, "error",
                               self._err_meta(meta.get("id"), e))
        except (ChannelClosed, ConnectionError, OSError):
            pass  # router went away; its pending futures are its problem

    def _send(self, ch: Channel, cmd: str, meta: Dict[str, Any],
              array=None) -> None:
        try:
            ch.send(cmd, meta, array=array, attempts=1)
        except (ChannelClosed, ConnectionError, OSError):
            pass  # client gone mid-reply

    def _pong_meta(self) -> Dict[str, Any]:
        r = self.replica
        return {"health": r.health(), "version": r.version,
                "queue_depth": r.outstanding_rows,
                "queue_capacity": r.queue_capacity,
                "input_shape": list(r.input_shape)}

    # dcnn: protocol=replica.c2s role=handler
    def _handle(self, ch: Channel, cmd: str, meta: Dict[str, Any],
                payload) -> None:  # dcnn: protocol=replica.s2c role=sender
        if cmd == "infer":
            rid = meta["id"]
            try:
                # adopt the router's trace context for this hop: the
                # batcher's serve.queue span (begun inside submit) — and
                # through it the dispatch/infer spans — join the
                # router-side request trace across the process boundary
                with get_tracer().activate(meta.get("_trace")):
                    fut = self.replica.submit(payload)
            except Exception as e:
                self._send(ch, "error", self._err_meta(rid, e))
                return
            fut.add_done_callback(lambda f: self._reply(ch, rid, f))
        elif cmd == "ping":
            # echo the client's monotonic stamp + our own: the client
            # estimates the cross-process clock offset the trace-merge
            # CLI aligns shards with (NTP-style midpoint; exact on one
            # host where perf_counter is CLOCK_MONOTONIC system-wide)
            pong = self._pong_meta()
            if "t_mono" in meta:
                pong["t_echo"] = meta["t_mono"]
                pong["t_srv"] = time.perf_counter()
            self._send(ch, "pong", pong)
        elif cmd == "swap":
            # swap drains — seconds of wall — and must not block this
            # reader (pings keep flowing or the client calls us dead)
            t = threading.Thread(
                target=self._do_swap, args=(ch, meta["id"], meta["version"]),
                daemon=True, name=f"dcnn-replica-swap-{self.port}")
            with self._lock:
                self._threads.append(t)
            t.start()
        elif cmd == "stats":
            self._send(ch, "stats", {"id": meta["id"],
                                     **self.replica.stats()})
        else:
            self._send(ch, "error", {"id": meta.get("id"),
                                     "etype": "ValueError",
                                     "emsg": f"unknown cmd {cmd!r}",
                                     "dead": False})

    @staticmethod
    def _err_meta(rid, exc: BaseException) -> Dict[str, Any]:
        return {"id": rid, "etype": type(exc).__name__, "emsg": str(exc),
                "dead": isinstance(exc, DEATH_ERRORS)}

    def _reply(self, ch: Channel, rid,
               fut: Future) -> None:  # dcnn: protocol=replica.s2c role=sender
        if fut.cancelled():
            self._send(ch, "error", {"id": rid, "etype": "CancelledError",
                                     "emsg": "cancelled", "dead": False})
            return
        exc = fut.exception()
        if exc is None:
            self._send(ch, "result", {"id": rid},
                       array=np.asarray(fut.result()))
        else:
            self._send(ch, "error", self._err_meta(rid, exc))

    def _do_swap(self, ch: Channel, rid,
                 version) -> None:  # dcnn: protocol=replica.s2c role=sender
        try:
            self.replica.swap(version)
        except Exception as e:
            self._send(ch, "error", self._err_meta(rid, e))
            return
        self._send(ch, "swapped", {"id": rid, "version": version})

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            channels = list(self._channels)
            threads = list(self._threads)
        try:
            # a bare close() does not wake a thread blocked in accept();
            # shutdown() does, so the acceptor exits now, not at a join
            # timeout
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._listener.close()
        for ch in channels:
            ch.close()
        for t in threads:
            if t is not threading.current_thread():
                t.join(timeout=10.0)
        if self._own:
            self.replica.close()

    def __enter__(self) -> "ReplicaServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TcpReplica:
    """Router-side client for a :class:`ReplicaServer` — the same
    interface as :class:`LocalReplica`, over one framed channel.

    Death is detected like the elastic membership mesh: immediately when
    the connection closes (reader thread ``on_close`` path), and by a
    **last-heard timeout** (``timeout_s`` since the last frame of any
    kind) for the partitioned-but-open case — :meth:`health` never
    blocks, and once either fires every pending request future fails
    with :class:`ReplicaDeadError` so the router re-admits the work."""

    def __init__(self, host: str, port: int, *, name: Optional[str] = None,
                 timeout_s: float = 10.0, connect_timeout: float = 10.0,
                 queue_capacity_hint: int = 128,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name if name is not None else f"tcp-{host}:{port}"
        self.timeout_s = timeout_s
        self._clock = clock
        self._chan = connect(host, port, timeout=connect_timeout)
        self._chan.set_send_timeout(timeout_s)
        self._lock = threading.Lock()
        self._pending: Dict[int, Tuple[Future, int]] = {}  # dcnn: guarded_by=_lock
        self._swaps: Dict[int, Future] = {}       # dcnn: guarded_by=_lock
        self._stats: Dict[int, Future] = {}       # dcnn: guarded_by=_lock
        self._next_id = 0                         # dcnn: guarded_by=_lock
        self._last_heard = clock()                # dcnn: guarded_by=_lock
        self._last_ping = clock()                 # dcnn: guarded_by=_lock
        self._dead_reason: Optional[str] = None   # dcnn: guarded_by=_lock
        self._remote: Dict[str, Any] = {          # dcnn: guarded_by=_lock
            "health": None, "version": None, "queue_depth": 0,
            "queue_capacity": queue_capacity_hint, "input_shape": None}
        # perf_counter-domain offset to the server process, estimated
        # from the ping/pong handshake (NTP midpoint) — the per-shard
        # alignment hint for `python -m dcnn_tpu.obs.trace merge`
        self.clock_offset_s: Optional[float] = None  # dcnn: guarded_by=_lock
        self.rtt_s: Optional[float] = None        # dcnn: guarded_by=_lock
        self._pong = threading.Event()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"dcnn-replica-cli-{host}:{port}")
        self._reader.start()
        # handshake: the first pong carries the remote identity
        # (input_shape, version, queue_capacity) that the router's
        # admission/row accounting needs — wait for it here so a freshly
        # constructed replica never makes the router mis-count rows
        # (a single sample would otherwise be admitted as shape[0] rows).
        # A server too slow to pong within the budget degrades to the
        # hints; health() still works.
        self.ping()
        self._pong.wait(timeout=connect_timeout)

    # -- wire --------------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while True:
                cmd, meta, payload = self._chan.recv()
                self._on_frame(cmd, meta, payload)
        except (ChannelClosed, ConnectionError, OSError) as e:
            self._mark_dead(f"connection closed: {e}")

    # dcnn: protocol=replica.s2c role=handler
    def _on_frame(self, cmd: str, meta: Dict[str, Any], payload) -> None:
        with self._lock:
            self._last_heard = self._clock()
        if cmd == "result":
            with self._lock:
                fut, _ = self._pending.pop(meta["id"], (None, 0))
            if fut is not None:
                try:
                    fut.set_result(payload)
                except InvalidStateError:
                    pass
        elif cmd == "error":
            self._on_error(meta)
        elif cmd == "pong":
            te, ts_srv = meta.get("t_echo"), meta.get("t_srv")
            with self._lock:
                self._remote.update(
                    {k: meta.get(k, self._remote.get(k))
                     for k in ("health", "version", "queue_depth",
                               "queue_capacity", "input_shape")})
                if te is not None and ts_srv is not None:
                    # handshake clock alignment: offset such that
                    # server_perf_counter ≈ client_perf_counter + offset
                    now = time.perf_counter()
                    rtt = max(now - float(te), 0.0)
                    self.rtt_s = rtt
                    self.clock_offset_s = float(ts_srv) - (float(te)
                                                           + rtt / 2.0)
            self._pong.set()
        elif cmd == "swapped":
            with self._lock:
                fut = self._swaps.pop(meta["id"], None)
            if fut is not None:
                try:
                    fut.set_result(meta["version"])
                except InvalidStateError:
                    pass
        elif cmd == "stats":
            with self._lock:
                fut = self._stats.pop(meta.pop("id"), None)
            if fut is not None:
                try:
                    fut.set_result(meta)
                except InvalidStateError:
                    pass

    def _on_error(self, meta: Dict[str, Any]) -> None:
        rid = meta.get("id")
        etype = meta.get("etype", "ReplicaError")
        emsg = meta.get("emsg", "")
        # re-typed so the router's shed/failover/death classification
        # works identically for local and remote replicas
        if meta.get("dead"):
            exc: BaseException = ReplicaDeadError(f"{etype}: {emsg}")
        elif etype == "QueueFullError":
            exc = QueueFullError(emsg)
        elif etype == "DrainingError":
            exc = DrainingError(emsg)
        else:
            exc = ReplicaError(f"{etype}: {emsg}")
        with self._lock:
            fut, _ = self._pending.pop(rid, (None, 0))
            sfut = self._swaps.pop(rid, None)
            # stats futures too: an error reply carrying a stats id
            # otherwise strands stats() for its full timeout
            tfut = self._stats.pop(rid, None)
        for f in (fut, sfut, tfut):
            if f is not None:
                try:
                    f.set_exception(exc)
                except InvalidStateError:
                    pass

    def _mark_dead(self, reason: str) -> None:
        with self._lock:
            if self._dead_reason is not None:
                return
            self._dead_reason = reason
            pending = list(self._pending.values())
            swaps = list(self._swaps.values())
            stats = list(self._stats.values())
            self._pending.clear()
            self._swaps.clear()
            self._stats.clear()
        exc = ReplicaDeadError(f"replica {self.name}: {reason}")
        for fut, _ in pending:
            try:
                fut.set_exception(exc)
            except InvalidStateError:
                pass
        for fut in swaps + stats:
            try:
                fut.set_exception(exc)
            except InvalidStateError:
                pass

    def _send(self, cmd: str, meta: Dict[str, Any], array=None) -> None:
        try:
            self._chan.send(cmd, meta, array=array, attempts=1)
        except (ChannelClosed, ConnectionError, OSError) as e:
            self._mark_dead(f"send failed: {e}")
            raise ReplicaDeadError(
                f"replica {self.name}: send failed: {e}") from e

    # -- the router-facing interface ---------------------------------------
    @property
    def version(self):
        with self._lock:
            return self._remote["version"]

    @property
    def input_shape(self):
        with self._lock:
            shp = self._remote["input_shape"]
        return tuple(shp) if shp is not None else None

    @property
    def queue_capacity(self) -> int:
        with self._lock:
            return int(self._remote["queue_capacity"])

    @property
    def outstanding_rows(self) -> int:
        with self._lock:
            return sum(n for _, n in self._pending.values())

    def submit(self, x) -> Future:  # dcnn: protocol=replica.c2s role=sender
        x = np.asarray(x, dtype=np.float32)
        with self._lock:
            if self._dead_reason is not None:
                raise ReplicaDeadError(
                    f"replica {self.name} is dead: {self._dead_reason}")
            rid = self._next_id
            self._next_id += 1
            fut: Future = Future()
            shp = self._remote["input_shape"]
            single = shp is not None and tuple(x.shape) == tuple(shp)
            n = 1 if single or x.ndim == 0 else int(x.shape[0])
            self._pending[rid] = (fut, n)
        try:
            self._send("infer", {"id": rid}, array=x)
        except ReplicaDeadError:
            with self._lock:
                self._pending.pop(rid, None)
            raise
        return fut

    def ping(self) -> None:  # dcnn: protocol=replica.c2s role=sender
        """Fire-and-forget liveness probe; the pong refreshes
        ``last_heard`` + the cached remote health/version. Send failures
        mark the replica dead (that IS the probe result).

        ``_last_ping`` records the FIRST probe since the last frame
        heard and is not reset while that probe is outstanding —
        otherwise a sweep's ping-then-health pattern would rewind the
        probe clock every pass and the unanswered-probe conviction in
        :meth:`health` could never fire."""
        with self._lock:
            if self._last_ping <= self._last_heard:
                self._last_ping = self._clock()
        try:
            self._send("ping", {"t_mono": time.perf_counter()})
        except ReplicaDeadError:
            pass  # already marked dead with the reason

    def health(self) -> Optional[str]:
        """Last-heard liveness that never false-positives on an IDLE
        replica: silence past ``timeout_s`` only escalates to dead after
        a probe sent SINCE the last frame has itself gone unanswered for
        the timeout window. A sweep cadence slower than ``timeout_s``
        therefore asks first (ping) and convicts on the next look — a
        healthy-but-quiet fleet is never ejected, while a genuinely
        partitioned peer is declared within one probe window and its
        pending work re-admitted (never waiting on TCP retransmit
        timescales)."""
        now = self._clock()
        with self._lock:
            if self._dead_reason is not None:
                return f"dead: {self._dead_reason}"
            age = now - self._last_heard
            probe_age = now - self._last_ping
            probed_since_heard = self._last_ping > self._last_heard
            remote = self._remote["health"]
        if age > self.timeout_s:
            if probed_since_heard and probe_age > self.timeout_s:
                self._mark_dead(
                    f"unresponsive: last frame {age:.1f}s ago and a probe "
                    f"{probe_age:.1f}s ago went unanswered "
                    f"(timeout {self.timeout_s:g}s)")
                return f"dead: unresponsive for {age:.1f}s"
            if not probed_since_heard:
                self.ping()  # ask now; the next look convicts or clears
        return remote

    def is_dead(self) -> bool:
        with self._lock:
            return self._dead_reason is not None

    def stats(self, timeout: Optional[float] = 10.0
              ) -> Dict[str, Any]:  # dcnn: protocol=replica.c2s role=sender
        with self._lock:
            if self._dead_reason is not None:
                raise ReplicaDeadError(
                    f"replica {self.name} is dead: {self._dead_reason}")
            rid = self._next_id
            self._next_id += 1
            fut: Future = Future()
            self._stats[rid] = fut
        self._send("stats", {"id": rid})
        return fut.result(timeout=timeout)

    def swap(self, version,
             timeout: Optional[float] = 300.0
             ) -> None:  # dcnn: protocol=replica.c2s role=sender
        """Remote drain → load → rejoin; blocks until the server answers
        ``swapped`` or ``error`` (re-raised typed). A wait past
        ``timeout`` surfaces as :class:`SwapError` too, with the pending
        entry dropped so a late reply cannot land in an orphan."""
        with self._lock:
            if self._dead_reason is not None:
                raise ReplicaDeadError(
                    f"replica {self.name} is dead: {self._dead_reason}")
            rid = self._next_id
            self._next_id += 1
            fut: Future = Future()
            self._swaps[rid] = fut
        self._send("swap", {"id": rid, "version": version})
        exc: BaseException
        try:
            fut.result(timeout=timeout)
            return
        except ReplicaError as e:
            exc = e
        except (TimeoutError, FutureTimeoutError) as e:
            # pre-3.11 futures raise their own TimeoutError class
            with self._lock:
                self._swaps.pop(rid, None)
            exc = e
        raise SwapError(f"replica {self.name}: remote swap to "
                        f"{version!r} failed: {exc}") from exc

    def close(self) -> None:
        self._chan.close()
        self._reader.join(timeout=10.0)
        self._mark_dead("closed by router")

    def __enter__(self) -> "TcpReplica":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        with self._lock:
            dead = self._dead_reason
        state = f"dead: {dead}" if dead else "up"
        return f"TcpReplica({self.name!r}, {state})"
