"""The shared diurnal autoscale soak — one driver, three consumers.

The ISSUE-11 acceptance story is an open-loop soak: a 10x peak-to-trough
diurnal traffic curve through the router while the autoscaler breathes
the fleet, with a replica preemption and a canary swap injected
mid-load, gated on availability, SLO-violation minutes, and scale-up
reaction time. This module IS that soak, shared verbatim by

- ``tests/test_autoscale.py`` (tier-1: asserts the gates, sleep-free),
- ``bench.py`` ``BENCH_AUTOSCALE=1`` (emits the ``autoscale`` block the
  ``autoscale.*`` regression-gate keys read), and
- ``examples/serve_autoscale.py`` (prints the fleet breathing),

so the offered load, injected faults, and gate arithmetic are produced
identically everywhere — the same contract ``traffic.open_loop``
established for the constant-rate case in PR 2.

Everything runs on a :class:`ManualClock` (no real sleeps): replica
dispatchers are stepped on a fixed service cadence and the autoscaler is
ticked on its own cadence by :func:`run_diurnal_soak`'s virtual-time
event loop, so a four-minute soak takes well under a second of wall and
is exactly reproducible. The replicas are real ``LocalReplica``s over a
:class:`SyntheticEngine` (numpy ``x + version`` — the control loop under
test is the router/autoscaler tier, not XLA).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .autoscale import Autoscaler, AutoscalerConfig
from .metrics import RouterMetrics, ServeMetrics
from .replica import LocalReplica
from .router import Router
from .traffic import diurnal, open_loop


class ManualClock:
    """A monotonic clock advanced by hand — the injectable-clock twin of
    ``time.monotonic`` every layer of the serve stack accepts."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class SyntheticEngine:
    """Batcher-compatible engine without jax: logits = x + version.
    Deterministic and instant, so soak outcomes measure the control
    loop, not compute jitter."""

    def __init__(self, version: Any = 1, name: str = "synthetic",
                 features: int = 4):
        self.input_shape = (features,)
        self.max_batch = 8
        self.bucket_sizes = [1, 2, 4, 8]
        self.name = name
        self.version = version
        self.batch_invariant = True

    def bucket_for(self, n: int) -> int:
        for b in self.bucket_sizes:
            if b >= n:
                return b
        raise ValueError(n)

    def pad_to_bucket(self, x):
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        b = self.bucket_for(n)
        if b > n:
            pad = np.zeros((b - n,) + x.shape[1:], np.float32)
            x = np.concatenate([x, pad])
        return x, n

    def run_padded(self, x):
        return np.asarray(x, np.float32) + (self.version or 0)


def synthetic_engine_factory(version: Any) -> SyntheticEngine:
    return SyntheticEngine(1 if version is None else version)


def make_soak_replica_factory(clock: Callable[[], float], *,
                              queue_capacity: int = 32,
                              prefix: str = "as",
                              window: Optional[int] = None
                              ) -> Callable[[Any], LocalReplica]:
    """``factory(version) -> LocalReplica`` over :class:`SyntheticEngine`
    — the autoscaler's spin-up path in every soak consumer. ``window``
    sizes the replica's ServeMetrics latency window (small windows age a
    cleared overload out of the p99 breach verdict quickly)."""
    made = [0]

    def factory(version: Any) -> LocalReplica:
        made[0] += 1
        metrics = (ServeMetrics(window=window, clock=clock)
                   if window is not None else None)
        return LocalReplica(
            synthetic_engine_factory, 1 if version is None else version,
            name=f"{prefix}{made[0]}", queue_capacity=queue_capacity,
            clock=clock, start=False, metrics=metrics)
    return factory


def run_diurnal_soak(*, seconds: float = 240.0, period: float = 240.0,
                     peak: float = 200.0, trough: float = 20.0,
                     service_dt: float = 0.1, tick_dt: float = 1.0,
                     kill_at: Optional[float] = 100.0,
                     canary_at: Optional[float] = 140.0,
                     slo_p99_ms: float = 150.0,
                     config: Optional[AutoscalerConfig] = None,
                     on_tick: Optional[Callable[[float, int], None]] = None
                     ) -> Tuple[Dict[str, Any], Autoscaler, Router]:
    """The sleep-free acceptance soak (module docstring). Returns
    ``(report, scaler, router)``; the report carries exactly the gate
    keys the ``BENCH_AUTOSCALE`` block emits and the regression gate
    reads (availability, slo_violation_minutes, scale_up_reaction_s,
    plus the breathing evidence). ``kill_at``/``canary_at`` of ``None``
    skip that injection; ``on_tick(t, fleet_size)`` observes each
    autoscaler turn (the example's live printout)."""
    fc = ManualClock()
    # window=512: the replica p99 describes the last few seconds of
    # traffic at soak rates, so a cleared overload ages out of the
    # breach verdict quickly instead of pinning it for half a minute
    factory = make_soak_replica_factory(fc, queue_capacity=32, window=512)
    boot = factory(1)
    cfg = config if config is not None else AutoscalerConfig(
        slo_p99_ms=slo_p99_ms, max_shed_fraction=0.0,
        high_utilization=0.70, low_utilization=0.20,
        min_replicas=1, max_replicas=6,
        up_cooldown_s=5.0, down_cooldown_s=20.0,
        breach_ticks=1, idle_ticks=3, drain_timeout_s=2.0)

    def pump_all():
        for rep in router.replicas().values():
            try:
                rep.step(force=True)
            except Exception:
                pass

    def router_sleep(dt):
        fc.advance(dt)
        pump_all()
    # router_sleep closes over `router` by name — bound below, before any
    # drain/decommission can call it
    router = Router(clock=fc, sleep=router_sleep,
                    metrics=RouterMetrics(clock=fc))
    router.add_replica(boot)
    scaler = Autoscaler(router, factory, config=cfg, clock=fc)

    state = {"next_service": 0.0, "next_tick": 0.0, "killed": False,
             "canaried": False, "fleet_sizes": [], "deaths": 0}

    def drive_until(t_end):
        while fc.t < t_end:
            nxt = min(t_end, state["next_service"], state["next_tick"])
            if fc.t < nxt:
                fc.advance(nxt - fc.t)
            if fc.t >= state["next_service"]:
                pump_all()
                state["next_service"] += service_dt
            if fc.t >= state["next_tick"]:
                if not state["killed"] and kill_at is not None \
                        and fc.t >= kill_at:
                    state["killed"] = True
                    victims = [r for n, r in router.replicas().items()
                               if not r.is_dead()]
                    victims[-1].kill()     # preemption mid-load
                    state["deaths"] += 1
                if not state["canaried"] and canary_at is not None \
                        and fc.t >= canary_at:
                    state["canaried"] = True
                    up = [n for n, st in router.replica_stats().items()
                          if st["state"] == "up"]
                    router.swap_replica(up[0], 2, canary=True)
                scaler.tick()
                fleet = sum(1 for st in router.replica_stats().values()
                            if st["state"] == "up")
                state["fleet_sizes"].append((fc.t, fleet))
                if on_tick is not None:
                    on_tick(fc.t, fleet)
                state["next_tick"] += tick_dt

    def soak_sleep(dt):
        drive_until(fc.t + dt)

    rate = diurnal(peak, trough, period_s=period)
    samples = [np.full((4,), 7, np.float32)]
    futs = open_loop(router, samples, rate, seconds,
                     clock=fc, sleep=soak_sleep)
    # run down the tail: no new arrivals, let everything settle
    deadline = fc.t + 30.0
    while router.outstanding() and fc.t < deadline:
        drive_until(fc.t + service_dt)
    accepted = len(futs)
    completed = sum(1 for _, f in futs
                    if f.done() and f.exception() is None)
    typed = sum(1 for _, f in futs
                if f.done() and f.exception() is not None)
    undone = accepted - completed - typed
    snap = scaler.router.metrics.registry.snapshot()
    sizes = [n for _, n in state["fleet_sizes"]]
    report = {
        "accepted": accepted,
        "completed": completed,
        "typed_failures": typed,
        "silently_dropped": undone,
        "availability": completed / accepted if accepted else None,
        "outstanding_after": router.outstanding(),
        "scale_ups": snap["autoscale_scale_ups_total"],
        "scale_downs": snap["autoscale_scale_downs_total"],
        "slo_violation_minutes":
            snap["autoscale_slo_violation_seconds_total"] / 60.0,
        "reaction_max_s": snap["autoscale_scale_up_reaction_seconds"]["max"],
        "peak_fleet": max(sizes),
        "final_fleet": sizes[-1],
        "shed": snap["serve_router_shed_normal_total"],
    }
    # monitoring-plane evidence: every tick's collect() flowed through
    # the scaler's FleetAggregator, so its tsdb holds the soak's
    # time-resolved fleet history — summarized here so the
    # BENCH_AUTOSCALE block carries it
    from ..obs.tsdb import series_stats
    store = scaler.aggregator.store
    report["history"] = {
        "series": len(store.series_names()),
        "points": store.points(),
        "p99_ms_max": series_stats(
            store.range('serve_latency_window_p99_ms{fleet="max"}')),
        "queue_depth_sum": series_stats(
            store.range('serve_queue_depth{fleet="sum"}')),
    }
    return report, scaler, router
