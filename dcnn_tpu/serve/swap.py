"""Versioned model hot-swap: checkpoint watching, canary rollout,
auto-promote, instant rollback.

The training side already gives serving everything it needs for safe
version changes: ``CheckpointManager`` commits are atomic directories
with checksum-verified manifests, so "the newest version" is a
well-defined, corruption-proof question. This module closes the loop:

- :func:`newest_valid_version` — the newest **checksum-valid** committed
  step under a checkpoint root. A torn/bit-flipped newest commit is
  skipped to the previous valid one (warned + counted on
  ``serve_swap_versions_skipped_total``) and — unlike the training-side
  ``restore_latest`` — **never quarantined or renamed**: the serving
  tier is a read-only consumer of the training run's directory.
- :class:`EngineFactory` — ``factory(version) -> InferenceEngine`` over
  a checkpoint root with the deployment transforms (fold / int8 calib)
  fixed at construction, so every replica of a fleet builds *the same
  graph* for a given version. The ``serve.swap`` fault point fires in
  the load path.
- :class:`ModelVersionManager` — the control loop over a
  :class:`~dcnn_tpu.serve.router.Router`:

  1. **Watch**: each :meth:`poll` discovers the newest valid version.
  2. **Canary**: a new version rolls out to ``ceil(canary_fraction·N)``
     replicas via drain → load → rejoin (``Router.swap_replica``); the
     rest keep serving the old version, so traffic is mixed-version with
     zero shed increase (capacity only dips by the replica being
     drained, which admission sees).
  3. **Judge**: per-replica completion/failure/latency deltas since
     canary start (``Router.replica_stats``). An error-rate or latency
     regression against the stable set triggers **instant rollback** —
     canaries are swapped back and the version is quarantined (never
     auto-retried). A clean observation window
     (``observe_s`` on the injectable clock, ``min_canary_requests``
     completions) **auto-promotes**: the remaining replicas swap up.

  Everything is driven by explicit :meth:`poll` calls — sleep-free under
  a fake clock in tests; production wires :meth:`start` (a daemon poll
  thread with a ``stop()`` owner, or calls ``poll()`` from any existing
  control loop).
"""

from __future__ import annotations

import math
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import get_registry
from ..resilience import faults as _faults
from ..resilience.checkpoint import list_steps, verify_dir
from .replica import SwapError


class NoValidVersionError(RuntimeError):
    """No checksum-valid committed checkpoint exists under the root."""


def newest_valid_version(directory: str, *, registry=None
                         ) -> Optional[Tuple[int, str]]:
    """``(step, path)`` of the newest checksum-valid ``ckpt-*`` commit
    under ``directory``, or ``None`` when no valid one exists. Corrupt
    newer candidates are skipped (warned, counted) but never touched on
    disk — read-only by contract."""
    reg = registry if registry is not None else get_registry()
    for step, path in sorted(list_steps(directory).items(), reverse=True):
        if verify_dir(path):
            return step, path
        warnings.warn(
            f"serve/swap: skipping torn/corrupt checkpoint {path} "
            f"(manifest/checksum mismatch); falling back to the previous "
            f"valid version", stacklevel=2)
        reg.counter("serve_swap_versions_skipped_total",
                    "corrupt checkpoint versions skipped by the serving "
                    "tier").inc()
    return None


class EngineFactory:
    """``factory(version) -> InferenceEngine`` over one checkpoint root.

    The deployment transforms are fixed here — every replica built from
    this factory serves the identical graph for a given version (the
    int8 calibration batch included, so the cross-bucket bit-identity
    contract holds fleet-wide). ``engine_kwargs`` forward to
    :meth:`InferenceEngine.from_model` (``max_batch``, ``fold``,
    ``int8_calib``, ...)."""

    def __init__(self, directory: str, *, registry=None, **engine_kwargs):
        self.directory = directory
        self._registry = registry
        self._kw = engine_kwargs

    def newest(self) -> Optional[int]:
        """Newest checksum-valid version (step), or ``None``."""
        found = newest_valid_version(self.directory,
                                     registry=self._registry)
        return found[0] if found else None

    def __call__(self, version: int):
        from .engine import InferenceEngine

        _faults.trip("serve.swap", version=version,
                     directory=self.directory)
        path = os.path.join(self.directory, f"ckpt-{int(version):08d}")
        if not verify_dir(path):
            raise NoValidVersionError(
                f"version {version} at {path} is missing or fails its "
                f"manifest checksums")
        kw = dict(self._kw)
        kw.setdefault("name", f"v{int(version)}")
        eng = InferenceEngine.from_checkpoint(path, **kw)
        eng.version = int(version)
        return eng


class ModelVersionManager:
    """Canary rollout / auto-promote / instant rollback over a router.

    ``factory`` is typically an :class:`EngineFactory` (its ``newest()``
    is the version watch); any object with ``newest() -> version`` works
    — the actual loading happens inside each replica's own factory via
    ``Router.swap_replica``. Judgement thresholds:

    - ``max_error_delta`` — rollback when the canary set's failure ratio
      since canary start exceeds the stable set's by more than this;
    - ``max_latency_ratio`` — rollback when the canary set's mean
      completion-latency EWMA exceeds ``max_latency_ratio ×`` the stable
      set's (an EWMA verdict, deliberately named so — windowed p99 is on
      the per-replica scrape surface but is not what this judges; both
      sides need
      ``min_canary_requests`` completions first — latency noise on three
      requests must not roll a good version back).
    """

    def __init__(self, router, factory, *, canary_fraction: float = 0.25,
                 observe_s: float = 30.0, min_canary_requests: int = 20,
                 min_error_samples: int = 5,
                 max_error_delta: float = 0.02,
                 max_latency_ratio: float = 3.0,
                 current_version: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 flight=None):
        if not 0.0 < canary_fraction <= 1.0:
            raise ValueError(f"canary_fraction must be in (0, 1], "
                             f"got {canary_fraction}")
        self._flight = flight  # None: process-global flight recorder
        self.router = router
        self.factory = factory
        self.canary_fraction = canary_fraction
        self.observe_s = observe_s
        self.min_canary_requests = min_canary_requests
        # floor for the error-ratio rollback: one transient failure on a
        # canary's very first request (the same class the router happily
        # re-admits) must not permanently quarantine a good version
        self.min_error_samples = min_error_samples
        self.max_error_delta = max_error_delta
        self.max_latency_ratio = max_latency_ratio
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "idle"                 # dcnn: guarded_by=_lock
        self._current = current_version      # dcnn: guarded_by=_lock
        self._target: Optional[int] = None   # dcnn: guarded_by=_lock
        self._canaries: List[str] = []       # dcnn: guarded_by=_lock
        self._pre_versions: Dict[str, Any] = {}  # dcnn: guarded_by=_lock
        self._t_canary: float = 0.0          # dcnn: guarded_by=_lock
        self._base: Dict[str, Dict] = {}     # dcnn: guarded_by=_lock
        self._quarantined: set = set()       # dcnn: guarded_by=_lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if current_version is None:
            # adopt the fleet's version (first replica that knows one)
            for st in router.replica_stats().values():
                if st["version"] is not None:
                    with self._lock:
                        self._current = st["version"]
                    break
        self._export_gauges()

    # -- introspection -----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def current_version(self):
        with self._lock:
            return self._current

    @property
    def target_version(self):
        with self._lock:
            return self._target

    @property
    def canaries(self) -> List[str]:
        with self._lock:
            return list(self._canaries)

    @property
    def quarantined(self) -> set:
        with self._lock:
            return set(self._quarantined)

    def _export_gauges(self) -> None:
        with self._lock:
            cur = self._current
        if cur is not None:
            self.router.metrics.version.set(cur)

    # -- the control loop --------------------------------------------------
    def poll(self) -> Dict[str, Any]:
        """One state-machine turn. Returns
        ``{"action": ..., "version": ..., "canaries": [...]}`` where
        action ∈ ``none | canary | canary_wait | promoted | rolled_back |
        swap_failed``."""
        self.router.check_replicas()  # judge on fresh liveness
        with self._lock:
            state = self._state
        if state == "idle":
            return self._poll_idle()
        return self._poll_canary()

    def _poll_idle(self) -> Dict[str, Any]:
        newest = self.factory.newest()
        with self._lock:
            cur, quarantined = self._current, set(self._quarantined)
        if newest is None or newest in quarantined \
                or (cur is not None and newest <= cur):
            healed = self._reconcile(cur)
            out = {"action": "none", "version": cur, "canaries": []}
            if healed:
                out["action"] = "reconciled"
                out["reconciled"] = healed
            return out
        return self._begin_canary(newest)

    def _reconcile(self, cur) -> List[str]:
        """Heal version drift: a replica that was dead through a promote
        (it rejoins serving the pre-promote version) or whose
        promote-time swap failed is swapped up to ``cur`` here — the idle
        watch converges the fleet instead of serving mixed versions
        forever. Failures stay visible via the swap_failures counter and
        are retried next poll."""
        if cur is None:
            return []
        healed: List[str] = []
        for name, st in self.router.replica_stats().items():
            if st["state"] == "up" and st["version"] is not None \
                    and st["version"] != cur:
                try:
                    self.router.swap_replica(name, cur, canary=False)
                    healed.append(name)
                except Exception:
                    pass
        return healed

    def _begin_canary(self, version: int) -> Dict[str, Any]:
        stats = self.router.replica_stats()
        up = sorted(n for n, st in stats.items() if st["state"] == "up")
        if not up:
            return {"action": "none", "version": self.current_version,
                    "canaries": [], "reason": "no routable replicas"}
        k = max(1, math.ceil(self.canary_fraction * len(up)))
        k = min(k, len(up))
        # remember each canary's OWN pre-canary version: rollback returns
        # a replica to what IT was serving, which works even when the
        # manager never learned a fleet-wide current version
        pre = {name: stats[name]["version"] for name in up[:k]}
        canaries: List[str] = []
        version_failed: Optional[SwapError] = None
        for name in up[:k]:
            try:
                self.router.swap_replica(name, version, canary=True)
                canaries.append(name)
            except SwapError as e:
                # the VERSION failed to load — a version verdict
                version_failed = e
                break
            except Exception:
                # the REPLICA failed (died between the snapshot and the
                # swap) — not the version's fault: skip it, don't
                # quarantine; the liveness sweep owns the replica
                continue
        if version_failed is not None:
            # the version cannot even load — quarantine it now and undo
            # any canary that did come up
            for name in canaries:
                old = pre.get(name)
                try:
                    if old is not None:
                        self.router.swap_replica(name, old, canary=False)
                    else:
                        self.router.set_canary(name, False)
                except Exception:
                    self.router.set_canary(name, False)
            with self._lock:
                self._quarantined.add(version)
            return {"action": "swap_failed", "version": version,
                    "canaries": canaries, "reason": str(version_failed)}
        if not canaries:
            # only replica failures — retry the rollout on a later poll
            return {"action": "none", "version": self.current_version,
                    "canaries": [],
                    "reason": "no canary could be started (replica "
                              "failures, version not judged)"}
        with self._lock:
            self._state = "canary"
            self._target = version
            self._canaries = canaries
            self._pre_versions = {n: pre.get(n) for n in canaries}
            self._t_canary = self._clock()
            self._base = {n: dict(st) for n, st in
                          self.router.replica_stats().items()}
        self.router.metrics.registry.gauge(
            "serve_router_target_version",
            "version under canary").set(version)
        return {"action": "canary", "version": version,
                "canaries": list(canaries)}

    def _deltas(self) -> Tuple[Dict[str, int], Dict[str, int],
                               Optional[float], Optional[float]]:
        """(canary {completed, failed}, stable {completed, failed},
        canary ewma_ms, stable ewma_ms) since canary start."""
        stats = self.router.replica_stats()
        with self._lock:
            base, canaries = self._base, set(self._canaries)
        cd = {"completed": 0, "failed": 0}
        sd = {"completed": 0, "failed": 0}
        c_lat: List[float] = []
        s_lat: List[float] = []
        for name, st in stats.items():
            b = base.get(name, {"completed": 0, "failed": 0})
            d = (cd if name in canaries else sd)
            d["completed"] += st["completed"] - b["completed"]
            d["failed"] += st["failed"] - b["failed"]
            if st["ewma_ms"] is not None:
                (c_lat if name in canaries else s_lat).append(st["ewma_ms"])
        c_ewma = (sum(c_lat) / len(c_lat)) if c_lat else None
        s_ewma = (sum(s_lat) / len(s_lat)) if s_lat else None
        return cd, sd, c_ewma, s_ewma

    @staticmethod
    def _ratio(d: Dict[str, int]) -> Optional[float]:
        n = d["completed"] + d["failed"]
        return (d["failed"] / n) if n else None

    def _poll_canary(self) -> Dict[str, Any]:
        cd, sd, c_ewma, s_ewma = self._deltas()
        with self._lock:
            version, canaries = self._target, list(self._canaries)
            elapsed = self._clock() - self._t_canary
        c_ratio, s_ratio = self._ratio(cd), self._ratio(sd)
        # -- instant rollback: error-rate regression -----------------------
        # two floors against small-sample noise: enough total samples AND
        # at least two failures — one transiently-failed (and re-admitted)
        # request is never a version verdict
        if c_ratio is not None and cd["failed"] >= 2 \
                and cd["completed"] + cd["failed"] >= self.min_error_samples \
                and c_ratio > (s_ratio or 0.0) + self.max_error_delta:
            return self._rollback(
                f"canary error ratio {c_ratio:.3f} vs stable "
                f"{(s_ratio or 0.0):.3f} (+{self.max_error_delta:g} "
                f"allowed)")
        # -- instant rollback: latency regression --------------------------
        enough = (cd["completed"] >= self.min_canary_requests
                  and sd["completed"] >= self.min_canary_requests)
        if enough and c_ewma is not None and s_ewma is not None \
                and s_ewma > 0 and c_ewma > self.max_latency_ratio * s_ewma:
            return self._rollback(
                f"canary latency {c_ewma:.2f}ms vs stable "
                f"{s_ewma:.2f}ms (> {self.max_latency_ratio:g}x)")
        # -- promote on a clean window -------------------------------------
        if elapsed >= self.observe_s \
                and cd["completed"] >= self.min_canary_requests:
            return self._promote()
        return {"action": "canary_wait", "version": version,
                "canaries": canaries, "elapsed_s": elapsed,
                "canary": cd, "stable": sd}

    def _rollback(self, reason: str) -> Dict[str, Any]:
        with self._lock:
            version, canaries = self._target, list(self._canaries)
            old = self._current
            pre = dict(self._pre_versions)
        for name in canaries:
            # prefer the replica's OWN pre-canary version (defined even
            # when the manager never learned a fleet-wide current one)
            target = pre.get(name) if pre.get(name) is not None else old
            try:
                if target is not None:
                    self.router.swap_replica(name, target, canary=False)
                else:
                    self.router.set_canary(name, False)
            except Exception:
                # a canary that cannot even reload the old version is a
                # replica problem, not a version problem — the liveness
                # sweep owns it from here
                self.router.set_canary(name, False)
        with self._lock:
            self._quarantined.add(version)
            quarantined = sorted(map(repr, self._quarantined))
            self._state = "idle"
            self._target = None
            self._canaries = []
            self._pre_versions = {}
            self._base = {}
        self.router.metrics.record_rollback()
        self._export_gauges()
        # postmortem bundle at the rollback edge: the judged deltas in
        # `reason`, the quarantined version, and the spans/metrics of the
        # canary window (no-op while the flight recorder is disabled)
        from ..obs.flight import resolve_flight_recorder
        resolve_flight_recorder(self._flight).record(
            "canary_rollback", reasons=[reason],
            registry=self.router.metrics.registry,
            config={"version": version, "canaries": canaries,
                    "pre_versions": {k: repr(v) for k, v in pre.items()},
                    "quarantined": quarantined})
        return {"action": "rolled_back", "version": version,
                "canaries": canaries, "reason": reason}

    def _promote(self) -> Dict[str, Any]:
        with self._lock:
            version, canaries = self._target, set(self._canaries)
        stats = self.router.replica_stats()
        rest = sorted(n for n, st in stats.items()
                      if st["state"] == "up" and n not in canaries
                      and st["version"] != version)
        failed: List[str] = []
        for name in rest:
            try:
                self.router.swap_replica(name, version, canary=False)
            except Exception:
                failed.append(name)  # SwapError: rejoined on the old
                # version; death mid-promote: the sweep owns it — either
                # way the idle watch's _reconcile converges it later
        for name in canaries:
            self.router.set_canary(name, False)
        with self._lock:
            self._current = version
            self._state = "idle"
            self._target = None
            self._canaries = []
            self._pre_versions = {}
            self._base = {}
        self.router.metrics.record_promotion()
        self._export_gauges()
        return {"action": "promoted", "version": version,
                "canaries": sorted(canaries), "swap_failed": failed}

    # -- background polling (production convenience) -----------------------
    def start(self, interval_s: float = 5.0) -> "ModelVersionManager":
        """Poll on a daemon thread every ``interval_s``; idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval_s,), daemon=True,
            name="dcnn-version-manager")
        self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.poll()
            except Exception:
                pass  # a broken poll must not kill the watch loop;
                # verdicts surface via counters/healthz, not this thread

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "ModelVersionManager":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def __repr__(self) -> str:
        with self._lock:
            return (f"ModelVersionManager(state={self._state!r}, "
                    f"current={self._current!r}, target={self._target!r})")
