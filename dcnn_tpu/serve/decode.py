"""Continuous-batching autoregressive decode: the generative serving stack.

One-shot classification (engine.py/batcher.py) dispatches a request once
and is done; generative decode holds a **slot** for hundreds of steps and
completes at a data-dependent length. Batching those naively — drain the
whole batch, then admit the next — leaves slots idle from the moment their
sequence finishes until the *longest* sequence in the batch does, which is
where decode throughput actually dies. This module implements the
iteration-level alternative (the Orca/vLLM line):

- :class:`DecodeEngine` — the compiled half: ONE jitted fixed-shape decode
  step (embed → per-layer scatter-K/V-into-pages → gather → causal attend
  → head → greedy argmax), lowered once per **(batch-bucket, page-bucket)**
  in the constructor (TS06-clean: one ``jax.jit``, per-bucket
  ``lower().compile()``, exactly like ``InferenceEngine``) and optionally
  warmed from the AOT executable cache via ``aot.warm_or_compile`` — so
  admitting a sequence mid-flight can NEVER retrace or recompile
  (``tests/test_decode.py`` asserts a zero ``compile_total`` delta);
- :class:`KVPagePool` (``kvcache.py``) — paged KV memory with free-list
  recycling, so slot count is bounded by the *working set*, not the
  worst-case sequence length;
- :class:`ContinuousBatcher` — the scheduler: admits pending sequences
  into free slots at **step boundaries** (no drain), retires each
  sequence the step it completes, and on page exhaustion preempts the
  most-recently-admitted sequence back to the queue
  (recompute-on-readmission — greedy decode is deterministic, so the
  replay is bit-exact). Same operational contract as
  :class:`~dcnn_tpu.serve.batcher.DynamicBatcher`: bounded intake
  (:class:`~dcnn_tpu.serve.batcher.QueueFullError`), typed refusal while
  draining, an accepted-futures ledger with the no-orphan guarantee, a
  sleep-free ``start=False`` synchronous mode, and ``decode.step`` /
  ``decode.admit`` fault trip points (``resilience/faults.py``).

Determinism contract (the acceptance bar): per-row computation in the
decode step depends only on that row's token/position/page-table and the
pages that row owns — padding rows ride the null page and mask to exact
zeros — so a sequence's greedy output is **bit-identical** whether it
decoded alone (:func:`decode_reference`) or interleaved with any mix of
neighbours under any admission order. ``tests/test_decode.py`` asserts
this across interleavings; ``examples/serve_decode.py`` demos it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import get_registry, get_tracer
from ..obs.xla import executable_cost, record_compile, sample_hbm
from ..resilience import faults
from ..resilience.faults import InjectedCrash
from .batcher import DrainingError, QueueFullError, ShutdownError
from .engine import InferenceEngine, serve_buckets
from .kvcache import KVPagePool, OutOfPagesError, suggest_num_pages
from .metrics import DecodeMetrics


class DecodeEngine:
    """Bucketed, pre-compiled, paged decode steps over one
    :class:`~dcnn_tpu.models.decoder.MHADecoder` checkpoint.

    The step function is written once and lowered per
    ``(batch_bucket, page_bucket)``: batch buckets are
    :func:`~dcnn_tpu.serve.engine.serve_buckets` of ``max_slots``; page
    buckets the same powers-of-two ladder over ``max_pages_per_seq``
    (page-table width — context grows through wider tables, not
    recompiles). ``num_pages=None`` sizes the pool from live HBM headroom
    (:func:`~dcnn_tpu.serve.kvcache.suggest_num_pages`), with a CPU
    default of every slot at full context.
    """

    def __init__(self, model, params, *, max_slots: int = 4,
                 page_size: int = 8, max_pages_per_seq: int = 4,
                 num_pages: Optional[int] = None,
                 donate: Optional[bool] = None, warmup: bool = True,
                 name: str = "decode", registry=None,
                 aot_cache: Any = None, aot_config: Optional[str] = None):
        self.model = model
        self.params = params
        self.name = name
        self.registry = registry if registry is not None else get_registry()
        self.bucket_sizes = serve_buckets(max_slots)
        self.max_slots = self.bucket_sizes[-1]
        self.page_buckets = serve_buckets(max_pages_per_seq)
        self.max_pages_per_seq = self.page_buckets[-1]
        self.page_size = int(page_size)
        self.max_context = self.max_pages_per_seq * self.page_size
        if self.max_context > model.max_seq_len:
            raise ValueError(
                f"max context {self.max_context} "
                f"({self.max_pages_per_seq} pages x {self.page_size}) "
                f"exceeds model max_seq_len {model.max_seq_len}")
        if num_pages is None:
            # worst case every slot at full context, + the null page; the
            # HBM-headroom suggestion can only grow it (more slack for
            # admission before preemption kicks in)
            floor = 1 + self.max_slots * self.max_pages_per_seq
            probe = KVPagePool(num_layers=model.num_layers,
                               embed_dim=model.embed_dim,
                               page_size=self.page_size, num_pages=2)
            num_pages = max(floor, suggest_num_pages(
                probe.page_bytes, default=floor, registry=self.registry))
        self.pool = KVPagePool(num_layers=model.num_layers,
                               embed_dim=model.embed_dim,
                               page_size=self.page_size,
                               num_pages=num_pages)
        if donate is None:
            # donation is a no-op (plus a warning per compile) on CPU
            donate = jax.default_backend() in ("tpu", "gpu")
        self._donate = bool(donate)

        page_size_ = self.page_size
        blocks, bparams = model.blocks, params["blocks"]

        def step_fn(tokens, positions, page_table, pool_k, pool_v):
            b = tokens.shape[0]
            mp = page_table.shape[1]
            x = model.embed_tokens(params, tokens)
            active = positions >= 0
            pos_c = jnp.maximum(positions, 0)
            pg, slot = pos_c // page_size_, pos_c % page_size_
            rows = jnp.arange(b)
            # inactive rows scatter onto the null page (kvcache.py) —
            # colliding writes land where nothing ever reads
            phys = jnp.where(active, page_table[rows, pg], 0)
            for li, (blk, bp) in enumerate(zip(blocks, bparams)):
                q, k_t, v_t = blk.decode_qkv(bp, x)
                pool_k = pool_k.at[li, phys, slot].set(k_t)
                pool_v = pool_v.at[li, phys, slot].set(v_t)
                # gather each row's pages into a (b, mp*page, E) context;
                # table padding gathers the null page, masked to exact 0
                # by decode_attend (positions past pos are NEG_INF'd)
                ctx_k = pool_k[li][page_table].reshape(b, mp * page_size_,
                                                       -1)
                ctx_v = pool_v[li][page_table].reshape(b, mp * page_size_,
                                                       -1)
                y = blk.decode_attend(bp, q, ctx_k, ctx_v, positions)
                x = jax.nn.relu(y + x)
            logits = model.head(params, x)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, logits, pool_k, pool_v

        donate_argnums = (3, 4) if self._donate else ()
        jitted = jax.jit(step_fn, donate_argnums=donate_argnums)
        if aot_cache is not False and not aot_config:
            aot_config = self._derive_aot_config(aot_cache, num_pages)
        aot = InferenceEngine._resolve_aot(aot_cache, aot_config)
        pool_spec = jax.ShapeDtypeStruct(self.pool.k.shape, self.pool.dtype)
        self._sessions: Dict[Tuple[int, int], Any] = {}
        self.compile_stats: Dict[Tuple[int, int], Dict[str, float]] = {}
        tracer = get_tracer()
        for b in self.bucket_sizes:
            for mp in self.page_buckets:
                specs = (jax.ShapeDtypeStruct((b,), jnp.int32),
                         jax.ShapeDtypeStruct((b,), jnp.int32),
                         jax.ShapeDtypeStruct((b, mp), jnp.int32),
                         pool_spec, pool_spec)
                aot_info = None
                t0 = time.perf_counter()
                with tracer.span("serve.compile", track="serve",
                                 engine=name, bucket=b, pages=mp):
                    if aot is not None:
                        from ..aot import warm_or_compile
                        session, aot_info = warm_or_compile(
                            jitted, *specs, cache=aot, what="decode",
                            config=aot_config, donate=donate_argnums,
                            registry=self.registry)
                    else:
                        session = jitted.lower(*specs).compile()
                compile_s = time.perf_counter() - t0
                if aot_info is None:
                    record_compile(compile_s, what="decode",
                                   registry=self.registry)
                t0 = time.perf_counter()
                if warmup:
                    with tracer.span("serve.warmup", track="serve",
                                     engine=name, bucket=b, pages=mp):
                        # all-inactive warmup batch: writes touch only
                        # the null page, so warmed sessions never dirty
                        # real cache state
                        jax.block_until_ready(session(
                            jnp.zeros((b,), jnp.int32),
                            jnp.full((b,), -1, jnp.int32),
                            jnp.zeros((b, mp), jnp.int32),
                            jnp.zeros(self.pool.k.shape, self.pool.dtype),
                            jnp.zeros(self.pool.k.shape, self.pool.dtype)))
                self._sessions[(b, mp)] = session
                st = {"compile_s": round(compile_s, 4),
                      "warmup_s": round(time.perf_counter() - t0, 4)}
                if aot_info is not None:
                    st["aot_hit"] = aot_info["hit"]
                cost = executable_cost(session)
                if cost is not None:
                    st.update({k: cost[k] for k in
                               ("flops", "bytes_accessed", "temp_bytes")
                               if k in cost})
                self.compile_stats[(b, mp)] = st
        # post-compile HBM watermark: pool + every bucket's executables
        # is the decode-side allocation spike; no-op without memory stats
        sample_hbm(self.registry)

    def _derive_aot_config(self, aot_cache: Any,
                           num_pages: int) -> Optional[str]:
        """Weights-covering cache digest (computed only when the AOT
        cache is actually on — hashing weights is cheap next to a
        compile, pointless next to nothing). The key MUST cover the
        params: jit bakes them into the program as constants."""
        try:
            from ..aot import digest, digest_arrays, enabled_root
            from ..aot.keys import decode_step_key_material
            ac = aot_cache
            on = (enabled_root(ac if isinstance(ac, str) else None)
                  is not None or (ac is not None
                                  and not isinstance(ac, str)))
            if not on:
                return None
            return digest(decode_step_key_material(
                self.model, page_size=self.page_size, num_pages=num_pages,
                weights=digest_arrays(self.params)))
        except Exception:
            return None

    # -- bucket math --
    def bucket_for(self, n: int) -> int:
        """Smallest batch bucket >= n active slots."""
        if not 1 <= n <= self.max_slots:
            raise ValueError(f"active count {n} outside [1, "
                             f"{self.max_slots}]")
        for b in self.bucket_sizes:
            if b >= n:
                return b
        raise AssertionError("unreachable: last bucket is max_slots")

    def page_bucket_for(self, pages: int) -> int:
        """Smallest page-table width bucket >= pages (min 1: even a
        0-length table dispatches at width 1, all null-page)."""
        pages = max(pages, 1)
        if pages > self.max_pages_per_seq:
            raise ValueError(f"{pages} pages exceeds max_pages_per_seq "
                             f"{self.max_pages_per_seq}")
        for mp in self.page_buckets:
            if mp >= pages:
                return mp
        raise AssertionError("unreachable: last bucket is max_pages_per_seq")

    # -- dispatch --
    def run_step(self, tokens, positions, page_table, pool_k, pool_v):
        """Pure bucketed step: shapes must already be exact buckets.
        Returns ``(next_tokens, logits, pool_k, pool_v)`` — the caller
        owns the pool handoff (on accelerator backends the input pools
        are DONATED/consumed). :func:`decode_reference` runs on private
        pools through this; :meth:`step` wraps it over :attr:`pool`."""
        key = (int(tokens.shape[0]), int(page_table.shape[1]))
        session = self._sessions.get(key)
        if session is None:
            raise ValueError(f"no session for (batch, pages)={key}; have "
                             f"{sorted(self._sessions)}")
        return session(jnp.asarray(tokens, jnp.int32),
                       jnp.asarray(positions, jnp.int32),
                       jnp.asarray(page_table, jnp.int32), pool_k, pool_v)

    def step(self, tokens, positions, page_table):
        """One decode step against the engine's own page pool; updates
        :attr:`pool` in place and returns ``(next_tokens, logits)`` as
        host arrays."""
        nxt, logits, k, v = self.run_step(tokens, positions, page_table,
                                          self.pool.k, self.pool.v)
        self.pool.k, self.pool.v = k, v
        return np.asarray(nxt), np.asarray(logits)

    def __repr__(self) -> str:
        return (f"DecodeEngine({self.name!r}, slots={self.bucket_sizes}, "
                f"page_buckets={self.page_buckets}, "
                f"page_size={self.page_size}, "
                f"pool_pages={self.pool.num_pages})")


def decode_reference(engine: DecodeEngine, prompt: Sequence[int], *,
                     max_new_tokens: int = 16,
                     eos_id: Optional[int] = None) -> np.ndarray:
    """Batch-of-one greedy decode of ``prompt`` through the SAME compiled
    sessions the continuous batcher uses — batch bucket 1, page bucket
    following the sequence's own length — on a private zeroed pool (the
    engine's live pool and allocator are untouched). This is the
    per-sequence oracle the bit-identity tests compare the continuous
    batcher against, and the naive baseline the ``BENCH_DECODE`` block
    measures."""
    prompt = [int(t) for t in prompt]
    if not prompt:
        raise ValueError("empty prompt")
    if len(prompt) + max_new_tokens > engine.max_context:
        raise ValueError(f"prompt {len(prompt)} + max_new {max_new_tokens} "
                         f"exceeds max context {engine.max_context}")
    pool_k = jnp.zeros(engine.pool.k.shape, engine.pool.dtype)
    pool_v = jnp.zeros(engine.pool.k.shape, engine.pool.dtype)
    ps = engine.page_size
    tokens = list(prompt)
    generated: List[int] = []
    pos = 0
    while True:
        mp = engine.page_bucket_for(-(-(pos + 1) // ps))
        table = np.zeros((1, mp), np.int32)
        npages = -(-(pos + 1) // ps)
        table[0, :npages] = np.arange(1, npages + 1)
        nxt, _, pool_k, pool_v = engine.run_step(
            np.asarray([tokens[pos]], np.int32),
            np.asarray([pos], np.int32), table, pool_k, pool_v)
        emit = pos == len(tokens) - 1
        pos += 1
        if emit:
            tok = int(np.asarray(nxt)[0])
            tokens.append(tok)
            generated.append(tok)
            if len(generated) >= max_new_tokens or tok == eos_id:
                return np.asarray(generated, np.int32)


class _Seq:
    """One accepted decode request and its slot-resident state."""

    __slots__ = ("seq_id", "tokens", "prompt_len", "max_new_tokens",
                 "eos_id", "future", "t_submit", "first_emit",
                 "generated", "pos")

    def __init__(self, seq_id, prompt, max_new_tokens, eos_id, future,
                 t_submit):
        self.seq_id = seq_id
        self.tokens: List[int] = list(prompt)
        self.prompt_len = len(prompt)
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.future = future
        self.t_submit = t_submit
        self.first_emit = False
        self.generated: List[int] = []
        self.pos = 0  # tokens consumed; a step emits iff pos==len(tokens)-1


class ContinuousBatcher:
    """Iteration-level scheduler over a :class:`DecodeEngine`.

    Each :meth:`step` (one fixed-shape engine dispatch): retire finished
    sequences → admit pending ones into free slots (``decode.admit`` trip
    point) → extend page allocations (preempting the most-recently-
    admitted sequence on :class:`~dcnn_tpu.serve.kvcache.OutOfPagesError`
    — it re-queues and recomputes bit-identically) → dispatch at the
    smallest (batch, page) bucket covering the active set (``decode.step``
    trip point; zero compiles — every bucket pair was built in the engine
    constructor).

    Failure contract mirrors ``DynamicBatcher``: every accepted future is
    ledgered and ALWAYS resolved — completion, typed rejection
    (:class:`~dcnn_tpu.serve.batcher.ShutdownError` on teardown), or the
    step's exception. A crash mid-step (``InjectedCrash``) fails every
    pending + active sequence typed before propagating: no silent drops.

    ``start=False`` runs no thread — tests drive :meth:`step` with an
    injected ``clock``, sleep-free.
    """

    def __init__(self, engine: DecodeEngine, *,
                 max_slots: Optional[int] = None,
                 queue_capacity: int = 64,
                 metrics: Optional[DecodeMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start: bool = True):
        if queue_capacity < 1:
            raise ValueError(f"queue_capacity must be >= 1, "
                             f"got {queue_capacity}")
        self.engine = engine
        self.max_slots = min(max_slots or engine.max_slots,
                             engine.max_slots)
        self.queue_capacity = queue_capacity
        self.metrics = metrics if metrics is not None else DecodeMetrics(
            clock=clock)
        self._clock = clock
        self._cond = threading.Condition()
        self._pending: deque = deque()  # dcnn: guarded_by=_cond
        self._active: List[_Seq] = []  # dcnn: guarded_by=_cond
        # every accepted, unresolved future: the no-orphan ledger
        self._accepted: set = set()  # dcnn: guarded_by=_cond
        self._closing = False  # dcnn: guarded_by=_cond
        self._steps = 0
        self._next_id = 0
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"dcnn-decode-batcher-{engine.name}")
            self._thread.start()

    # -- intake --
    def submit(self, prompt: Sequence[int], *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> Future:
        """Enqueue one greedy-decode request. The future resolves to the
        generated token ids as an int32 array (EOS token included when it
        fired). Raises :class:`~dcnn_tpu.serve.batcher.QueueFullError` at
        capacity and :class:`~dcnn_tpu.serve.batcher.DrainingError` after
        :meth:`drain`/:meth:`shutdown`."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        vocab = self.engine.model.vocab_size
        if any(not 0 <= t < vocab for t in prompt):
            raise ValueError(f"prompt tokens outside [0, {vocab})")
        if len(prompt) + max_new_tokens > self.engine.max_context:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds engine max context {self.engine.max_context}")
        fut: Future = Future()
        with self._cond:
            if self._closing:
                raise DrainingError(
                    "decode batcher is draining or shut down")
            if len(self._pending) >= self.queue_capacity:
                self.metrics.record_shed()
                raise QueueFullError(
                    f"decode queue at capacity ({len(self._pending)}/"
                    f"{self.queue_capacity} sequences)")
            seq = _Seq(self._next_id, prompt, max_new_tokens, eos_id, fut,
                       self._clock())
            self._next_id += 1
            self._pending.append(seq)
            self._accepted.add(fut)
            self.metrics.record_submit()
            self.metrics.record_queue_depth(len(self._pending))
            self._cond.notify_all()
        return fut

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    @property
    def active_slots(self) -> int:
        with self._cond:
            return len(self._active)

    def health_reason(self) -> Optional[str]:
        """``None`` while accepting traffic, else the machine-readable
        refusal — the same ``/healthz`` contract as ``DynamicBatcher``."""
        with self._cond:
            closing = self._closing
        if closing:
            return "draining or shut down: not accepting sequences"
        if self._thread is not None and not self._thread.is_alive():
            return "decode scheduler thread dead"
        return None

    # -- scheduling core --
    def _admit(self) -> None:
        """Move pending sequences into free slots (a step-boundary
        operation — never mid-step). An ``InjectedFault`` at
        ``decode.admit`` fails just that sequence, typed; a crash
        propagates to :meth:`step`'s fail-everything handler."""
        with self._cond:
            while self._pending and len(self._active) < self.max_slots:
                seq = self._pending[0]
                try:
                    faults.trip("decode.admit", seq=seq.seq_id)
                except InjectedCrash:
                    raise
                except Exception as e:
                    self._pending.popleft()
                    self._accepted.discard(seq.future)
                    try:
                        seq.future.set_exception(e)
                    except InvalidStateError:
                        pass
                    continue
                try:
                    self.engine.pool.ensure(seq.seq_id, 1)
                except OutOfPagesError:
                    break  # no room for even one page: admit next step
                self._pending.popleft()
                self._active.append(seq)
                self.metrics.record_admit()
            self.metrics.record_queue_depth(len(self._pending))

    def _preempt_last(self) -> bool:
        """Recompute-preemption: release the most-recently-admitted active
        sequence's pages and re-queue it at the FRONT of pending (it
        re-admits first; greedy decode replays its tokens bit-exactly).
        Returns False when there is nothing to preempt."""
        with self._cond:
            if not self._active:
                return False
            victim = self._active.pop()
            self.engine.pool.release(victim.seq_id)
            victim.pos = 0  # replay prompt + already-generated tokens
            self._pending.appendleft(victim)
            self.metrics.record_evict()
            self.metrics.record_queue_depth(len(self._pending))
        return True

    def _fail_all(self, exc: BaseException) -> int:
        """Fail every accepted, unresolved future with ``exc`` and release
        all pages — the no-orphan guarantee when a step dies. Returns how
        many futures this call failed."""
        with self._cond:
            seqs = list(self._active) + list(self._pending)
            self._active.clear()
            self._pending.clear()
            pending = set(self._accepted)
            self._accepted.clear()
            self.metrics.record_queue_depth(0)
        for s in seqs:
            self.engine.pool.release(s.seq_id)
        failed = 0
        for fut in pending:
            try:
                fut.set_exception(exc if isinstance(exc, Exception)
                                  else ShutdownError(str(exc)))
                failed += 1
            except InvalidStateError:
                pass  # resolved/cancelled while we swept
        return failed

    def step(self) -> int:
        """One scheduler iteration: admit, allocate, dispatch one engine
        step, retire completions. Returns the number of active sequences
        stepped (0 = nothing to do). Any dispatch exception — including
        an injected crash — fails every accepted sequence typed and then
        propagates: the batcher never silently drops work it accepted."""
        self._admit()
        with self._cond:
            active = list(self._active)
        if not active:
            return 0
        try:
            # page allocation for this step's positions, preempting the
            # newest sequence (possibly the grower itself) until it fits
            i = 0
            while i < len(active):
                seq = active[i]
                try:
                    self.engine.pool.ensure(seq.seq_id, seq.pos + 1)
                    i += 1
                except OutOfPagesError:
                    if not self._preempt_last():
                        raise
                    with self._cond:
                        active = [s for s in active if s in self._active]
                    i = min(i, len(active))
            if not active:
                return 0
            b = self.engine.bucket_for(len(active))
            mp = self.engine.page_bucket_for(max(
                self.engine.pool.num_seq_pages(s.seq_id) for s in active))
            tokens = np.zeros(b, np.int32)
            positions = np.full(b, -1, np.int32)
            table = np.zeros((b, mp), np.int32)
            for i, seq in enumerate(active):
                tokens[i] = seq.tokens[seq.pos]
                positions[i] = seq.pos
                table[i] = self.engine.pool.table(seq.seq_id, mp)
            faults.trip("decode.step", step=self._steps)
            tracer = get_tracer()
            with tracer.span("decode.step", track="decode",
                             active=len(active), bucket=b, pages=mp):
                nxt, _ = self.engine.step(tokens, positions, table)
        except BaseException as e:
            # fail-everything-typed, then propagate (an InjectedCrash is
            # the process dying: the thread/test sees it re-raised, and
            # every accepted future is already resolved — no orphans)
            with self._cond:
                self._closing = True
            self._fail_all(e)
            raise
        self._steps += 1
        now = self._clock()
        done: List[_Seq] = []
        for i, seq in enumerate(active):
            emit = seq.pos == len(seq.tokens) - 1
            seq.pos += 1
            if not emit:
                # prefill (or post-preemption replay): KV written, output
                # already known
                self.metrics.record_prefill()
                continue
            tok = int(nxt[i])
            seq.tokens.append(tok)
            seq.generated.append(tok)
            self.metrics.record_token()
            if not seq.first_emit:
                seq.first_emit = True
                self.metrics.record_ttft(max(now - seq.t_submit, 0.0))
            if (len(seq.generated) >= seq.max_new_tokens
                    or tok == seq.eos_id):
                done.append(seq)
        for seq in done:
            self.engine.pool.release(seq.seq_id)
            with self._cond:
                if seq in self._active:
                    self._active.remove(seq)
                self._accepted.discard(seq.future)
            try:
                seq.future.set_result(np.asarray(seq.generated, np.int32))
            except InvalidStateError:
                pass  # failed by a timed-out drain racing this step
            self.metrics.record_complete()
        self.metrics.record_step(len(active), self.max_slots)
        self.metrics.record_pages(self.engine.pool.pages_in_use)
        return len(active)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while (not self._pending and not self._active
                       and not self._closing):
                    self._cond.wait()
                if self._closing and not self._pending and not self._active:
                    return
            try:
                self.step()
            except BaseException:
                # step() already failed every accepted future typed; a
                # crashed scheduler thread reports through health_reason
                return

    # -- teardown --
    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop intake; decode everything already accepted to completion.
        If ``timeout`` trips, still-pending futures fail with
        :class:`~dcnn_tpu.serve.batcher.ShutdownError` (never orphaned)
        and ``TimeoutError`` raises."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                n = self._fail_all(ShutdownError(
                    f"decode drain timed out after {timeout}s"))
                raise TimeoutError(
                    f"decode drain did not finish in {timeout}s "
                    f"({n} pending sequence(s) failed with ShutdownError)")
            self._thread = None
        else:
            while self.step():
                pass

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """``drain=True``: :meth:`drain`. ``drain=False``: fail every
        accepted, unfinished sequence with
        :class:`~dcnn_tpu.serve.batcher.ShutdownError`."""
        if drain:
            self.drain(timeout)
            return
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self._fail_all(ShutdownError("decode batcher shut down without "
                                     "drain"))

    def __enter__(self) -> "ContinuousBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def __repr__(self) -> str:
        return (f"ContinuousBatcher(engine={self.engine.name!r}, "
                f"max_slots={self.max_slots}, "
                f"capacity={self.queue_capacity})")
