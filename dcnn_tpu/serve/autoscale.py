"""Telemetry-driven autoscaler: the control loop that closes PR 8/9/10.

The router admits/ejects/rejoins replicas live (PR 9), the AOT cache
makes a replica spin-up a ~1 s deserialize instead of a compile wall
(PR 10), and the elastic trainer reshapes to any world size (PR 8) — but
until now nothing *decided* to scale. :class:`Autoscaler` is that
decision loop, deliberately boring where it matters:

- **Signals come off the scrape surface, not private objects.** Each
  tick reads every replica's Prometheus exposition text (in-process via
  ``replica.metrics.prometheus()``, or over HTTP via
  :class:`~dcnn_tpu.obs.fleet.HttpScraper`) through the shared
  :class:`~dcnn_tpu.obs.fleet.FleetAggregator` — the autoscaler's only
  contract with a replica is the same text an external Prometheus reads
  (queue depth, windowed p99, shed fraction, HBM watermark gauges), and
  the aggregator retains the per-replica + sum/max history in its tsdb
  while counting per-target scrape latency/failures. Router-level
  shed/offered counters are read as per-tick deltas so the breach
  verdict tracks *current* traffic, not history.
- **Deterministic and injectable-clock.** :meth:`Autoscaler.tick` is one
  pure decision turn; tests drive the whole diurnal soak sleep-free
  under a fake clock (the ModelVersionManager pattern). Production runs
  :meth:`start`'s daemon poll thread.
- **Hysteresis + cooldowns, not a thermostat on a hair trigger.**
  Scale-up and scale-down trigger on *separate* utilization bands with
  *separate* consecutive-tick requirements and cooldowns, so a fleet
  never oscillates on noise: up is fast (a breach is user-visible), down
  is slow (capacity is cheap compared to a p99 violation).
- **Scale-up fast path**: new replicas come from the injected
  ``factory(version)`` — in production an
  :class:`~dcnn_tpu.serve.swap.EngineFactory`-backed builder whose
  engine construction rides the shared AOT executable cache, so the
  reaction time the soak gates on is dominated by the cooldown budget,
  not XLA. Spin-up wall is recorded per replica
  (``autoscale_spinup_seconds``).
- **Scale-down is drain-then-remove** (:meth:`Router.decommission`) —
  the accepted-ledger no-silent-drop guarantee holds through a shrink,
  and a victim dying mid-drain re-admits its work to survivors.
- **Shared hardware**: when a :class:`DeviceLeaseBroker` is wired in,
  every replica costs a device lease. The serving tenant outranks
  training: a scale-up that finds no free device fires a revocation at
  the training tenant (whose elastic twin —
  :mod:`dcnn_tpu.parallel.autoscale` — shrinks the training world via
  the PR-8 reconfiguration protocol and surrenders the chip); the
  autoscaler simply retries next tick, so the handoff needs no blocking
  rendezvous. Scale-down returns the lease, and training re-grows.

SLO accounting for the soak gates: ``autoscale_slo_violation_seconds_
total`` integrates breach time tick-by-tick, and the first scale-up of
each breach episode records breach-start → capacity-added on
``autoscale_scale_up_reaction_seconds``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

# HttpScraper moved to obs/fleet.py with the monitoring plane; imported
# here so `from dcnn_tpu.serve.autoscale import HttpScraper` keeps
# working (it predates the fleet tier and is the documented name)
from ..obs.fleet import FleetAggregator, HttpScraper  # noqa: F401
from .router import Router


@dataclass
class AutoscalerConfig:
    """SLO targets + hysteresis/cooldown knobs (docs/deployment.md §6).

    The scale-up band must sit strictly above the scale-down band
    (``low_utilization < high_utilization``) — the gap IS the
    hysteresis; a single threshold would flap a fleet whose load sits on
    it."""

    slo_p99_ms: float = 200.0        # windowed p99 above this = breach
    max_shed_fraction: float = 0.0   # any admission shed = breach
    high_utilization: float = 0.80   # mean queue fill that triggers up
    low_utilization: float = 0.30    # mean queue fill that allows down
    min_replicas: int = 1
    max_replicas: int = 8
    up_cooldown_s: float = 5.0       # min gap between scale-ups
    down_cooldown_s: float = 30.0    # min gap between scale-downs
    breach_ticks: int = 1            # consecutive breach ticks before up
    idle_ticks: int = 3              # consecutive idle ticks before down
    step_up: int = 1                 # replicas added per scale-up
    max_hbm_fraction: float = 0.92   # scale-up blocked past this
    drain_timeout_s: float = 30.0    # decommission drain budget
    # scale-down traffic guard: a fleet that is KEEPING UP reads ~0
    # instantaneous queue depth between ticks, so utilization alone
    # would shrink it at steady peak load and pay a breach + re-grow
    # limit cycle every down_cooldown_s. Down is therefore also gated on
    # offered traffic: the projected per-replica rate after the shrink
    # must stay under this fraction of the per-replica rate that forced
    # the last pressure-driven scale-up. 0 disables the guard.
    down_headroom: float = 0.9

    def __post_init__(self):
        if not 0 <= self.low_utilization < self.high_utilization:
            raise ValueError(
                f"need 0 <= low_utilization < high_utilization for a "
                f"hysteresis band, got {self.low_utilization} / "
                f"{self.high_utilization}")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas} / {self.max_replicas}")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.breach_ticks < 1 or self.idle_ticks < 1:
            raise ValueError("breach_ticks / idle_ticks must be >= 1")
        if self.step_up < 1:
            raise ValueError("step_up must be >= 1")


@dataclass
class ReplicaSignals:
    """One replica's scraped view for one tick. ``shed_fraction`` is the
    replica's LIFETIME shed/offered ratio (ServeMetrics semantics) —
    carried for operator visibility via ``FleetSignals.replicas``; the
    breach verdict's shed signal is the router-tier per-tick delta
    (``FleetSignals.shed_fraction``), which tracks current traffic
    instead of pinning breach on history."""

    name: str
    routable: bool
    queue_depth: float = 0.0
    queue_capacity: float = 0.0
    p99_ms: Optional[float] = None
    shed_fraction: float = 0.0
    hbm_fraction: Optional[float] = None


@dataclass
class FleetSignals:
    """The aggregate the decision runs on. ``p99_ms`` is the worst
    routable replica's windowed p99 (a breach on ANY replica is a
    user-visible breach); ``utilization`` is the mean queue fill;
    ``shed_fraction`` is the router-tier *per-tick* shed ratio."""

    replicas: List[ReplicaSignals] = field(default_factory=list)
    routable: int = 0
    utilization: float = 0.0
    p99_ms: Optional[float] = None
    shed_fraction: float = 0.0
    offered: float = 0.0             # requests offered since last tick
    hbm_fraction: Optional[float] = None


def _default_scrape(name: str, replica) -> Optional[str]:
    """In-process scrape: the replica's own ``ServeMetrics`` exposition
    text — the same bytes its HTTP ``/metrics`` serves, so the parse
    path (and therefore the whole signal contract) is identical in tests
    and production."""
    m = getattr(replica, "metrics", None)
    if m is None:
        return None
    try:
        return m.prometheus()
    except Exception:
        return None


class DeviceLeaseBroker:
    """Arbitrates a fixed pool of accelerator devices between tenants
    with strict priority — the shared-hardware contract between the
    serving fleet and the elastic training world.

    Rules (docs/deployment.md §6 "Device leases"):

    - ``register`` each tenant once with a ``priority`` (higher wins;
      serving registers above training) and an optional ``on_revoke``
      callback.
    - :meth:`request` grants only devices that are free *right now* and
      returns the granted count. A shortfall fires ``on_revoke(k)`` at
      lower-priority holders (largest holders first) — **a notification,
      not a seizure**: the holder surrenders by calling :meth:`release`
      when its own protocol allows (the elastic trainer finishes its
      reshape first). The claimant polls ``request`` again; no blocking
      rendezvous, no deadlock.
    - Revocations are edge-triggered per shortfall: a pending revocation
      is remembered so a claimant retrying every tick does not spam the
      holder with duplicate revokes for the same devices. A holder that
      cannot fulfil part of a revocation (e.g. a ``min_hold`` floor)
      must :meth:`decline` that part — otherwise the phantom pending
      count would suppress every future revocation even after the
      holder re-grew and COULD surrender (permanent starvation of the
      higher-priority tenant).
    - All accounting is lock-guarded; callbacks fire OUTSIDE the lock
      (an ``on_revoke`` is free to call back into the broker).
    """

    def __init__(self, devices: int, *,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        if devices < 1:
            raise ValueError(f"devices must be >= 1, got {devices}")
        self.devices = devices
        self._clock = clock
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._reg = registry
        self._lock = threading.Lock()
        self._held: Dict[str, int] = {}        # dcnn: guarded_by=_lock
        self._priority: Dict[str, int] = {}    # dcnn: guarded_by=_lock
        self._on_revoke: Dict[str, Optional[Callable[[int], None]]] = {}  # dcnn: guarded_by=_lock
        self._revoke_pending: Dict[str, int] = {}  # dcnn: guarded_by=_lock
        self._grants = registry.counter(
            "lease_grants_total", "device leases granted")
        self._revocations = registry.counter(
            "lease_revocations_total",
            "devices asked back from lower-priority tenants")
        self._free_gauge = registry.gauge(
            "lease_free_devices", "devices currently unleased")
        self._free_gauge.set(devices)

    def register(self, tenant: str, *, priority: int = 0, held: int = 0,
                 on_revoke: Optional[Callable[[int], None]] = None
                 ) -> None:
        """Add a tenant. ``held`` pre-assigns devices the tenant already
        physically owns at wiring time (the usual bootstrap: training
        starts holding the night fleet)."""
        with self._lock:
            if tenant in self._held:
                raise ValueError(f"tenant {tenant!r} already registered")
            total = sum(self._held.values()) + held
            if held < 0 or total > self.devices:
                raise ValueError(
                    f"cannot pre-assign {held} devices to {tenant!r}: "
                    f"{total} > pool of {self.devices}")
            self._held[tenant] = held
            self._priority[tenant] = priority
            self._on_revoke[tenant] = on_revoke
            self._revoke_pending[tenant] = 0
            self._update_gauges_locked()

    def _update_gauges_locked(self) -> None:
        free = self.devices - sum(self._held.values())
        self._free_gauge.set(free)
        for tenant, n in self._held.items():
            self._reg.gauge(
                f"lease_held_{tenant}",
                f"devices leased to tenant {tenant}").set(n)

    def held(self, tenant: str) -> int:
        with self._lock:
            return self._held.get(tenant, 0)

    def free(self) -> int:
        with self._lock:
            return self.devices - sum(self._held.values())

    def request(self, tenant: str, n: int) -> int:
        """Grant up to ``n`` free devices now; fire revocations at
        lower-priority holders for any shortfall. Returns the granted
        count (0 is a normal answer — retry after the holders
        surrender)."""
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        revoke_calls: List = []
        with self._lock:
            if tenant not in self._held:
                raise KeyError(f"tenant {tenant!r} not registered")
            free = self.devices - sum(self._held.values())
            granted = min(free, n)
            if granted > 0:
                self._held[tenant] += granted
                self._grants.inc(granted)
                self._update_gauges_locked()
            shortfall = n - granted
            if shortfall > 0:
                my_pri = self._priority[tenant]
                # devices already asked back count against the shortfall —
                # a claimant retrying every tick must not spam duplicate
                # revokes for the same devices (edge-triggered contract)
                already_pending = sum(
                    p for t, p in self._revoke_pending.items()
                    if self._priority[t] < my_pri and t != tenant)
                shortfall -= already_pending
                holders = sorted(
                    ((t, h) for t, h in self._held.items()
                     if self._priority[t] < my_pri and t != tenant),
                    key=lambda th: (-th[1], self._priority[th[0]]))
                for t, h in holders:
                    if shortfall <= 0:
                        break
                    revocable = h - self._revoke_pending[t]
                    k = min(max(revocable, 0), shortfall)
                    if k <= 0:
                        continue
                    self._revoke_pending[t] += k
                    shortfall -= k
                    self._revocations.inc(k)
                    cb = self._on_revoke[t]
                    if cb is not None:
                        revoke_calls.append((cb, k))
        for cb, k in revoke_calls:
            cb(k)
        return granted

    def release(self, tenant: str, n: int) -> None:
        """Hand ``n`` held devices back to the pool (a surrender after a
        revocation, or a voluntary scale-down)."""
        with self._lock:
            if tenant not in self._held:
                raise KeyError(f"tenant {tenant!r} not registered")
            if n < 1 or n > self._held[tenant]:
                raise ValueError(
                    f"tenant {tenant!r} cannot release {n} of "
                    f"{self._held[tenant]} held device(s)")
            self._held[tenant] -= n
            self._revoke_pending[tenant] = max(
                self._revoke_pending[tenant] - n, 0)
            self._update_gauges_locked()

    def decline(self, tenant: str, n: int) -> None:
        """Refuse ``n`` devices of a pending revocation without
        releasing them (the holder's own floor forbids surrendering).
        The claimant's next :meth:`request` re-fires a revocation for
        the shortfall, so a holder that later re-grows past its floor
        is asked again instead of being shadowed by stale pending."""
        if n < 1:
            return
        with self._lock:
            if tenant not in self._held:
                raise KeyError(f"tenant {tenant!r} not registered")
            self._revoke_pending[tenant] = max(
                self._revoke_pending[tenant] - n, 0)

    def revoke_pending(self, tenant: str) -> int:
        """Devices this tenant has been asked to surrender and has not
        yet released — the elastic twin polls this to size its shrink."""
        with self._lock:
            return self._revoke_pending.get(tenant, 0)

    def __repr__(self) -> str:
        with self._lock:
            held = dict(self._held)
            free = self.devices - sum(held.values())
        return f"DeviceLeaseBroker(free={free}, held={held})"


class Autoscaler:
    """The serving-fleet control loop over a :class:`Router`.

    ``factory(version) -> replica`` builds one new replica ready for
    ``Router.add_replica`` (the AOT-warmed spin-up path); the autoscaler
    owns the replicas it builds (closes them after decommission) and
    ONLY those — the bootstrap fleet stays the caller's. ``version_fn``
    overrides which version new replicas load (default: the modal
    version among routable replicas, so a mid-canary scale-up joins the
    stable set, not the canary)."""

    def __init__(self, router: Router, factory: Callable[[Any], Any], *,
                 config: Optional[AutoscalerConfig] = None,
                 broker: Optional[DeviceLeaseBroker] = None,
                 tenant: str = "serve",
                 version_fn: Optional[Callable[[], Any]] = None,
                 scrape: Callable[[str, Any], Optional[str]]
                 = _default_scrape,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "autoscaler", flight=None,
                 aggregator: Optional[FleetAggregator] = None):
        self.router = router
        self.factory = factory
        self.cfg = config if config is not None else AutoscalerConfig()
        self.broker = broker
        self.tenant = tenant
        self.version_fn = version_fn
        self.scrape = scrape
        self.name = name
        self._flight = flight  # None: process-global flight recorder
        self._clock = clock
        self._reg = registry if registry is not None \
            else router.metrics.registry
        # the ONE scrape surface (obs/fleet.py): every tick's replica
        # expositions flow through the aggregator, which parses them,
        # retains per-replica + sum/max fleet history in its tsdb, and
        # counts per-target scrape latency/failures — the autoscaler
        # keeps only the DECISION state (deltas, hysteresis runs)
        self.aggregator = aggregator if aggregator is not None \
            else FleetAggregator(registry=self._reg, clock=clock)
        self._lock = threading.Lock()
        self._owned: Dict[str, Any] = {}      # dcnn: guarded_by=_lock
        self._spawned = 0                     # dcnn: guarded_by=_lock
        self._breach_run = 0                  # dcnn: guarded_by=_lock
        self._idle_run = 0                    # dcnn: guarded_by=_lock
        self._breach_since: Optional[float] = None  # dcnn: guarded_by=_lock
        self._breach_reacted = False          # dcnn: guarded_by=_lock
        self._slo_breached = False            # dcnn: guarded_by=_lock
        self._last_up: Optional[float] = None  # dcnn: guarded_by=_lock
        self._last_down: Optional[float] = None  # dcnn: guarded_by=_lock
        self._last_tick: Optional[float] = None  # dcnn: guarded_by=_lock
        # baseline the per-tick shed delta on the router's CURRENT
        # counters — attached to a long-lived router, tick 1 must not
        # read the entire shed history as one tick's shed fraction
        totals = router.metrics.snapshot()["total"]
        self._last_counts = {"requests": totals["requests"],
                             "shed": totals["shed"]}  # dcnn: guarded_by=_lock
        self._last_error: Optional[str] = None  # dcnn: guarded_by=_lock
        self._blocked_reason: Optional[str] = None  # dcnn: guarded_by=_lock
        self._scrape_error: Optional[str] = None  # dcnn: guarded_by=_lock
        # per-replica offered rps at the last pressure-driven scale-up —
        # the demand watermark the down_headroom guard projects against
        self._up_rate: Optional[float] = None  # dcnn: guarded_by=_lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        r = self._reg
        self._ticks = r.counter("autoscale_ticks_total",
                                "autoscaler decision turns")
        self._ups = r.counter("autoscale_scale_ups_total",
                              "scale-up actions taken")
        self._downs = r.counter("autoscale_scale_downs_total",
                                "scale-down (decommission) actions taken")
        self._up_failures = r.counter(
            "autoscale_scale_up_failures_total",
            "replica factory/spin-up failures during scale-up")
        self._lease_blocked = r.counter(
            "autoscale_lease_blocked_total",
            "scale-up ticks blocked waiting on a device lease")
        self._scrape_failures = r.counter(
            "autoscale_scrape_parse_failures_total",
            "replica /metrics bodies that failed to parse")
        self._hbm_blocked = r.counter(
            "autoscale_hbm_blocked_total",
            "scale-up ticks refused at the HBM watermark guard")
        self._slo_violation_s = r.counter(
            "autoscale_slo_violation_seconds_total",
            "integrated wall seconds spent in SLO breach")
        self._spinup_hist = r.histogram(
            "autoscale_spinup_seconds",
            "replica factory + fleet-join wall per scale-up replica")
        self._reaction_hist = r.histogram(
            "autoscale_scale_up_reaction_seconds",
            "breach start to first capacity added, per breach episode")
        self._breach_gauge = r.gauge(
            "autoscale_breach", "1 while the fleet is in SLO breach")
        self._target_gauge = r.gauge(
            "autoscale_replicas_target",
            "fleet size the autoscaler is steering toward")
        self._reaction_gauge = r.gauge(
            "autoscale_last_scale_up_reaction_s",
            "most recent breach-to-scale-up reaction")
        self._devices_gauge = r.gauge(
            "autoscale_devices_held",
            "device leases held by the serving tenant")
        self._target_gauge.set(len(router.replica_names()))

    # -- signals -----------------------------------------------------------
    def collect(self, *, _commit: bool = False) -> FleetSignals:
        """One scrape pass THROUGH the aggregator: per-replica exposition
        text → parsed signals + the router's per-tick shed delta. The
        aggregator does the scraping/parsing/history bookkeeping
        (obs/fleet.py); this method reduces its results to the decision
        signals. Public calls are READ-ONLY on decision state: only the
        decision loop commits the counter baseline (``_commit``) — an
        operator dashboard polling ``collect()`` between ticks must not
        consume the shed delta and blind the next tick's breach
        verdict."""
        stats = self.router.replica_stats()
        fleet = FleetSignals()
        fills: List[float] = []
        hbms: List[float] = []
        handles = self.router.replicas()
        parse_errors: List[str] = []
        scraped = self.aggregator.poll(targets={
            rname: (lambda rn=rname: self.scrape(rn, handles.get(rn)))
            for rname in stats})
        for rname, st in stats.items():
            sig = ReplicaSignals(name=rname,
                                 routable=st["state"] == "up")
            res = scraped.get(rname, {})
            if res.get("parse_error"):
                # a half-parsed scrape must not feed the decision — but
                # it must not be INVISIBLE either: the replica scores
                # signal-less (a latency-only breach there goes dark),
                # so count it and degrade /healthz via autoscale_check
                # until a tick parses clean
                parse_errors.append(f"{rname}: {res['parse_error']}")
                if _commit:
                    self._scrape_failures.inc()
            vals = res.get("values")
            if res.get("fetched"):
                vals = vals if vals is not None else {}
                sig.queue_depth = float(vals.get("serve_queue_depth", 0.0))
                sig.p99_ms = vals.get("serve_latency_window_p99_ms")
                sig.shed_fraction = float(
                    vals.get("serve_shed_fraction", 0.0))
                limit = vals.get("hbm_bytes_limit")
                used = vals.get("hbm_bytes_in_use")
                if limit and used is not None:
                    sig.hbm_fraction = float(used) / float(limit)
            cap = getattr(handles.get(rname), "queue_capacity", 0)
            sig.queue_capacity = float(cap or 0)
            fleet.replicas.append(sig)
            if sig.routable:
                fleet.routable += 1
                # router-side outstanding covers rows in flight even when
                # a replica exposes no scrape text
                depth = max(sig.queue_depth, float(st["outstanding"]))
                if sig.queue_capacity > 0:
                    fills.append(depth / sig.queue_capacity)
                if sig.p99_ms is not None:
                    fleet.p99_ms = (sig.p99_ms if fleet.p99_ms is None
                                    else max(fleet.p99_ms, sig.p99_ms))
                if sig.hbm_fraction is not None:
                    hbms.append(sig.hbm_fraction)
        fleet.utilization = (sum(fills) / len(fills)) if fills else 0.0
        fleet.hbm_fraction = (sum(hbms) / len(hbms)) if hbms else None
        totals = self.router.metrics.snapshot()["total"]
        with self._lock:
            if _commit:
                # like the counter baseline, scrape health is DECISION
                # state: a dashboard poll must neither clear a tick's
                # degradation nor degrade /healthz over a blip no tick saw
                self._scrape_error = (parse_errors[-1] if parse_errors
                                      else None)
            d_req = totals["requests"] - self._last_counts["requests"]
            d_shed = totals["shed"] - self._last_counts["shed"]
            if _commit:
                self._last_counts = {"requests": totals["requests"],
                                     "shed": totals["shed"]}
        offered = d_req + d_shed
        fleet.offered = float(offered)
        fleet.shed_fraction = (d_shed / offered) if offered > 0 else 0.0
        return fleet

    def _pick_version(self) -> Any:
        if self.version_fn is not None:
            return self.version_fn()
        counts: Dict[Any, int] = {}
        for st in self.router.replica_stats().values():
            if st["state"] == "up" and not st["canary"] \
                    and st["version"] is not None:
                counts[st["version"]] = counts.get(st["version"], 0) + 1
        if not counts:
            return None
        return max(counts.items(), key=lambda kv: kv[1])[0]

    # -- the decision turn -------------------------------------------------
    def tick(self) -> Dict[str, Any]:
        """One control-loop turn: scrape → classify → (maybe) act.
        Returns ``{"action": "up" | "down" | "hold" | "blocked",
        ...}``. Never raises — a broken turn is recorded and surfaces
        via :func:`autoscale_check`."""
        with self._lock:
            # this turn's verdict replaces the last one: a clean turn
            # clears a prior error/block so a transient failure (or an
            # HBM/lease block whose scale-up demand has since passed)
            # cannot pin /healthz degraded for the process lifetime
            self._last_error = None
            self._blocked_reason = None
        try:
            return self._tick_inner()
        except Exception as e:
            msg = f"{type(e).__name__}: {e}"
            with self._lock:
                self._last_error = msg
            return {"action": "error", "error": msg}

    def _tick_inner(self) -> Dict[str, Any]:
        self._ticks.inc()
        now = self._clock()
        self.router.check_replicas()
        self._reap_dead_owned()
        fleet = self.collect(_commit=True)
        cfg = self.cfg
        breach_p99 = (fleet.p99_ms is not None
                      and fleet.p99_ms > cfg.slo_p99_ms)
        breach_shed = fleet.shed_fraction > cfg.max_shed_fraction
        breach_none = fleet.routable < cfg.min_replicas
        hot = fleet.utilization > cfg.high_utilization
        breach = breach_p99 or breach_shed or breach_none
        # "pressure" (breach OR running hot) drives scale-up; only a true
        # SLO breach accrues violation seconds — pre-emptive growth on
        # utilization is the loop doing its job BEFORE users notice
        pressure = breach or hot
        idle = (not pressure
                and fleet.utilization < cfg.low_utilization
                and fleet.shed_fraction == 0.0)
        with self._lock:
            dt = (now - self._last_tick) if self._last_tick is not None \
                else 0.0
            self._last_tick = now
            if pressure:
                if self._breach_since is None:
                    self._breach_since = now
                    self._breach_reacted = False
                self._breach_run += 1
                self._idle_run = 0
            else:
                self._breach_since = None
                self._breach_run = 0
                self._idle_run = self._idle_run + 1 if idle else 0
            breach_run, idle_run = self._breach_run, self._idle_run
            last_up, last_down = self._last_up, self._last_down
        if breach and dt > 0:
            self._slo_violation_s.inc(dt)
        self._breach_gauge.set(1 if breach else 0)
        # flight recorder at the SLO-breach EDGE (first breaching tick of
        # an episode — `breach`, not `pressure`: pre-emptive growth on
        # utilization is the loop working, not a violation). record()
        # never raises, honoring tick()'s never-raise contract.
        with self._lock:
            breach_edge = breach and not self._slo_breached
            self._slo_breached = breach
        if breach_edge:
            from ..obs.flight import resolve_flight_recorder
            resolve_flight_recorder(self._flight).record(
                "autoscale_slo_breach",
                reasons=[r for r, hit in (
                    (f"p99 {fleet.p99_ms}ms > slo {cfg.slo_p99_ms}ms",
                     breach_p99),
                    (f"shed fraction {fleet.shed_fraction:.4f} > "
                     f"{cfg.max_shed_fraction:g}", breach_shed),
                    (f"routable {fleet.routable} < min_replicas "
                     f"{cfg.min_replicas}", breach_none)) if hit],
                registry=self._reg,
                config={"slo_p99_ms": cfg.slo_p99_ms,
                        "max_shed_fraction": cfg.max_shed_fraction,
                        "min_replicas": cfg.min_replicas,
                        "max_replicas": cfg.max_replicas},
                extra={"routable": fleet.routable,
                       "p99_ms": fleet.p99_ms,
                       "shed_fraction": fleet.shed_fraction,
                       "utilization": fleet.utilization})
        out: Dict[str, Any] = {
            "routable": fleet.routable,
            "utilization": round(fleet.utilization, 4),
            "p99_ms": fleet.p99_ms,
            "shed_fraction": round(fleet.shed_fraction, 4),
            "breach": breach,
        }
        want_up = pressure and breach_run >= cfg.breach_ticks
        # a fleet below min_replicas is always grown, cooldown or not —
        # that is availability repair, not load-tracking
        repair = fleet.routable < cfg.min_replicas
        if repair:
            want_up = True
        if want_up:
            if fleet.routable >= cfg.max_replicas:
                out.update(action="blocked", reason="at max_replicas")
                return out
            if (fleet.routable >= cfg.min_replicas
                    and last_up is not None
                    and now - last_up < cfg.up_cooldown_s):
                out.update(action="hold", reason="up cooldown")
                return out
            if fleet.hbm_fraction is not None \
                    and fleet.hbm_fraction > cfg.max_hbm_fraction:
                self._hbm_blocked.inc()
                self._set_blocked(f"hbm watermark "
                                  f"{fleet.hbm_fraction:.2f} > "
                                  f"{cfg.max_hbm_fraction:g}")
                out.update(action="blocked", reason="hbm watermark")
                return out
            return self._scale_up(fleet, now, out,
                                  rate_now=(fleet.offered / dt)
                                  if (dt > 0 and not repair) else None)
        if idle and idle_run >= cfg.idle_ticks \
                and fleet.routable > cfg.min_replicas:
            if last_down is not None \
                    and now - last_down < cfg.down_cooldown_s:
                out.update(action="hold", reason="down cooldown")
                return out
            # traffic guard: instantaneous queues read ~0 on a fleet
            # that is keeping up — project the post-shrink per-replica
            # offered rate against the demand watermark instead of
            # decommissioning at steady peak and paying a breach +
            # re-grow limit cycle every down_cooldown_s
            rate_now = (fleet.offered / dt) if dt > 0 else None
            with self._lock:
                up_rate = self._up_rate
            if (cfg.down_headroom > 0 and up_rate is not None
                    and rate_now is not None and fleet.routable > 1
                    and rate_now / (fleet.routable - 1)
                    > up_rate * cfg.down_headroom):
                out.update(action="hold", reason="traffic needs fleet")
                return out
            return self._scale_down(fleet, now, out)
        out.update(action="hold")
        return out

    def _set_blocked(self, reason: Optional[str]) -> None:
        with self._lock:
            self._blocked_reason = reason

    def _release_lease(self, n: int = 1) -> None:
        if self.broker is None:
            return
        try:
            self.broker.release(self.tenant, n)
        except ValueError as e:
            # mis-wired lease bootstrap (serve registered without
            # held=<bootstrap fleet size> — docs/deployment.md §6): the
            # fleet change already happened, so surface the accounting
            # error without failing the turn
            with self._lock:
                self._last_error = f"lease release failed: {e}"
        self._devices_gauge.set(self.broker.held(self.tenant))

    def _reap_dead_owned(self) -> None:
        """Reclaim owned replicas that died (preemption, crash) and that
        the sweep could not revive: drop them from the fleet map, close
        them, and return their device leases. Without this, a dead owned
        replica is unreachable forever — ``_scale_down`` only ever
        considers routable victims and nobody restarts an
        autoscaler-owned replica — so its lease and dispatcher/HBM would
        leak until the pool starved every future scale-up."""
        stats = self.router.replica_stats()
        with self._lock:
            owned = list(self._owned)
        for rname in owned:
            st = stats.get(rname)
            if st is not None and st["state"] != "dead":
                continue
            if st is not None:
                # death detection already swept + re-admitted its ledger
                self.router.remove_replica(rname)
            with self._lock:
                replica = self._owned.pop(rname, None)
            if replica is not None:
                try:
                    replica.close()
                except Exception:
                    pass
            self._release_lease()

    def _scale_up(self, fleet: FleetSignals, now: float,
                  out: Dict[str, Any], *,
                  rate_now: Optional[float] = None) -> Dict[str, Any]:
        cfg = self.cfg
        need = min(cfg.step_up, cfg.max_replicas - fleet.routable)
        # resolve the version BEFORE taking leases: a raising version_fn
        # must not strand granted devices behind tick()'s catch-all
        version = self._pick_version()
        if self.broker is not None:
            granted = self.broker.request(self.tenant, need)
            self._devices_gauge.set(self.broker.held(self.tenant))
            if granted == 0:
                self._lease_blocked.inc()
                self._set_blocked(
                    "scale-up waiting on a device lease (revocation "
                    "sent to lower-priority tenants)")
                out.update(action="blocked", reason="awaiting lease")
                return out
            need = granted
        added: List[str] = []
        for _ in range(need):
            t0 = self._clock()
            replica = None
            try:
                replica = self.factory(version)
                rname = self.router.add_replica(replica)
            except Exception as e:
                self._up_failures.inc()
                if replica is not None:
                    # built but never joined the fleet: nobody else owns
                    # it, so close it here or leak its dispatcher/HBM
                    try:
                        replica.close()
                    except Exception:
                        pass
                self._release_lease()
                with self._lock:
                    self._last_error = (f"scale-up factory failed: "
                                        f"{type(e).__name__}: {e}")
                continue
            self._spinup_hist.observe(self._clock() - t0)
            with self._lock:
                self._owned[rname] = replica
                self._spawned += 1
            added.append(rname)
        if added:
            self._ups.inc()
            with self._lock:
                self._last_up = now
                if rate_now is not None and rate_now > 0:
                    # the demand a one-smaller fleet could not carry,
                    # per replica of the fleet sized to carry it —
                    # repair scale-ups (rate_now=None) never lower it
                    self._up_rate = rate_now / (fleet.routable
                                                + len(added))
                since, reacted = self._breach_since, self._breach_reacted
                if since is not None and not reacted:
                    self._breach_reacted = True
            if since is not None and not reacted:
                reaction = now - since
                self._reaction_hist.observe(reaction)
                self._reaction_gauge.set(reaction)
            self._target_gauge.set(fleet.routable + len(added))
            out.update(action="up", added=added, version=version)
        else:
            out.update(action="blocked", reason="factory failures")
        return out

    def _scale_down(self, fleet: FleetSignals, now: float,
                    out: Dict[str, Any]) -> Dict[str, Any]:
        stats = self.router.replica_stats()
        # victim: least-loaded routable non-canary (a canary is the
        # version manager's experiment — never the autoscaler's victim);
        # prefer replicas this autoscaler spawned so the bootstrap fleet
        # survives a quiet night
        with self._lock:
            owned = set(self._owned)
        candidates = [(n, st) for n, st in stats.items()
                      if st["state"] == "up" and not st["canary"]]
        if not candidates:
            out.update(action="hold", reason="no eligible victim")
            return out
        candidates.sort(key=lambda kv: (kv[0] not in owned,
                                        kv[1]["outstanding"]))
        victim = candidates[0][0]
        report = self.router.decommission(
            victim, timeout=self.cfg.drain_timeout_s)
        with self._lock:
            replica = self._owned.pop(victim, None)
            self._last_down = now
            self._idle_run = 0
        if replica is not None:
            try:
                replica.close()
            except Exception:
                pass
        self._release_lease()
        self._downs.inc()
        self._target_gauge.set(max(fleet.routable - 1,
                                   self.cfg.min_replicas))
        out.update(action="down", removed=victim, drain=report)
        return out

    # -- introspection / health --------------------------------------------
    @property
    def last_error(self) -> Optional[str]:
        with self._lock:
            return self._last_error

    @property
    def blocked_reason(self) -> Optional[str]:
        with self._lock:
            return self._blocked_reason

    @property
    def scrape_error(self) -> Optional[str]:
        """The most recent tick's replica ``/metrics`` parse failure, or
        ``None`` when every scraped body parsed clean."""
        with self._lock:
            return self._scrape_error

    def owned_replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._owned)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "owned": sorted(self._owned),
                "spawned_total": self._spawned,
                "breach_run": self._breach_run,
                "idle_run": self._idle_run,
                "blocked": self._blocked_reason,
                "last_error": self._last_error,
                "scrape_error": self._scrape_error,
                "tsdb": self.aggregator.store.summary(),
            }

    # -- background polling (production convenience) -----------------------
    def start(self, interval_s: float = 2.0) -> "Autoscaler":
        """Tick on a daemon thread every ``interval_s``; idempotent.
        Tests never call this — they drive :meth:`tick` by hand under a
        fake clock."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval_s,), daemon=True,
            name=f"dcnn-{self.name}")
        self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.tick()  # tick() never raises — errors land on last_error

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "Autoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def __repr__(self) -> str:
        with self._lock:
            owned, blocked = len(self._owned), self._blocked_reason
        return (f"Autoscaler({self.name!r}, owned={owned}, "
                f"blocked={blocked!r})")


def autoscale_check(scaler: Autoscaler) -> Callable[[], Optional[str]]:
    """Health check over an :class:`Autoscaler` for a
    :class:`~dcnn_tpu.obs.server.TelemetryServer`: degraded while the
    last decision turn errored, or while a needed scale-up is pinned
    (lease/HBM blocked during a breach) — the operator should know the
    fleet cannot grow BEFORE the SLO graph says it mattered."""
    def _check() -> Optional[str]:
        err = scaler.last_error
        if err is not None:
            return f"autoscaler turn failed: {err}"
        blocked = scaler.blocked_reason
        if blocked is not None:
            return f"scale-up blocked: {blocked}"
        scrape = scaler.scrape_error
        if scrape is not None:
            return f"replica scrape unparseable: {scrape}"
        return None
    return _check
