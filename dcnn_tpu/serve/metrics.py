"""Serving metrics: rolling latency percentiles, queue depth, batch
occupancy, throughput, shed accounting.

The one rule that shapes this module: every timestamp comes from an
**injectable clock** (``clock=``, default ``time.monotonic``). The serve
test suite passes a fake clock and advances it by hand, so latency
assertions are exact equalities and tier-1 runs sleep-free; the live
batcher and the bench pass nothing and get wall time. (Same motive as the
reference's ``Matrix`` profiling maps being plain data — measurement that
can be driven deterministically is measurement that can be tested.)

All recorders are thread-safe (the batcher's dispatcher thread and many
submitter threads hit them concurrently) and O(1); ``snapshot()`` does the
O(window log window) percentile sort, once, on the caller's thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional


class ServeMetrics:
    """Rolling serving statistics exported as a plain dict.

    ``window`` bounds the latency/occupancy deques — percentiles describe
    the last ``window`` completed requests, not all of history, so a load
    spike ages out instead of polluting the p99 forever. Counters
    (submitted / completed / shed) are cumulative since construction or
    :meth:`reset`.
    """

    def __init__(self, *, window: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._clock = clock
        self._window = window
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Zero every counter and restart the throughput wall-clock."""
        with self._lock:
            self._lat_s: deque = deque(maxlen=self._window)
            self._occ: deque = deque(maxlen=self._window)
            self._submitted = 0
            self._completed = 0
            self._shed = 0
            self._batches = 0
            self._queue_depth = 0
            self._t0 = self._clock()

    # -- recorders (all O(1), thread-safe) --
    def record_submit(self, n: int = 1) -> None:
        """A request of ``n`` samples was accepted into the queue."""
        with self._lock:
            self._submitted += n

    def record_shed(self, n: int = 1) -> None:
        """A request of ``n`` samples was rejected by backpressure."""
        with self._lock:
            self._shed += n

    def record_queue_depth(self, depth: int) -> None:
        """Gauge: samples currently queued (set on enqueue and dispatch)."""
        with self._lock:
            self._queue_depth = depth

    def record_batch(self, size: int, bucket: int) -> None:
        """A batch of ``size`` real samples ran in a ``bucket``-sized
        session; occupancy = size/bucket (the padding waste indicator)."""
        with self._lock:
            self._batches += 1
            self._occ.append(size / max(bucket, 1))

    def record_done(self, latency_s: float, n: int = 1) -> None:
        """A request of ``n`` samples completed ``latency_s`` after it was
        submitted (queue wait + batching delay + compute)."""
        with self._lock:
            self._completed += n
            self._lat_s.append(latency_s)

    # -- export --
    def snapshot(self) -> Dict[str, Optional[float]]:
        """Point-in-time view. Latency keys are ``None`` until the first
        completion so a consumer can't mistake 'no data' for 'zero ms'."""
        with self._lock:
            lat = sorted(self._lat_s)
            occ = list(self._occ)
            submitted, completed = self._submitted, self._completed
            shed, batches = self._shed, self._batches
            depth = self._queue_depth
            wall_s = max(self._clock() - self._t0, 0.0)

        def pct(q: float) -> Optional[float]:
            if not lat:
                return None
            # nearest-rank on the sorted window; exact for the fake-clock
            # tests, standard for live traffic
            i = min(int(q * (len(lat) - 1) + 0.5), len(lat) - 1)
            return lat[i] * 1e3

        offered = submitted + shed
        return {
            "requests_submitted": submitted,
            "requests_completed": completed,
            "requests_shed": shed,
            "shed_fraction": (shed / offered) if offered else 0.0,
            "queue_depth": depth,
            "batches": batches,
            "batch_occupancy": (sum(occ) / len(occ)) if occ else None,
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
            "p99_ms": pct(0.99),
            "mean_ms": (sum(lat) / len(lat) * 1e3) if lat else None,
            "throughput_rps": (completed / wall_s) if wall_s > 0 else None,
            "wall_s": wall_s,
        }

    def __repr__(self) -> str:
        s = self.snapshot()
        return (f"ServeMetrics(completed={s['requests_completed']}, "
                f"shed={s['requests_shed']}, p99_ms={s['p99_ms']})")
