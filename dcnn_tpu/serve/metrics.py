"""Serving metrics: rolling latency percentiles, queue depth, batch
occupancy, throughput, shed accounting.

The one rule that shapes this module: every timestamp comes from an
**injectable clock** (``clock=``, default ``time.monotonic``). The serve
test suite passes a fake clock and advances it by hand, so latency
assertions are exact equalities and tier-1 runs sleep-free; the live
batcher and the bench pass nothing and get wall time. (Same motive as the
reference's ``Matrix`` profiling maps being plain data — measurement that
can be driven deterministically is measurement that can be tested.)

Since the ``dcnn_tpu.obs`` subsystem landed, every recorder ALSO feeds a
:class:`~dcnn_tpu.obs.registry.MetricsRegistry` (counters / queue-depth
gauge / log-bucketed latency histogram) — by default a **private
per-instance one**; pass ``registry=`` to pool instruments into a shared
registry (e.g. ``obs.get_registry()``) when one scrape endpoint should
cover the process. Constructing on a shared registry never resets the
shared instruments (a second batcher must not zero the first's
cumulative counters — Prometheus counters may never go backwards);
:meth:`reset` does reset them, explicitly. :meth:`prometheus` exports
the text exposition either way, with the exact windowed percentiles
appended as gauges.

The :meth:`snapshot` source of truth stays the pre-obs internal state —
plain fields and the exact-percentile deques under ONE lock — so it
remains a consistent point-in-time view (and nearest-rank percentiles
stay exact under the fake clock, which a fixed-bucket histogram cannot
provide). The registry instruments are the scrape-side mirror of the
same stream, self-consistent for ``rate()`` but not atomically coupled
to a given ``snapshot()``.

All recorders are thread-safe (the batcher's dispatcher thread and many
submitter threads hit them concurrently) and O(1); ``snapshot()`` does the
O(window log window) percentile sort, once, on the caller's thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..obs.registry import MetricsRegistry

#: Dispatch-slot goodput states: a replica's dispatcher is either running
#: a batch (occupied), waiting for work (idle), or refusing new work on
#: the way down (draining). Time-weighted via ``record_slot_state``.
SLOT_STATES = ("idle", "occupied", "draining")


class ServeMetrics:
    """Rolling serving statistics exported as a plain dict.

    ``window`` bounds the latency/occupancy deques — percentiles describe
    the last ``window`` completed requests, not all of history, so a load
    spike ages out instead of polluting the p99 forever. Counters
    (submitted / completed / shed) are cumulative since construction or
    :meth:`reset`.
    """

    def __init__(self, *, window: int = 4096,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._clock = clock
        self._window = window
        self._lock = threading.Lock()
        self.registry = (registry if registry is not None
                         else MetricsRegistry(clock=clock))
        self._submitted = self.registry.counter(
            "serve_samples_submitted_total",
            "samples accepted into the request queue")
        self._completed = self.registry.counter(
            "serve_samples_completed_total", "samples served")
        self._shed = self.registry.counter(
            "serve_samples_shed_total", "samples rejected by backpressure")
        self._batches = self.registry.counter(
            "serve_batches_total", "dispatched batches")
        self._queue_depth = self.registry.gauge(
            "serve_queue_depth", "samples currently queued")
        self._lat_hist = self.registry.histogram(
            "serve_latency_seconds", "request latency (submit to complete)")
        self._slot_counters = {
            state: self.registry.counter(
                f"serve_slot_{state}_seconds_total",
                f"cumulative seconds the dispatch slot spent {state}")
            for state in SLOT_STATES}
        # initialize the per-instance state WITHOUT touching the registry
        # instruments: on an injected shared registry they may belong to a
        # live sibling instance, and a counter must never go backwards
        # because someone constructed a second batcher
        self._init_local()

    def _init_local(self) -> None:
        with self._lock:
            self._lat_s: deque = deque(maxlen=self._window)
            self._occ: deque = deque(maxlen=self._window)
            self._submitted_n = 0
            self._completed_n = 0
            self._shed_n = 0
            self._batches_n = 0
            self._depth_n = 0
            self._slot_state: Optional[str] = None
            self._slot_t = 0.0
            self._slot_s = {state: 0.0 for state in SLOT_STATES}
            self._t0 = self._clock()

    def reset(self) -> None:
        """Zero every counter and restart the throughput wall-clock. Also
        resets this instance's registry instruments — on an injected
        shared registry that zeroes the shared series (an explicit caller
        decision here, never an accident of construction)."""
        self._init_local()
        for inst in (self._submitted, self._completed, self._shed,
                     self._batches, self._queue_depth, self._lat_hist,
                     *self._slot_counters.values()):
            inst.reset()

    # -- recorders (all O(1), thread-safe) --
    def record_submit(self, n: int = 1) -> None:
        """A request of ``n`` samples was accepted into the queue."""
        with self._lock:
            self._submitted_n += n
        self._submitted.inc(n)

    def record_shed(self, n: int = 1) -> None:
        """A request of ``n`` samples was rejected by backpressure."""
        with self._lock:
            self._shed_n += n
        self._shed.inc(n)

    def record_queue_depth(self, depth: int) -> None:
        """Gauge: samples currently queued (set on enqueue and dispatch)."""
        with self._lock:
            self._depth_n = depth
        self._queue_depth.set(depth)

    def record_batch(self, size: int, bucket: int) -> None:
        """A batch of ``size`` real samples ran in a ``bucket``-sized
        session; occupancy = size/bucket (the padding waste indicator)."""
        with self._lock:
            self._batches_n += 1
            self._occ.append(size / max(bucket, 1))
        self._batches.inc()

    def record_done(self, latency_s: float, n: int = 1) -> None:
        """A request of ``n`` samples completed ``latency_s`` after it was
        submitted (queue wait + batching delay + compute)."""
        with self._lock:
            self._completed_n += n
            self._lat_s.append(latency_s)
        self._completed.inc(n)
        self._lat_hist.observe(latency_s)

    def record_slot_state(self, state: str) -> None:
        """The dispatch slot entered ``state`` (one of
        :data:`SLOT_STATES`). Time-weighted: the interval since the
        previous transition is credited to the previous state, locally
        and on the ``serve_slot_<state>_seconds_total`` counters — the
        per-replica goodput decomposition ``obs/fleet.py`` aggregates."""
        if state not in SLOT_STATES:
            raise ValueError(f"slot state must be one of {SLOT_STATES}, "
                             f"got {state!r}")
        now = self._clock()
        prev: Optional[str] = None
        dt = 0.0
        with self._lock:
            if self._slot_state is not None:
                prev = self._slot_state
                dt = max(now - self._slot_t, 0.0)
                self._slot_s[prev] += dt
            self._slot_state = state
            self._slot_t = now
        if prev is not None and dt > 0:
            self._slot_counters[prev].inc(dt)

    # -- export --
    def snapshot(self) -> Dict[str, Optional[float]]:
        """Point-in-time view (every field read under ONE lock — e.g.
        ``requests_completed`` always agrees with the latency window).
        Latency keys are ``None`` until the first completion so a consumer
        can't mistake 'no data' for 'zero ms'."""
        with self._lock:
            now = self._clock()
            lat = sorted(self._lat_s)
            occ = list(self._occ)
            submitted, completed = self._submitted_n, self._completed_n
            shed, batches = self._shed_n, self._batches_n
            depth = self._depth_n
            wall_s = max(now - self._t0, 0.0)
            slot = dict(self._slot_s)
            slot_state = self._slot_state
            if slot_state is not None:
                # credit the open interval so the decomposition always
                # sums to the time since the first transition
                slot[slot_state] += max(now - self._slot_t, 0.0)

        def pct(q: float) -> Optional[float]:
            if not lat:
                return None
            # nearest-rank on the sorted window; exact for the fake-clock
            # tests, standard for live traffic
            i = min(int(q * (len(lat) - 1) + 0.5), len(lat) - 1)
            return lat[i] * 1e3

        offered = submitted + shed
        slot_total = sum(slot.values())
        return {
            "slot_state": slot_state,
            "slot_seconds": slot,
            # None until the first transition: no data is not 100% idle
            "slot_goodput": (slot["occupied"] / slot_total)
            if slot_total > 0 else None,
            "requests_submitted": submitted,
            "requests_completed": completed,
            "requests_shed": shed,
            "shed_fraction": (shed / offered) if offered else 0.0,
            "queue_depth": depth,
            "batches": batches,
            "batch_occupancy": (sum(occ) / len(occ)) if occ else None,
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
            "p99_ms": pct(0.99),
            "mean_ms": (sum(lat) / len(lat) * 1e3) if lat else None,
            "throughput_rps": (completed / wall_s) if wall_s > 0 else None,
            "wall_s": wall_s,
        }

    def prometheus(self) -> str:
        """Prometheus text exposition: the registry instruments (counters,
        queue-depth gauge, latency histogram) plus the exact windowed
        percentiles/occupancy appended as gauges (they are derived views
        over the rolling window, not registry instruments)."""
        from ..obs.exposition import render_scalar

        s = self.snapshot()
        lines = [self.registry.prometheus().rstrip("\n")]
        derived = {
            "serve_latency_window_p50_ms": s["p50_ms"],
            "serve_latency_window_p95_ms": s["p95_ms"],
            "serve_latency_window_p99_ms": s["p99_ms"],
            "serve_latency_window_mean_ms": s["mean_ms"],
            "serve_batch_occupancy": s["batch_occupancy"],
            "serve_shed_fraction": s["shed_fraction"],
            "serve_throughput_rps": s["throughput_rps"],
            "serve_slot_goodput": s["slot_goodput"],
        }
        for name, v in derived.items():
            if v is None:
                continue  # absent series, not a lying 0.0
            lines.extend(render_scalar(
                name, "gauge", v))  # dcnn: metric=serve_latency_window_*_ms,serve_batch_occupancy,serve_shed_fraction,serve_throughput_rps,serve_slot_goodput
        return "\n".join(lines) + "\n"

    def __repr__(self) -> str:
        s = self.snapshot()
        return (f"ServeMetrics(completed={s['requests_completed']}, "
                f"shed={s['requests_shed']}, p99_ms={s['p99_ms']})")


#: Router priority classes, best first. Admission shares are keyed on
#: these; anything else at ``Router.submit`` is a ``ValueError``.
PRIORITIES = ("high", "normal", "low")


class RouterMetrics:
    """Router-tier accounting: per-priority-class request/shed/latency
    series plus fleet gauges and swap/rollback counters, on the same
    rules as :class:`ServeMetrics` — injectable clock, O(1) recorders,
    one private registry per instance (``registry=`` to pool), exact
    windowed percentiles per priority in :meth:`snapshot`, Prometheus
    text via the shared :mod:`~dcnn_tpu.obs.exposition` renderer.

    The registry has no label support (by design — see obs/registry.py),
    so per-priority series are name-suffixed and keep the ``_total``
    counter convention: ``serve_router_requests_<class>_total``,
    ``serve_router_shed_<class>_total``,
    ``serve_router_completed_<class>_total``,
    ``serve_router_failed_<class>_total``, and histogram
    ``serve_router_latency_seconds_<class>``. Fleet state rides gauges
    (``serve_router_replicas`` / ``_replicas_routable`` /
    ``_outstanding_rows`` / ``_capacity_rows`` / ``_canary_replicas`` /
    ``_version``) and lifecycle counters
    (``serve_router_readmits_total``, ``serve_router_replica_deaths_total``,
    ``serve_router_rejoins_total``, ``serve_router_replica_errors_total``,
    ``serve_router_swaps_total``, ``serve_router_swap_failures_total``,
    ``serve_router_promotions_total``, ``serve_router_rollbacks_total``).
    Gray-failure serving (ISSUE 19): ``serve_router_hedges_total`` /
    ``serve_router_hedge_wins_total`` count tail-latency hedging,
    ``serve_router_probations_total`` /
    ``serve_router_probation_rejoins_total`` + gauge
    ``serve_router_probation_replicas`` track slow-replica probation.
    """

    def __init__(self, *, window: int = 4096,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._clock = clock
        self._window = window
        self._lock = threading.Lock()
        self.registry = (registry if registry is not None
                         else MetricsRegistry(clock=clock))
        r = self.registry
        self._req = {p: r.counter(f"serve_router_requests_{p}_total",
                                  f"{p}-priority requests admitted")
                     for p in PRIORITIES}
        self._shed = {p: r.counter(f"serve_router_shed_{p}_total",
                                   f"{p}-priority requests shed at admission")
                      for p in PRIORITIES}
        self._done = {p: r.counter(f"serve_router_completed_{p}_total",
                                   f"{p}-priority requests completed")
                      for p in PRIORITIES}
        self._fail = {p: r.counter(f"serve_router_failed_{p}_total",
                                   f"{p}-priority requests failed with a "
                                   f"typed error")
                      for p in PRIORITIES}
        self._lat_hist = {p: r.histogram(
            f"serve_router_latency_seconds_{p}",
            f"{p}-priority request latency (admit to complete)")
            for p in PRIORITIES}
        self._readmits = r.counter(
            "serve_router_readmits_total",
            "accepted requests re-admitted to a surviving replica")
        self._deaths = r.counter("serve_router_replica_deaths_total",
                                 "replicas ejected as dead")
        self._rejoins = r.counter("serve_router_rejoins_total",
                                  "dead replicas re-admitted to the fleet")
        self._errors = r.counter("serve_router_replica_errors_total",
                                 "request failures attributed to a replica")
        self._swaps = r.counter("serve_router_swaps_total",
                                "completed replica version swaps")
        self._swap_failures = r.counter(
            "serve_router_swap_failures_total",
            "version swaps that failed (replica rejoined on old version)")
        self._promotions = r.counter(
            "serve_router_promotions_total",
            "canary versions promoted to the whole fleet")
        self._rollbacks = r.counter(
            "serve_router_rollbacks_total",
            "canary versions rolled back on regression")
        self._decommissions = r.counter(
            "serve_router_decommissions_total",
            "replicas removed via graceful drain-then-remove")
        self._decommission_sweeps = r.counter(
            "serve_router_decommission_sweeps_total",
            "decommissions that had to force-sweep outstanding work "
            "(drain timeout or death mid-drain); the work failed typed "
            "and re-admitted — never silently dropped")
        self._hedges = r.counter(
            "serve_router_hedges_total",
            "tail requests duplicated to a second replica after the "
            "hedge delay")
        self._hedge_wins = r.counter(
            "serve_router_hedge_wins_total",
            "hedged requests where the duplicate answered first")
        self._probations = r.counter(
            "serve_router_probations_total",
            "replicas demoted to probation as sustained latency outliers")
        self._probation_rejoins = r.counter(
            "serve_router_probation_rejoins_total",
            "probation replicas released after a clean probe")
        self.replicas = r.gauge("serve_router_replicas",
                                "replicas known to the router")
        self.replicas_routable = r.gauge(
            "serve_router_replicas_routable",
            "replicas currently accepting traffic")
        self.outstanding_rows = r.gauge(
            "serve_router_outstanding_rows",
            "accepted sample rows not yet resolved")
        self.capacity_rows = r.gauge(
            "serve_router_capacity_rows",
            "aggregate queue capacity of routable replicas")
        self.canary_replicas = r.gauge(
            "serve_router_canary_replicas",
            "replicas currently serving the canary version")
        self.version = r.gauge("serve_router_version",
                               "fleet model version (checkpoint step)")
        self.probation_replicas = r.gauge(
            "serve_router_probation_replicas",
            "replicas currently held in latency probation")
        self._init_local()

    def _init_local(self) -> None:
        with self._lock:
            self._lat_s = {p: deque(maxlen=self._window) for p in PRIORITIES}
            self._counts = {p: {"requests": 0, "shed": 0, "completed": 0,
                                "failed": 0} for p in PRIORITIES}
            self._t0 = self._clock()

    # -- recorders (all O(1), thread-safe) --
    def record_submit(self, priority: str, n: int = 1) -> None:
        with self._lock:
            self._counts[priority]["requests"] += n
        self._req[priority].inc(n)

    def record_shed(self, priority: str, n: int = 1) -> None:
        with self._lock:
            self._counts[priority]["shed"] += n
        self._shed[priority].inc(n)

    def record_done(self, priority: str, latency_s: float,
                    n: int = 1) -> None:
        with self._lock:
            self._counts[priority]["completed"] += n
            self._lat_s[priority].append(latency_s)
        self._done[priority].inc(n)
        self._lat_hist[priority].observe(latency_s)

    def record_failed(self, priority: str, n: int = 1) -> None:
        with self._lock:
            self._counts[priority]["failed"] += n
        self._fail[priority].inc(n)

    def record_readmit(self) -> None:
        self._readmits.inc()

    def record_replica_death(self) -> None:
        self._deaths.inc()

    def record_rejoin(self) -> None:
        self._rejoins.inc()

    def record_replica_error(self) -> None:
        self._errors.inc()

    def record_swap(self, ok: bool) -> None:
        (self._swaps if ok else self._swap_failures).inc()

    def record_promotion(self) -> None:
        self._promotions.inc()

    def record_rollback(self) -> None:
        self._rollbacks.inc()

    def record_decommission(self, clean: bool = True) -> None:
        self._decommissions.inc()
        if not clean:
            self._decommission_sweeps.inc()

    def record_hedge(self) -> None:
        self._hedges.inc()

    def record_hedge_win(self) -> None:
        self._hedge_wins.inc()

    def record_probation(self) -> None:
        self._probations.inc()

    def record_probation_rejoin(self) -> None:
        self._probation_rejoins.inc()

    def p99_ms(self, min_samples: int = 20) -> Optional[float]:
        """Exact windowed p99 across ALL priority classes — the hedge
        delay's base signal. ``None`` until ``min_samples`` completions
        exist (a hedge delay derived from two data points would fire on
        noise)."""
        with self._lock:
            lat = sorted(v for p in PRIORITIES for v in self._lat_s[p])
        if len(lat) < max(min_samples, 1):
            return None
        i = min(int(0.99 * (len(lat) - 1) + 0.5), len(lat) - 1)
        return lat[i] * 1e3

    # -- export --
    def snapshot(self) -> Dict[str, object]:
        """Point-in-time per-priority view + totals, consistent under one
        lock; latency keys ``None`` until the first completion (same
        no-data-is-not-zero rule as :meth:`ServeMetrics.snapshot`)."""
        with self._lock:
            lat = {p: sorted(self._lat_s[p]) for p in PRIORITIES}
            counts = {p: dict(self._counts[p]) for p in PRIORITIES}
            wall_s = max(self._clock() - self._t0, 0.0)

        def pct(vals, q):
            if not vals:
                return None
            i = min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)
            return vals[i] * 1e3

        out: Dict[str, object] = {"wall_s": wall_s}
        totals = {"requests": 0, "shed": 0, "completed": 0, "failed": 0}
        for p in PRIORITIES:
            c = counts[p]
            offered = c["requests"] + c["shed"]
            out[p] = {
                **c,
                "shed_fraction": (c["shed"] / offered) if offered else 0.0,
                "p50_ms": pct(lat[p], 0.50),
                "p99_ms": pct(lat[p], 0.99),
                "mean_ms": (sum(lat[p]) / len(lat[p]) * 1e3)
                if lat[p] else None,
            }
            for k in totals:
                totals[k] += c[k]
        offered = totals["requests"] + totals["shed"]
        totals["shed_fraction"] = (totals["shed"] / offered) if offered \
            else 0.0
        completed = totals["completed"]
        totals["throughput_rps"] = (completed / wall_s) if wall_s > 0 \
            else None
        out["total"] = totals
        return out

    def prometheus(self) -> str:
        """Registry instruments plus the exact per-priority windowed
        percentiles appended as gauges (derived views, like
        :meth:`ServeMetrics.prometheus`)."""
        from ..obs.exposition import render_scalar

        s = self.snapshot()
        lines = [self.registry.prometheus().rstrip("\n")]
        for p in PRIORITIES:
            for key, v in ((f"serve_router_latency_window_p50_ms_{p}",
                            s[p]["p50_ms"]),
                           (f"serve_router_latency_window_p99_ms_{p}",
                            s[p]["p99_ms"])):
                if v is None:
                    continue  # absent series, not a lying 0.0
                lines.extend(render_scalar(
                    key, "gauge", v))  # dcnn: metric=serve_router_latency_window_*
        return "\n".join(lines) + "\n"

    def __repr__(self) -> str:
        t = self.snapshot()["total"]
        return (f"RouterMetrics(completed={t['completed']}, "
                f"shed={t['shed']}, failed={t['failed']})")


class DecodeMetrics:
    """Continuous-batching decode telemetry (``serve/decode.py``), on the
    :class:`ServeMetrics` rules — injectable clock, O(1) thread-safe
    recorders, a private per-instance registry unless ``registry=`` pools
    one, one-lock :meth:`snapshot`, derived windowed views appended as
    gauges in :meth:`prometheus`.

    The decode plane's own vocabulary: **tokens** (generated — the unit
    throughput is priced in) vs **prefill tokens** (prompt/replay steps
    that write KV but emit nothing new), **slots** (iteration-level batch
    rows; occupancy = active/max over the step window is the metric
    continuous batching exists to raise), **pages**
    (:class:`~dcnn_tpu.serve.kvcache.KVPagePool` occupancy), admissions /
    evictions (preempt-and-recompute), and **TTFT** (submit → first
    generated token, the latency decode users actually feel).
    """

    def __init__(self, *, window: int = 4096,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._clock = clock
        self._window = window
        self._lock = threading.Lock()
        self.registry = (registry if registry is not None
                         else MetricsRegistry(clock=clock))
        r = self.registry
        self._submitted = r.counter(
            "decode_sequences_submitted_total",
            "sequences accepted into the decode queue")
        self._shed = r.counter(
            "decode_sequences_shed_total",
            "sequences rejected by decode-queue backpressure")
        self._admissions = r.counter(
            "decode_admissions_total",
            "sequences admitted into a running batch at a step boundary")
        self._evictions = r.counter(
            "decode_evictions_total",
            "sequences preempted to the queue on page exhaustion "
            "(recompute-on-readmission)")
        self._completions = r.counter(
            "decode_completions_total",
            "sequences decoded to max_new_tokens or EOS")
        self._tokens = r.counter(
            "decode_tokens_total", "tokens generated (emission steps)")
        self._prefill = r.counter(
            "decode_prefill_tokens_total",
            "prompt/replay tokens consumed (KV written, nothing emitted)")
        self._steps = r.counter(
            "decode_steps_total", "fixed-shape decode steps dispatched")
        self._active = r.gauge(
            "decode_active_slots", "sequences resident in decode slots")
        self._pages = r.gauge(
            "decode_pages_in_use", "KV pages currently allocated")
        self._queue_depth = r.gauge(
            "decode_queue_depth", "sequences waiting for a slot")
        self._ttft_hist = r.histogram(
            "decode_ttft_seconds",
            "time to first generated token (submit to first emission)")
        self._init_local()

    def _init_local(self) -> None:
        with self._lock:
            self._ttft_s: deque = deque(maxlen=self._window)
            self._occ: deque = deque(maxlen=self._window)
            self._counts = {k: 0 for k in (
                "submitted", "shed", "admitted", "evicted", "completed",
                "tokens", "prefill_tokens", "steps")}
            self._active_n = 0
            self._pages_n = 0
            self._depth_n = 0
            self._t0 = self._clock()

    def reset(self) -> None:
        """Zero everything and restart the throughput wall-clock —
        including this instance's registry instruments (same explicit-
        decision semantics as :meth:`ServeMetrics.reset`)."""
        self._init_local()
        for inst in (self._submitted, self._shed, self._admissions,
                     self._evictions, self._completions, self._tokens,
                     self._prefill, self._steps, self._active, self._pages,
                     self._queue_depth, self._ttft_hist):
            inst.reset()

    # -- recorders (all O(1), thread-safe) --
    def record_submit(self, n: int = 1) -> None:
        with self._lock:
            self._counts["submitted"] += n
        self._submitted.inc(n)

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self._counts["shed"] += n
        self._shed.inc(n)

    def record_admit(self, n: int = 1) -> None:
        with self._lock:
            self._counts["admitted"] += n
        self._admissions.inc(n)

    def record_evict(self, n: int = 1) -> None:
        with self._lock:
            self._counts["evicted"] += n
        self._evictions.inc(n)

    def record_complete(self, n: int = 1) -> None:
        with self._lock:
            self._counts["completed"] += n
        self._completions.inc(n)

    def record_token(self, n: int = 1) -> None:
        with self._lock:
            self._counts["tokens"] += n
        self._tokens.inc(n)

    def record_prefill(self, n: int = 1) -> None:
        with self._lock:
            self._counts["prefill_tokens"] += n
        self._prefill.inc(n)

    def record_ttft(self, seconds: float) -> None:
        with self._lock:
            self._ttft_s.append(seconds)
        self._ttft_hist.observe(seconds)

    def record_step(self, active: int, max_slots: int) -> None:
        """One decode step ran with ``active`` of ``max_slots`` slots
        occupied — the occupancy sample continuous batching is judged
        on."""
        with self._lock:
            self._counts["steps"] += 1
            self._occ.append(active / max(max_slots, 1))
            self._active_n = active
        self._steps.inc()
        self._active.set(active)

    def record_pages(self, pages_in_use: int) -> None:
        with self._lock:
            self._pages_n = pages_in_use
        self._pages.set(pages_in_use)

    def record_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._depth_n = depth
        self._queue_depth.set(depth)

    # -- export --
    def snapshot(self) -> Dict[str, Optional[float]]:
        """Point-in-time view under ONE lock. TTFT keys and
        ``slot_occupancy`` are ``None`` until data exists (no-data is not
        zero); ``tokens_per_sec`` prices GENERATED tokens only — prefill
        rides ``prefill_tokens`` so the two are never conflated."""
        with self._lock:
            now = self._clock()
            ttft = sorted(self._ttft_s)
            occ = list(self._occ)
            c = dict(self._counts)
            active, pages = self._active_n, self._pages_n
            depth = self._depth_n
            wall_s = max(now - self._t0, 0.0)

        def pct(q: float) -> Optional[float]:
            if not ttft:
                return None
            i = min(int(q * (len(ttft) - 1) + 0.5), len(ttft) - 1)
            return ttft[i] * 1e3

        return {
            "sequences_submitted": c["submitted"],
            "sequences_shed": c["shed"],
            "admissions": c["admitted"],
            "evictions": c["evicted"],
            "completions": c["completed"],
            "tokens": c["tokens"],
            "prefill_tokens": c["prefill_tokens"],
            "steps": c["steps"],
            "active_slots": active,
            "pages_in_use": pages,
            "queue_depth": depth,
            "slot_occupancy": (sum(occ) / len(occ)) if occ else None,
            "ttft_p50_ms": pct(0.50),
            "ttft_p99_ms": pct(0.99),
            "ttft_mean_ms": (sum(ttft) / len(ttft) * 1e3) if ttft else None,
            "tokens_per_sec": (c["tokens"] / wall_s) if wall_s > 0 else None,
            "wall_s": wall_s,
        }

    def prometheus(self) -> str:
        """Registry instruments plus the derived windowed views appended
        as gauges (same split as :meth:`ServeMetrics.prometheus`)."""
        from ..obs.exposition import render_scalar

        s = self.snapshot()
        lines = [self.registry.prometheus().rstrip("\n")]
        derived = {
            "decode_ttft_window_p50_ms": s["ttft_p50_ms"],
            "decode_ttft_window_p99_ms": s["ttft_p99_ms"],
            "decode_slot_occupancy": s["slot_occupancy"],
            "decode_tokens_per_sec": s["tokens_per_sec"],
        }
        for name, v in derived.items():
            if v is None:
                continue  # absent series, not a lying 0.0
            lines.extend(render_scalar(
                name, "gauge", v))  # dcnn: metric=decode_ttft_window_*_ms,decode_slot_occupancy,decode_tokens_per_sec
        return "\n".join(lines) + "\n"

    def __repr__(self) -> str:
        s = self.snapshot()
        return (f"DecodeMetrics(tokens={s['tokens']}, "
                f"completions={s['completions']}, "
                f"occupancy={s['slot_occupancy']})")
