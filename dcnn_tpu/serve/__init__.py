"""Online inference serving: bucketed compiled sessions, dynamic batching,
load shedding, latency metrics.

The north-star asks for a system that "serves heavy traffic from millions
of users"; the deployment transforms (``nn.fold_batchnorm``,
``nn.quantize_model``, ``nn.export_inference``) produce the graph, and this
subsystem puts it online:

- :class:`~dcnn_tpu.serve.engine.InferenceEngine` — loads a checkpoint,
  live model, or StableHLO artifact; pre-compiles one donated-buffer
  session per power-of-two batch bucket and warms them, so no request ever
  pays a compile;
- :class:`~dcnn_tpu.serve.batcher.DynamicBatcher` — bounded thread-safe
  queue + dispatcher that coalesces requests up to ``max_batch`` or a
  ``max_wait_ms`` deadline, pads to the nearest bucket, and scatters
  results through per-request futures; beyond queue capacity it sheds
  (:class:`~dcnn_tpu.serve.batcher.QueueFullError`) instead of queueing
  unboundedly;
- :class:`~dcnn_tpu.serve.metrics.ServeMetrics` — rolling p50/p95/p99
  latency, queue depth, batch occupancy, throughput, shed fraction, as a
  snapshot dict; backed by the shared ``dcnn_tpu.obs`` registry with
  Prometheus text exposition (:meth:`ServeMetrics.prometheus`).

The whole path is traced on the unified tracer (``dcnn_tpu.obs``):
``serve.queue`` (enqueue → dispatch, cross-thread), ``serve.dispatch`` ⊃
``serve.infer``, ``serve.compile``/``serve.warmup``, and ``serve.shed``
instants — a request's latency decomposes into queue/batch/compute on a
Perfetto timeline (docs/observability.md).

On top of the single-replica stack sits the **router tier**
(docs/deployment.md §"Router tier"):

- :class:`~dcnn_tpu.serve.router.Router` — fronts N replicas
  (:class:`~dcnn_tpu.serve.replica.LocalReplica` in-process,
  :class:`~dcnn_tpu.serve.replica.TcpReplica` over ``parallel/comm.py``
  framing) with priority-class admission (low sheds first),
  least-loaded health-driven routing, replica-death ejection +
  re-admission of accepted work, and rejoin;
- :class:`~dcnn_tpu.serve.swap.ModelVersionManager` — watches
  ``CheckpointManager`` commits and rolls new versions out canary-first
  with auto-promote / instant rollback
  (:class:`~dcnn_tpu.serve.swap.EngineFactory` builds the per-version
  engines).

The telemetry-driven **autoscaler** (``autoscale.py``) closes the loop
over all of it: scrapes every replica's ``/metrics`` exposition, grows
the fleet against SLO targets through the AOT-warmed ``factory``,
shrinks it with drain-then-remove decommission, and — via
:class:`~dcnn_tpu.serve.autoscale.DeviceLeaseBroker` + the elastic twin
in :mod:`dcnn_tpu.parallel.autoscale` — hands chips back and forth with
the training world on shared hardware.

**Generative decode** (ISSUE 20) is the iterative sibling of the one-shot
path above — requests hold a slot for many steps and finish at
data-dependent lengths, so batching is *iteration-level*
(docs/deployment.md §"Generative serving"):

- :class:`~dcnn_tpu.serve.kvcache.KVPagePool` — paged KV cache: fixed
  pages, free-list recycling, per-sequence page tables, null page 0;
  sized off live HBM headroom (:func:`~dcnn_tpu.serve.kvcache.suggest_num_pages`);
- :class:`~dcnn_tpu.serve.decode.DecodeEngine` — ONE jitted paged decode
  step compiled per (batch-bucket, page-bucket) at construction, AOT
  warmable, so admission never compiles;
- :class:`~dcnn_tpu.serve.decode.ContinuousBatcher` — admits at step
  boundaries, retires per sequence, preempts-and-recomputes on page
  exhaustion; per-sequence output bit-identical to
  :func:`~dcnn_tpu.serve.decode.decode_reference` (batch of one);
- :class:`~dcnn_tpu.serve.metrics.DecodeMetrics` — tokens/s, TTFT,
  slot occupancy, page occupancy on the standard scrape surface.

End-to-end drivers: ``examples/serve_snapshot.py`` (committed digits28
snapshot under open-loop traffic), ``examples/serve_router.py`` (the
router tier: replica kill + rejoin + hot-swap),
``examples/serve_autoscale.py`` (the autoscaler's diurnal soak +
device-lease handoff), ``examples/serve_decode.py`` (continuous-batching
decode + the bit-identity check), and ``BENCH_SERVE=1 / BENCH_AUTOSCALE=1
/ BENCH_DECODE=1 python bench.py`` (latency-vs-offered-load curve +
``router`` + ``autoscale`` + ``decode`` blocks). Quickstart:
docs/deployment.md §5–6.
"""

from .engine import InferenceEngine, serve_buckets
from .batcher import (
    DrainingError, DynamicBatcher, QueueFullError, ShutdownError,
)
from .metrics import DecodeMetrics, PRIORITIES, RouterMetrics, ServeMetrics
from .kvcache import KVPagePool, OutOfPagesError, suggest_num_pages
from .decode import ContinuousBatcher, DecodeEngine, decode_reference
from .replica import (
    LocalReplica, ReplicaDeadError, ReplicaError, ReplicaServer, SwapError,
    TcpReplica,
)
from .router import NoReplicasError, Router, RouterShedError
from .swap import EngineFactory, ModelVersionManager, newest_valid_version
from .traffic import diurnal, open_loop, spike, step
from .autoscale import (
    Autoscaler, AutoscalerConfig, DeviceLeaseBroker, HttpScraper,
    autoscale_check,
)

__all__ = [
    "InferenceEngine", "serve_buckets",
    "DynamicBatcher", "DrainingError", "QueueFullError", "ShutdownError",
    "ServeMetrics", "RouterMetrics", "DecodeMetrics", "PRIORITIES",
    "KVPagePool", "OutOfPagesError", "suggest_num_pages",
    "DecodeEngine", "ContinuousBatcher", "decode_reference",
    "LocalReplica", "TcpReplica", "ReplicaServer",
    "ReplicaError", "ReplicaDeadError", "SwapError",
    "Router", "RouterShedError", "NoReplicasError",
    "EngineFactory", "ModelVersionManager", "newest_valid_version",
    "open_loop", "diurnal", "spike", "step",
    "Autoscaler", "AutoscalerConfig", "DeviceLeaseBroker", "HttpScraper",
    "autoscale_check",
]
