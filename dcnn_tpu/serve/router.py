"""Fault-tolerant multi-replica serving router.

The missing tier between "one engine + one batcher on one device"
(PR 2) and the ROADMAP's million-user traffic goal: a :class:`Router`
fronts N replicas (:mod:`~dcnn_tpu.serve.replica` — in-process or behind
TCP hosts) and owns three guarantees the single-replica stack cannot
give:

1. **SLO-aware admission.** Requests carry a priority class
   (``high`` / ``normal`` / ``low``). Admission is layered on the
   ``DynamicBatcher`` shed path: the router admits a class only while
   total outstanding rows stay under that class's share of the fleet's
   aggregate queue capacity (``high`` = 1.0 by default), so under load
   the low class saturates — and sheds — first, and the shed error is
   the same *typed backpressure* (:class:`RouterShedError`, a
   ``QueueFullError``) callers already handle. A request that clears
   admission but finds every individual replica full is shed too
   (admission is aggregate; per-replica capacity is the ground truth).
2. **No silent drops.** Every admitted request enters an accepted-ledger
   and leaves it in exactly one of two ways: its future resolves with
   the result, or with a *typed* error. A replica that dies with
   accepted-but-unanswered requests (connection close, injected crash,
   last-heard timeout — never detected by hanging) is ejected and those
   requests are **re-admitted to survivors** through the shared
   ``resilience.retry`` backoff primitive, bounded by ``max_readmits``;
   exhaustion resolves the future with the last typed error. A restarted
   replica rejoins on the next :meth:`Router.check_replicas` sweep.
3. **Health/latency-driven routing.** Dispatch picks the routable
   replica with the fewest router-tracked outstanding rows (ties: lowest
   completion-latency EWMA) — the per-replica ``/healthz`` + ``/metrics``
   contract from PR 6 stays the external scrape surface, while in-band
   the router reads the same verdicts via ``replica.health()``/pongs.

Versioned hot-swap / canary / rollback live in
:class:`~dcnn_tpu.serve.swap.ModelVersionManager`, which drives
:meth:`Router.swap_replica` (drain → load → rejoin per replica).

Observability: every decision lands on
:class:`~dcnn_tpu.serve.metrics.RouterMetrics` (``serve_router_*``
series), and :meth:`Router.start_telemetry` exposes the router's own
``/metrics`` / ``/healthz`` / ``/snapshot`` — ``/healthz`` goes 503 when
no replica is routable, when the router is draining, or when a sweep
finds the fleet degraded below ``min_routable``.

Gray failure (fail-slow, ISSUE 19; docs/reliability.md §11): a replica
that stays alive but answers 10x slower defeats guarantees 2 and 3 — it
passes every health probe while dragging the tail. Two mitigations,
both off by default:

- **Hedged requests** (``hedge=True`` / ``DCNN_HEDGE``; "The Tail at
  Scale", Dean & Barroso): :meth:`Router.check_hedges` duplicates an
  in-flight request older than the hedge delay (``hedge_multiplier`` ×
  the fleet-wide windowed p99, floored at ``hedge_min_s``) to a second
  replica that has not seen it. First settle wins through the accepted
  ledger's exactly-once retire — the loser resolves nothing, so the
  no-silent-drop guarantee gains a no-double-resolve twin for free.
- **Slow-replica probation** (``slow_detect=True`` /
  ``DCNN_SLOW_DETECT``): per-replica completion latencies feed a
  :class:`~dcnn_tpu.resilience.slowness.SlownessDetector`; a replica
  convicted as a *sustained* relative outlier is demoted to probation
  (sorts last in routing — traffic only when nothing healthier can
  take it), and auto-rejoins after ``probation_cooldown_s`` once its
  health probe passes clean, its score forgotten so fresh traffic
  re-judges it (a still-slow replica re-convicts after the dwell). A
  fleet-wide slowdown moves the median with everyone — nobody convicts.

Chaos surface: ``serve.route`` trips in :meth:`Router.submit` (armed =
routing-layer failure), ``serve.replica_infer`` in every replica
dispatch, ``serve.swap`` in the version-load path, and the
``serve.slow_replica`` delay point (``FaultPlan.slow``) stretches a
replica's engine wall (docs/reliability.md fault cookbook).
"""

from __future__ import annotations

import math
import threading
import time
from concurrent.futures import CancelledError, Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..obs import get_tracer
from ..resilience import faults as _faults
from ..resilience.retry import retry_call
from ..resilience.slowness import SlownessConfig, SlownessDetector
from ..utils.env import get_env
from .batcher import DrainingError, QueueFullError
from .metrics import PRIORITIES, RouterMetrics
from .replica import DEATH_ERRORS, ReplicaDeadError, ReplicaError

#: Default admission shares: the fraction of aggregate fleet queue
#: capacity each priority class may fill. ``high`` may use everything;
#: ``low`` sheds once the fleet is 60% committed — the SLO knob.
DEFAULT_SHARES: Dict[str, float] = {"high": 1.0, "normal": 0.85,
                                    "low": 0.6}


class RouterShedError(QueueFullError):
    """Admission rejected this request (its priority class is over its
    share, or the fleet is out of capacity). Subclasses
    ``QueueFullError`` so every existing backpressure handler — the
    open-loop generator included — treats router shed as batcher shed."""


class NoReplicasError(ReplicaError):
    """No routable replica exists (all dead/draining). Typed terminal
    failure for accepted requests that exhausted re-admission."""


class _Handle:
    """Router-side state for one replica. Every field except ``name`` and
    ``replica`` is mutated under the router's ``_lock``."""

    __slots__ = ("name", "replica", "state", "outstanding", "completed",
                 "failed", "consecutive_failures", "ewma_ms", "canary",
                 "last_seq", "auto_rejoin", "probation", "probation_since")

    def __init__(self, name: str, replica):
        self.name = name
        self.replica = replica
        self.state = "up"            # up | unroutable | draining | dead
        self.outstanding = 0         # rows dispatched, not yet settled
        self.completed = 0
        self.failed = 0
        self.consecutive_failures = 0
        self.ewma_ms: Optional[float] = None
        self.canary = False
        self.last_seq = 0            # routing round-robin stamp
        # False when ejected for failing REQUESTS while health passed
        # (failure_eject_threshold): the sweep must not flap it back in
        # on the same health probe that was lying — rejoin is explicit
        self.auto_rejoin = True
        # latency probation (gray failure): still "up" but sorts last in
        # routing until the cooldown elapses and a probe passes clean
        self.probation = False
        self.probation_since = 0.0


class _Request:
    __slots__ = ("x", "n", "priority", "future", "t_submit", "attempts",
                 "tried", "span", "hedged", "dispatched", "hedge_names",
                 "inflight")

    def __init__(self, x, n, priority, t_submit):
        self.x, self.n, self.priority = x, n, priority
        self.future: Future = Future()
        self.t_submit = t_submit
        self.attempts = 0            # re-admissions consumed
        self.tried: set = set()      # replica names tried THIS admission
        # root distributed-trace span (admit → resolve): every replica
        # hop runs under its context, so one request is ONE trace across
        # the fleet (null handle when tracing is off)
        self.span = None
        # hedging state (check_hedges), mutated under the router lock:
        self.hedged = False          # a duplicate was already launched
        self.dispatched: set = set()  # every replica ever holding this
        self.hedge_names: set = set()  # the duplicates' replicas
        self.inflight = 0            # live dispatches; >0 blocks readmit


class Router:
    """N-replica serving front-end: priority admission, least-loaded
    routing, replica-death re-admission, rejoin, hot-swap hooks.

    ``clock``/``sleep`` are injectable (the re-admission backoff and all
    latency accounting run sleep-free in tests). ``replicas`` may be an
    iterable of replica objects (named by their ``.name``) or
    ``(name, replica)`` pairs.
    """

    def __init__(self, replicas=(), *, shares: Optional[Dict[str, float]]
                 = None, max_readmits: int = 3, min_routable: int = 1,
                 failure_eject_threshold: int = 0,
                 metrics: Optional[RouterMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 name: str = "router", flight=None,
                 hedge: Optional[bool] = None,
                 hedge_multiplier: Optional[float] = None,
                 hedge_min_s: Optional[float] = None,
                 slow_detect: Optional[bool] = None,
                 slow_config: Optional[SlownessConfig] = None,
                 probation_cooldown_s: Optional[float] = None):
        self.name = name
        self.shares = dict(DEFAULT_SHARES if shares is None else shares)
        unknown = set(self.shares) - set(PRIORITIES)
        if unknown:
            raise ValueError(f"unknown priority classes {sorted(unknown)}; "
                             f"known: {PRIORITIES}")
        for p in PRIORITIES:
            self.shares.setdefault(p, 1.0)
        self.max_readmits = max_readmits
        self.min_routable = min_routable
        # >0: eject a replica after this many CONSECUTIVE failed requests
        # even while its health probe still passes (a replica that answers
        # pings but fails every request is dead for routing purposes)
        self.failure_eject_threshold = failure_eject_threshold
        self.metrics = metrics if metrics is not None else RouterMetrics(
            clock=clock)
        # gray-failure serving knobs (module docstring): None = read the
        # env so a deployed router is switchable without a code change
        self.hedge = bool(get_env("DCNN_HEDGE", False)
                          if hedge is None else hedge)
        self.hedge_multiplier = float(
            get_env("DCNN_HEDGE_MULT", 3.0)
            if hedge_multiplier is None else hedge_multiplier)
        self.hedge_min_s = float(get_env("DCNN_HEDGE_MIN_S", 0.01)
                                 if hedge_min_s is None else hedge_min_s)
        self.slow_detect = bool(get_env("DCNN_SLOW_DETECT", False)
                                if slow_detect is None else slow_detect)
        self.probation_cooldown_s = float(
            get_env("DCNN_SLOW_PROBATION_S", 5.0)
            if probation_cooldown_s is None else probation_cooldown_s)
        self.slowness = SlownessDetector(SlownessConfig.from_env(slow_config),
                                         clock=clock)
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._handles: Dict[str, _Handle] = {}  # dcnn: guarded_by=_lock
        self._ledger: set = set()               # dcnn: guarded_by=_lock
        self._outstanding = 0                   # dcnn: guarded_by=_lock
        self._closing = False                   # dcnn: guarded_by=_lock
        self._seq = 0                           # dcnn: guarded_by=_lock
        self._telemetry = None
        # failure flight recorder (obs/flight.py): replica death/eviction
        # dumps a postmortem bundle. None = the process-global recorder
        # (disabled unless DCNN_FLIGHT_DIR / configure_flight enabled it).
        self._flight = flight
        for item in replicas:
            if isinstance(item, tuple):
                self.add_replica(item[1], name=item[0])
            else:
                self.add_replica(item)

    # -- fleet management --------------------------------------------------
    def add_replica(self, replica, name: Optional[str] = None) -> str:
        with self._lock:
            if name is None:
                name = getattr(replica, "name", None) \
                    or f"replica-{len(self._handles)}"
            if name in self._handles:
                raise ValueError(f"replica {name!r} already registered")
            self._handles[name] = _Handle(name, replica)
            self._update_gauges_locked()
        return name

    def remove_replica(self, name: str) -> None:
        """Administratively drop a replica (it is NOT closed — the caller
        owns its lifecycle). In-flight requests settle normally."""
        with self._lock:
            self._handles.pop(name, None)
            self._update_gauges_locked()

    def decommission(self, name: str,
                     timeout: Optional[float] = 30.0) -> Dict[str, Any]:
        """Graceful scale-down: **drain, then remove** — the path the
        autoscaler shrinks the fleet through, and the reason scale-down
        can never violate the accepted-ledger no-silent-drop guarantee.

        1. The victim goes ``draining``: admission capacity and routing
           exclude it immediately (new traffic lands on the rest of the
           fleet), but work already dispatched to it keeps running.
        2. Wait (injectable clock/sleep, like :meth:`drain`) until every
           outstanding row settles. A victim that dies mid-drain is
           swept (``kill()``) so its accepted-but-unanswered requests
           fail typed and **re-admit to survivors now** — same path as
           an ejection.
        3. On ``timeout`` the remainder is force-swept the same way —
           typed failure + re-admission, never an orphan.
        4. ``remove_replica``. Late settles are safe after removal: the
           settle callback holds the handle object and the ledger, not
           the fleet map.

        The replica object is NOT closed (the caller — typically the
        autoscaler, which built it — owns its lifecycle). Returns
        ``{"drained": rows_settled_cleanly, "swept": rows_force_failed,
        "was_dead": bool}``."""
        with self._lock:
            h = self._handles.get(name)
            if h is None:
                raise KeyError(f"no replica {name!r}")
            was_dead = h.state == "dead"
            start_outstanding = h.outstanding
            if not was_dead:
                h.state = "draining"
                self._update_gauges_locked()
        deadline = (self._clock() + timeout) if timeout is not None \
            else None
        swept = 0
        while True:
            with self._lock:
                outstanding = h.outstanding
                dead = h.state == "dead"
            if outstanding <= 0:
                break
            if dead or (deadline is not None
                        and self._clock() >= deadline):
                # died mid-drain, or out of patience: sweep the replica's
                # queue so everything it still holds fails typed and the
                # settle path re-admits it to survivors — the ledger
                # completes every accepted request either way
                if not dead:
                    self._note_dead(h, "decommission drain timed out")
                swept = outstanding
                try:
                    h.replica.kill()
                except Exception:
                    pass
                # give the sweep's synchronous settle callbacks (and a
                # TCP replica's reader-side failure path) a bounded
                # window to run down
                grace = self._clock() + 5.0
                while self._clock() < grace:
                    with self._lock:
                        if h.outstanding <= 0:
                            break
                    self._sleep(0.005)
                break
            self._sleep(0.005)
        with self._lock:
            self._handles.pop(name, None)
            self._update_gauges_locked()
        # clean == "no force-sweep happened": removing an already-settled
        # corpse (was_dead, swept 0) is not a sweep and must not trip
        # alerts on serve_router_decommission_sweeps_total
        self.metrics.record_decommission(clean=swept == 0)
        return {"drained": max(start_outstanding - swept, 0),
                "swept": swept, "was_dead": was_dead}

    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._handles)

    def replicas(self) -> Dict[str, Any]:
        """Point-in-time ``{name: replica_object}`` snapshot — the
        autoscaler's scrape pass reads each replica's exposition surface
        through this (never the router's internals)."""
        with self._lock:
            return {h.name: h.replica for h in self._handles.values()}

    def _update_gauges_locked(self) -> None:
        m = self.metrics
        m.replicas.set(len(self._handles))
        routable = [h for h in self._handles.values() if h.state == "up"]
        m.replicas_routable.set(len(routable))
        m.capacity_rows.set(sum(h.replica.queue_capacity for h in routable))
        m.outstanding_rows.set(self._outstanding)
        m.canary_replicas.set(
            sum(1 for h in self._handles.values() if h.canary))
        m.probation_replicas.set(
            sum(1 for h in self._handles.values() if h.probation))

    # -- admission + dispatch ----------------------------------------------
    def submit(self, x, priority: str = "normal") -> Future:
        """Admit one request (single sample or small batch, batcher
        conventions) into its priority class. Returns a future resolving
        to the logits, or to a typed error — never silently dropped.
        Raises :class:`RouterShedError` at admission (not accepted) and
        ``DrainingError`` after :meth:`drain`/:meth:`shutdown`."""
        if priority not in self.shares:
            raise ValueError(f"unknown priority {priority!r}; "
                             f"known: {PRIORITIES}")
        _faults.trip("serve.route", priority=priority)
        x = np.asarray(x)
        with self._lock:
            if self._closing:
                raise DrainingError("router is draining or shut down")
            shp = self._input_shape_locked()
            n = 1 if (shp is not None and tuple(x.shape) == shp) \
                else (int(x.shape[0]) if x.ndim > 0 else 1)
            cap = sum(h.replica.queue_capacity
                      for h in self._handles.values() if h.state == "up")
            limit = self.shares[priority] * cap
            if cap == 0 or self._outstanding + n > limit:
                self.metrics.record_shed(priority, n)
                raise RouterShedError(
                    f"{priority}-priority request of {n} shed: outstanding "
                    f"{self._outstanding} + {n} over class limit "
                    f"{limit:g} (fleet capacity {cap})")
            req = _Request(x, n, priority, self._clock())
            self._ledger.add(req)
            self._outstanding += n
            self.metrics.outstanding_rows.set(self._outstanding)
        tracer = get_tracer()
        # ONE root span per admitted request, admit → resolve. Dispatch
        # runs under its context so the whole chain — replica submit, the
        # batcher's queue/dispatch/infer spans, the TCP infer frame's
        # _trace carrier — shares this trace_id across threads AND
        # processes. Ended exactly once, where the ledger retires.
        req.span = tracer.begin("serve.request", track="router",
                                priority=priority, n=n)
        try:
            with tracer.activate(req.span):
                self._first_dispatch(req)
        except RouterShedError:
            # aggregate admission passed but every replica's own queue
            # shed: undo acceptance — the caller sees one coherent shed,
            # counted ONLY as shed (it was never truly admitted)
            if self._retire(req):
                self.metrics.record_shed(priority, req.n)
                tracer.end(req.span, outcome="shed")
            raise
        except BaseException as e:
            # anything non-typed out of the dispatch path (a malformed
            # request the replica's own validation rejects, an injected
            # routing fault) is the CALLER's error: un-admit so the
            # ledger cannot leak the request, then propagate. The span
            # ends only when THIS path retired the request — a typed
            # resolve inside dispatch already ended it.
            if self._retire(req):
                tracer.end(req.span, outcome=type(e).__name__)
            raise
        # counted as admitted only once placement is secured (or the
        # future already failed typed — still an accepted request), so a
        # shed request never double-counts in offered traffic
        self.metrics.record_submit(priority, n)
        return req.future

    def _input_shape_locked(self):
        for h in self._handles.values():
            shp = getattr(h.replica, "input_shape", None)
            if shp is not None:
                return tuple(shp)
        return None

    def _pick(self, exclude: set) -> Optional[_Handle]:
        """Least-outstanding routable replica not in ``exclude``.
        Probation replicas sort last outright (they take traffic only
        when nothing healthier can). Remaining ties break on the
        completion-latency EWMA quantized to ~30% log buckets
        (meaningfully slower replicas get less traffic; noise-level
        differences do not starve anyone), then on
        least-recently-dispatched — so an idle fleet round-robins instead
        of pinning everything to whichever replica happens to sort
        first."""
        with self._lock:
            candidates = [h for h in self._handles.values()
                          if h.state == "up" and h.name not in exclude]
            if not candidates:
                return None

            def score(h: _Handle):
                lat = (int(math.log(h.ewma_ms) * 4.0)
                       if h.ewma_ms is not None and h.ewma_ms > 0 else 0)
                return (h.probation, h.outstanding, lat, h.last_seq)

            best = min(candidates, key=score)
            self._seq += 1
            best.last_seq = self._seq
            return best

    def _try_replica(self, req: _Request) -> None:
        """One dispatch attempt: pick, submit, register the settle
        callback. Raises the replica's typed rejection for the retry
        wrapper to classify."""
        h = self._pick(req.tried)
        if h is None:
            with self._lock:
                fleet = {n: hh.state for n, hh in self._handles.items()}
            raise NoReplicasError(
                f"no routable replica for {req.priority}-priority request "
                f"(fleet: {fleet})")
        try:
            inner = h.replica.submit(req.x)
        except DEATH_ERRORS as e:
            req.tried.add(h.name)
            self._note_dead(h, f"submit failed: {e}")
            raise ReplicaDeadError(str(e)) from e
        except (QueueFullError, DrainingError, ReplicaError):
            req.tried.add(h.name)
            raise
        with self._lock:
            h.outstanding += req.n
            req.inflight += 1
            req.dispatched.add(h.name)
        inner.add_done_callback(lambda f, h=h: self._settle(req, h, f))

    def _first_dispatch(self, req: _Request) -> None:
        """Initial placement: walk the routable replicas once, least
        loaded first. Every replica shedding ⇒ RouterShedError (the
        caller un-admits); no replica at all ⇒ the future resolves with
        NoReplicasError (the request WAS admitted against capacity that
        vanished between admission and dispatch)."""
        last: Optional[BaseException] = None
        with self._lock:
            rounds = max(len(self._handles), 1)
        for _ in range(rounds):
            try:
                self._try_replica(req)
                return
            except (QueueFullError, DrainingError, ReplicaDeadError) as e:
                last = e
            except NoReplicasError as e:
                # candidates ran out mid-walk (dead handles shrink the
                # pool below `rounds`): if every replica actually TRIED
                # shed, this is still one coherent shed, not a typed
                # admitted-then-failed — availability metrics must not
                # blame overload on replica deaths
                if isinstance(last, (QueueFullError, DrainingError)):
                    break
                self._resolve_exc(req, e)
                return
        if isinstance(last, (QueueFullError, DrainingError)):
            raise RouterShedError(f"every routable replica shed: {last}")
        self._resolve_exc(req, NoReplicasError(
            f"no replica accepted the request: {last}"))

    def _readmit(self, req: _Request, failed: str) -> None:
        """Re-admission after a replica-attributed failure: the accepted
        request MUST complete or fail typed. The replica that just failed
        it is excluded whenever another routable one exists (a request
        must not ping-pong into the same degraded replica); the attempt
        loop rides the shared resilience.retry backoff (visible as
        ``serve_router_readmit_retry_attempts_total``)."""
        with self._lock:
            attempts = min(max(2, len(self._handles) + 1), 5)
        self.metrics.record_readmit()

        def attempt() -> None:
            # fresh exclusion set each backoff attempt: a replica that
            # shed on the PREVIOUS attempt gets reconsidered after the
            # sleep (queues drain in milliseconds) — only the replica
            # that just failed this request stays excluded, and only
            # while another routable one exists
            with self._lock:
                others = any(h.state == "up" and h.name != failed
                             for h in self._handles.values())
            req.tried = {failed} if others else set()
            # re-dispatch stays inside the request's root trace (this
            # runs on whatever thread settled the failed future — the
            # submitter's context is long gone)
            with get_tracer().activate(req.span):
                self._try_replica(req)

        try:
            # NOTE: this runs on whatever thread settled the failed future
            # — usually the dying replica's dispatcher — so the backoff
            # budget is deliberately tiny (<= 4 sleeps capped at 20 ms,
            # ~80 ms worst case per request): a survivors-briefly-full
            # fleet gets a fair second chance without parking a
            # dispatcher thread for whole backoff windows. Exhaustion is
            # a typed failure, counted, never a silent drop.
            retry_call(attempt,
                       attempts=attempts,
                       base=0.002, cap=0.02, timeout=0.25,
                       retry_on=(QueueFullError, DrainingError,
                                 ReplicaError),
                       retry_if=lambda e: not isinstance(
                           e, NoReplicasError),
                       sleep=self._sleep, clock=self._clock,
                       registry=self.metrics.registry,
                       name="serve_router_readmit")
        except NoReplicasError as e:
            self._resolve_exc(req, e)
        except (QueueFullError, DrainingError, ReplicaError) as e:
            self._resolve_exc(req, ReplicaDeadError(
                f"re-admission exhausted after replica death: {e}"))
        except BaseException as e:
            # the request is already accepted: whatever went wrong, its
            # future must resolve typed (never a silent ledger leak)
            self._resolve_exc(req, ReplicaError(
                f"re-admission failed: {type(e).__name__}: {e}"))

    # -- settlement --------------------------------------------------------
    def _settle(self, req: _Request, h: _Handle, inner: Future) -> None:
        exc: Optional[BaseException]
        if inner.cancelled():
            exc = CancelledError("replica-level future cancelled")
        else:
            exc = inner.exception()
        with self._lock:
            h.outstanding = max(h.outstanding - req.n, 0)
            req.inflight = max(req.inflight - 1, 0)
        if exc is None:
            t_done = self._clock()
            lat_ms = (t_done - req.t_submit) * 1e3
            with self._lock:
                h.completed += 1
                h.consecutive_failures = 0
                h.ewma_ms = (lat_ms if h.ewma_ms is None
                             else 0.8 * h.ewma_ms + 0.2 * lat_ms)
            if self.slow_detect:
                # probation signal: admit-to-complete wall attributed to
                # the replica that served it (the losing half of a hedged
                # pair lands here too — correctly, with its big latency)
                self.slowness.observe(h.name, lat_ms)
            won = self._resolve_ok(req, inner.result(),
                                   latency_s=t_done - req.t_submit)
            if won and h.name in req.hedge_names:
                self.metrics.record_hedge_win()
            return
        # replica-attributed failure: count it, maybe eject, re-admit
        self.metrics.record_replica_error()
        dead = isinstance(exc, DEATH_ERRORS)
        with self._lock:
            h.failed += 1
            h.consecutive_failures += 1
            over = (self.failure_eject_threshold > 0
                    and h.consecutive_failures
                    >= self.failure_eject_threshold)
            closing = self._closing
        if dead:
            self._note_dead(h, f"request failed: {type(exc).__name__}: "
                               f"{exc}")
        elif over:
            with self._lock:
                h.auto_rejoin = False  # its health probe still passes —
                # only an explicit rejoin() may re-admit it
            self._note_dead(h, f"{h.consecutive_failures} consecutive "
                               f"request failures")
        if req.future.done():
            # resolved while in flight — a drain timeout (already
            # retired) or a caller cancel (not): retire here so a
            # cancelled-then-failed request cannot leak the ledger
            if self._retire(req):
                get_tracer().end(req.span, outcome="cancelled")
            return
        with self._lock:
            still_inflight = req.inflight > 0
        if still_inflight:
            # a hedge (or, if the hedge just failed, the primary) still
            # holds a live dispatch for this request — it owns settlement
            # now; re-admitting here would triple-dispatch the request
            return
        if closing or req.attempts >= self.max_readmits:
            self._resolve_exc(req, exc if isinstance(exc, ReplicaError)
                              else ReplicaDeadError(
                                  f"replica {h.name} failed the request "
                                  f"({type(exc).__name__}: {exc}) and "
                                  f"re-admission is exhausted"))
            return
        req.attempts += 1
        self._readmit(req, failed=h.name)

    def _retire(self, req: _Request) -> bool:
        """Remove ``req`` from the ledger exactly once. False when someone
        (a drain timeout racing a late settle) already did — the loser
        must not decrement outstanding a second time."""
        with self._lock:
            if req not in self._ledger:
                return False
            self._ledger.discard(req)
            self._outstanding -= req.n
            self.metrics.outstanding_rows.set(self._outstanding)
            return True

    def _resolve_ok(self, req: _Request, result,
                    latency_s: float) -> bool:
        """True iff THIS call retired the request — the hedging dedupe:
        the first settle of a hedged pair wins the ledger, the loser
        resolves nothing (and must not count a hedge win)."""
        if not self._retire(req):
            return False
        get_tracer().end(req.span, outcome="ok",
                         latency_ms=round(latency_s * 1e3, 3))
        try:
            req.future.set_result(result)
            self.metrics.record_done(req.priority, latency_s, req.n)
        except InvalidStateError:
            pass  # cancelled by the caller while in flight
        return True

    def _resolve_exc(self, req: _Request, exc: BaseException) -> None:
        if not self._retire(req):
            return
        get_tracer().end(req.span, outcome=type(exc).__name__)
        try:
            req.future.set_exception(exc)
            self.metrics.record_failed(req.priority, req.n)
        except InvalidStateError:
            pass

    # -- liveness ----------------------------------------------------------
    def _flight_recorder(self):
        from ..obs.flight import resolve_flight_recorder
        return resolve_flight_recorder(self._flight)

    def _note_dead(self, h: _Handle, reason: str) -> None:
        with self._lock:
            if h.state == "dead":
                return
            h.state = "dead"
            h.probation = False  # death supersedes latency probation
            self._update_gauges_locked()
        # a corpse's latency score must not keep shifting the fleet median
        self.slowness.forget(h.name)
        self.metrics.record_replica_death()
        # postmortem evidence AT the death edge (once per ejection — the
        # guard above makes this edge-triggered): recent spans hold the
        # victim's last requests, the registry snapshot the fleet state.
        # record() never raises and is a no-op while the recorder is off.
        self._flight_recorder().record(
            "replica_death",
            reasons=[f"replica {h.name}: {reason}"],
            registry=self.metrics.registry,
            extra={"replica": h.name, "router": self.name,
                   "fleet": self.replica_stats()})

    # -- gray failure: hedging + probation (module docstring; ISSUE 19) --
    def _hedge_delay_s(self) -> Optional[float]:
        """p99-derived hedge trigger: ``hedge_multiplier`` × the exact
        fleet-wide windowed p99, floored at ``hedge_min_s``; ``None``
        (no hedging) until enough completions exist to make the p99
        meaningful."""
        p99 = self.metrics.p99_ms()
        if p99 is None:
            return None
        return max(self.hedge_min_s, self.hedge_multiplier * p99 / 1e3)

    def check_hedges(self) -> int:
        """Tail-latency hedging sweep ("The Tail at Scale"): every
        accepted request with exactly one live dispatch older than the
        hedge delay gets a duplicate on a replica that has not seen it.
        First settle wins through the ledger's exactly-once retire; the
        loser resolves nothing. Runs from :meth:`check_replicas` (and by
        hand in tests/tight loops). Returns hedges launched."""
        if not self.hedge:
            return 0
        delay = self._hedge_delay_s()
        if delay is None:
            return 0
        now = self._clock()
        with self._lock:
            due = [req for req in self._ledger
                   if not req.hedged and req.inflight == 1
                   and now - req.t_submit >= delay
                   and not req.future.done()]
            for req in due:
                req.hedged = True  # claimed under the lock: a racing
                #                    sweep cannot double-hedge
        launched = 0
        for req in due:
            if self._hedge_one(req):
                launched += 1
        return launched

    def _hedge_one(self, req: _Request) -> bool:
        """Dispatch the duplicate. Mirrors ``_try_replica`` but never
        escalates: a hedge that cannot place (no untried routable
        replica, or its submit sheds) is simply dropped — the primary
        still owns the request, and hedging is strictly opportunistic
        extra load, never extra failure."""
        with self._lock:
            exclude = set(req.dispatched)
        h = self._pick(exclude)
        if h is None:
            return False
        try:
            with get_tracer().activate(req.span):
                inner = h.replica.submit(req.x)
        except DEATH_ERRORS as e:
            self._note_dead(h, f"hedge submit failed: {e}")
            return False
        except Exception:
            return False
        with self._lock:
            h.outstanding += req.n
            req.inflight += 1
            req.dispatched.add(h.name)
            req.hedge_names.add(h.name)
        self.metrics.record_hedge()
        inner.add_done_callback(lambda f, h=h: self._settle(req, h, f))
        return True

    def check_probation(self) -> List[str]:
        """Slow-replica probation sweep: steps the latency slowness
        detector; a replica convicted as a *sustained* relative outlier
        (probation → convict with dwell, docs/reliability.md §11) is
        demoted — still "up", but it sorts last in routing. Release
        needs the cooldown to elapse AND a clean health probe (the
        eject/rejoin plumbing's probe); the released replica's score is
        forgotten so fresh traffic re-judges it from scratch — a
        still-slow replica re-convicts after the dwell. Returns the
        names currently held in probation."""
        if not self.slow_detect:
            return []
        now = self._clock()
        for tr in self.slowness.evaluate():
            if tr["to"] != "convicted":
                continue
            with self._lock:
                h = self._handles.get(str(tr["component"]))
                if h is None or h.probation:
                    continue
                h.probation = True
                h.probation_since = now
                self._update_gauges_locked()
            self.metrics.record_probation()
            self._flight_recorder().record(
                "replica_probation",
                reasons=[f"replica {tr['component']} latency EWMA "
                         f"{tr['ewma']:.2f}ms vs fleet median "
                         f"{tr['median']:.2f}ms — sustained outlier"],
                config={"cooldown_s": self.probation_cooldown_s},
                registry=self.metrics.registry,
                extra={"router": self.name,
                       "slowness": self.slowness.snapshot(),
                       "fleet": self.replica_stats()})
        with self._lock:
            held = [h for h in self._handles.values() if h.probation]
        still: List[str] = []
        for h in held:
            release = now - h.probation_since >= self.probation_cooldown_s
            if release:
                try:
                    release = (h.replica.health() is None
                               and not h.replica.is_dead())
                except Exception:
                    release = False
            if release:
                with self._lock:
                    h.probation = False
                    self._update_gauges_locked()
                self.slowness.forget(h.name)
                self.metrics.record_probation_rejoin()
            else:
                still.append(h.name)
        return still

    def check_replicas(self) -> Dict[str, Any]:
        """One liveness sweep — the router's heartbeat, called by the
        telemetry health check, the version manager's poll, or a test by
        hand (sleep-free):

        - ping every replica (refreshes TCP last-heard windows);
        - a replica whose ``health()``/``is_dead()`` says dead is ejected
          (``kill()`` sweeps its queue so accepted requests fail typed
          and re-admit NOW, not at some timeout);
        - an ejected replica that reports alive again (restarted process,
          re-established channel) **rejoins**;
        - returns the per-replica verdict map."""
        with self._lock:
            handles = list(self._handles.values())
        report: Dict[str, Any] = {}
        for h in handles:
            r = h.replica
            try:
                r.ping()
            except Exception:
                pass  # ping failures surface via health() below
            try:
                reason = r.health()
                hard_dead = r.is_dead()
            except Exception as e:
                reason, hard_dead = f"health probe failed: {e}", True
            with self._lock:
                state, auto = h.state, h.auto_rejoin
            if state == "draining":
                # mid-decommission: never flapped back to "up" by a
                # passing probe (the decommission owns the state from
                # here), but a death mid-drain is ejected NOW so its
                # accepted work re-admits instead of waiting out the
                # drain timeout
                if hard_dead:
                    self._note_dead(h, reason or "died while draining")
                    try:
                        r.kill()
                    except Exception:
                        pass
                    report[h.name] = f"ejected mid-drain ({reason})"
                else:
                    report[h.name] = "draining (decommission in progress)"
                continue
            if state == "dead":
                if not hard_dead and reason is None and auto:
                    with self._lock:
                        h.state = "up"
                        h.consecutive_failures = 0
                        self._update_gauges_locked()
                    self.metrics.record_rejoin()
                    report[h.name] = "rejoined"
                elif not auto:
                    report[h.name] = "dead (ejected for request " \
                                     "failures; explicit rejoin() required)"
                else:
                    report[h.name] = f"dead ({reason})"
                continue
            if hard_dead:
                self._note_dead(h, reason or "reported dead")
                try:
                    r.kill()  # sweep its queue: typed failures re-admit
                except Exception:
                    pass
                report[h.name] = f"ejected ({reason})"
            elif reason is not None:
                with self._lock:
                    h.state = "unroutable"
                    self._update_gauges_locked()
                report[h.name] = f"unroutable ({reason})"
            else:
                with self._lock:
                    if h.state == "unroutable":
                        h.state = "up"
                        self._update_gauges_locked()
                report[h.name] = "up"
        # the gray-failure sweeps ride the same heartbeat: probation
        # verdicts step first (so a convicted replica stops catching
        # hedges), then overdue tail requests hedge out
        for name in self.check_probation():
            report[name] = f"{report.get(name, 'up')} (probation)"
        self.check_hedges()
        return report

    def rejoin(self, name: str) -> None:
        """Explicitly re-admit a replica ejected by
        ``failure_eject_threshold`` (the sweep never auto-rejoins those —
        its health probe was passing while requests failed, so only an
        operator/controller decision brings it back)."""
        with self._lock:
            h = self._handles.get(name)
            if h is None:
                raise KeyError(f"no replica {name!r}")
            h.auto_rejoin = True
            h.consecutive_failures = 0
            if h.state == "dead" and not h.replica.is_dead():
                h.state = "up"
                self._update_gauges_locked()
                rejoined = True
            else:
                rejoined = False
        if rejoined:
            self.metrics.record_rejoin()

    # -- hot-swap hook (driven by swap.ModelVersionManager) ---------------
    def swap_replica(self, name: str, version, *,
                     canary: bool = False) -> None:
        """Drain → load ``version`` → rejoin for one replica. The replica
        is unroutable for the duration (new traffic fails over); a load
        failure rejoins it on the old version and re-raises
        :class:`~dcnn_tpu.serve.replica.SwapError`."""
        with self._lock:
            h = self._handles.get(name)
            if h is None:
                raise KeyError(f"no replica {name!r}")
            if h.state == "dead":
                raise ReplicaDeadError(f"replica {name!r} is dead")
            if h.state == "draining":
                raise ReplicaError(
                    f"replica {name!r} is being decommissioned; it cannot "
                    f"take a version swap")
            h.state = "unroutable"
            self._update_gauges_locked()
        try:
            h.replica.swap(version)
        except Exception:
            self.metrics.record_swap(ok=False)
            if h.replica.is_dead():
                # through _note_dead so the death is COUNTED — a replica
                # lost mid-swap must show on serve_router_replica_deaths
                self._note_dead(h, "died during version swap")
            else:
                with self._lock:
                    if h.state == "unroutable":
                        h.state = "up"  # rejoined on the old version
                    self._update_gauges_locked()
            raise
        with self._lock:
            # only an undisturbed swap rejoins: a concurrent decommission
            # (state "draining") or death sweep (state "dead") that landed
            # mid-load owns the handle now — resurrecting it to "up" would
            # route new traffic at a replica being drained or killed
            if h.state == "unroutable":
                h.state = "up"
                h.canary = canary
                h.consecutive_failures = 0
            self._update_gauges_locked()
        self.metrics.record_swap(ok=True)

    def set_canary(self, name: str, canary: bool) -> None:
        with self._lock:
            h = self._handles.get(name)
            if h is not None:
                h.canary = canary
                self._update_gauges_locked()

    def replica_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-replica router-side accounting — the feed the version
        manager judges canaries on."""
        with self._lock:
            return {h.name: {
                "state": h.state,
                "canary": h.canary,
                "version": h.replica.version,
                "outstanding": h.outstanding,
                "completed": h.completed,
                "failed": h.failed,
                "consecutive_failures": h.consecutive_failures,
                "ewma_ms": h.ewma_ms,
                "probation": h.probation,
            } for h in self._handles.values()}

    # -- health / telemetry ------------------------------------------------
    def health_reason(self) -> Optional[str]:
        """``None`` while the router can serve: not draining, and at
        least ``min_routable`` replicas routable."""
        with self._lock:
            if self._closing:
                return "draining or shut down: not accepting requests"
            routable = sum(1 for h in self._handles.values()
                           if h.state == "up")
        if routable < self.min_routable:
            return (f"degraded: {routable} routable replica(s), "
                    f"need >= {self.min_routable}")
        return None

    def outstanding(self) -> int:
        """Accepted-but-unresolved rows — the ledger sweep tests assert
        this returns to 0 (nothing silently dropped)."""
        with self._lock:
            return self._outstanding

    def start_telemetry(self, port: int = 0, host: str = "127.0.0.1"):
        """The router's own scrape surface: ``/metrics`` =
        ``RouterMetrics.prometheus()``, ``/healthz`` runs a live
        :meth:`check_replicas` sweep then applies :meth:`health_reason`
        (a scrape sees a dead replica the moment it is scraped, not at
        the next sweep), ``/snapshot`` adds per-replica stats."""
        from ..obs.server import TelemetryServer

        if self._telemetry is not None:
            self._telemetry.stop()
            self._telemetry = None

        def _check() -> Optional[str]:
            self.check_replicas()
            return self.health_reason()

        srv = TelemetryServer(registry=self.metrics.registry,
                              metrics_text=self.metrics.prometheus,
                              host=host, port=port)
        srv.set_identity(component="router", name=self.name)
        srv.attach_flight(self._flight_recorder())
        srv.add_check("router", _check)
        srv.add_snapshot("router", self.metrics.snapshot)
        srv.add_snapshot("replicas", self.replica_stats)
        self._telemetry = srv.start()
        return srv

    # -- teardown ----------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop intake; wait for the accepted ledger to empty (replicas
        keep dispatching their queues). On timeout the remaining ledger
        is failed typed — never orphaned — and ``TimeoutError`` raises."""
        with self._lock:
            self._closing = True
        deadline = (self._clock() + timeout) if timeout is not None else None
        while True:
            with self._lock:
                if not self._ledger:
                    return
            if deadline is not None and self._clock() >= deadline:
                break
            self._sleep(0.005)
        with self._lock:
            pending = list(self._ledger)
        exc = DrainingError(f"router drain timed out after {timeout}s")
        for req in pending:
            self._resolve_exc(req, exc)
        raise TimeoutError(
            f"router drain did not finish in {timeout}s "
            f"({len(pending)} accepted request(s) failed typed)")

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """``drain=True`` completes the ledger first. Replicas are NOT
        closed (the caller owns them) but the telemetry port is always
        released."""
        try:
            if drain:
                self.drain(timeout)
            else:
                with self._lock:
                    self._closing = True
                    pending = list(self._ledger)
                exc = DrainingError("router shut down without drain")
                for req in pending:
                    self._resolve_exc(req, exc)
        finally:
            if self._telemetry is not None:
                self._telemetry.stop()
                self._telemetry = None

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def __repr__(self) -> str:
        with self._lock:
            states = {h.name: h.state for h in self._handles.values()}
        return f"Router({self.name!r}, replicas={states})"
