"""Paged KV-cache: fixed-size page pool + per-sequence page tables.

The memory problem generative serving actually has (the vLLM observation):
a dense per-slot KV cache must be provisioned for the *longest possible*
sequence, so a fleet of mostly-short requests wastes most of its cache HBM
on padding, and slot count — hence batch occupancy, hence tokens/s — is
capped by the worst case instead of the working set. Paging fixes both:
the cache is one pool of fixed-size pages (``page_size`` token slots per
page, per layer), a sequence owns only the pages its current length
needs, pages recycle through a free list the moment a sequence completes,
and a sequence's *logical* positions map to *physical* pool slots through
its page table — which is exactly the indirection the decode step's
gather/scatter consumes (``serve/decode.py``).

Layout: ``k``/``v`` are ``(num_layers, num_pages, page_size, embed_dim)``
device arrays. **Page 0 is the null page**: never allocated, target of
every padded page-table entry and of inactive batch rows' writes. Active
sequences never read it — the decode mask excludes positions past a
sequence's length — so colliding garbage writes land where they can't be
observed, and the step function needs no scatter predication.

Sizing: :func:`suggest_num_pages` turns the live HBM headroom
(``obs/xla.sample_hbm`` — in-use vs limit, the same gauges the watermark
rides) into a page budget, with an explicit default for backends that
report no memory stats (CPU). The engine reports its executables'
``memory_analysis`` bytes alongside (``obs/xla.executable_cost``), so a
capture shows both what the pool took and what the step needs.

Thread-safety: the allocator's bookkeeping (free list, tables) is guarded
by one lock — the continuous batcher calls it from its scheduler thread
while telemetry reads occupancy from scrape threads. The ``k``/``v``
arrays themselves are owned by the engine step loop (single writer).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

import jax.numpy as jnp


class OutOfPagesError(RuntimeError):
    """The page pool is exhausted — a *typed* allocation failure so the
    scheduler can preempt-and-recompute (release a victim's pages, requeue
    it) instead of crashing mid-step."""


class KVPagePool:
    """Fixed page pool + free list + per-sequence page tables.

    ``pages_for(length)`` pages hold a ``length``-token sequence;
    :meth:`ensure` grows a sequence's table to cover a target length and
    raises :class:`OutOfPagesError` (allocating nothing) when the free
    list can't; :meth:`release` returns a completed sequence's pages to
    the free list. :meth:`table` renders the page table padded to a
    bucket width with null-page zeros — the fixed-shape array the
    compiled decode step indexes with.
    """

    def __init__(self, *, num_layers: int, embed_dim: int,
                 page_size: int = 8, num_pages: int = 64,
                 dtype: Any = jnp.float32):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2 (page 0 is the null "
                             f"page), got {num_pages}")
        self.num_layers = int(num_layers)
        self.embed_dim = int(embed_dim)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.dtype = jnp.dtype(dtype)
        shape = (self.num_layers, self.num_pages, self.page_size,
                 self.embed_dim)
        # engine-owned device state: the step loop threads these through
        # the compiled step and writes the updated arrays back
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self._lock = threading.Lock()
        # page 0 excluded: it is the null page (module docstring)
        self._free: deque = deque(range(1, self.num_pages))  # dcnn: guarded_by=_lock
        self._tables: Dict[Any, List[int]] = {}  # dcnn: guarded_by=_lock

    # -- geometry --
    def pages_for(self, length: int) -> int:
        """Pages a ``length``-token sequence occupies (0 for length 0)."""
        return -(-int(length) // self.page_size)

    @property
    def page_bytes(self) -> int:
        """Device bytes one page costs across K+V and all layers — the
        unit :func:`suggest_num_pages` budgets in."""
        return (2 * self.num_layers * self.page_size * self.embed_dim
                * self.dtype.itemsize)

    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return (self.num_pages - 1) - len(self._free)

    @property
    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    def num_seq_pages(self, seq_id: Any) -> int:
        with self._lock:
            return len(self._tables.get(seq_id, ()))

    # -- allocation --
    def ensure(self, seq_id: Any, length: int) -> int:
        """Grow ``seq_id``'s page table until it covers ``length`` tokens.
        Returns the table's page count. All-or-nothing: raises
        :class:`OutOfPagesError` without allocating anything when the
        free list can't cover the growth, so a failed extension never
        leaks partial pages."""
        need = self.pages_for(length)
        with self._lock:
            table = self._tables.setdefault(seq_id, [])
            grow = need - len(table)
            if grow <= 0:
                return len(table)
            if grow > len(self._free):
                raise OutOfPagesError(
                    f"sequence {seq_id!r} needs {grow} more page(s) for "
                    f"length {length}; only {len(self._free)} of "
                    f"{self.num_pages - 1} allocatable pages free")
            table.extend(self._free.popleft() for _ in range(grow))
            return len(table)

    def release(self, seq_id: Any) -> int:
        """Return ``seq_id``'s pages to the free list (recycling on
        completion/preemption). Unknown ids are a no-op — release must be
        safe to call from every teardown path. Returns pages freed."""
        with self._lock:
            table = self._tables.pop(seq_id, [])
            self._free.extend(table)
            return len(table)

    def table(self, seq_id: Any, width: int) -> np.ndarray:
        """``seq_id``'s page table as int32, zero-padded to ``width``
        entries (padding = the null page). ``width`` is the page bucket
        the scheduler picked; a table longer than ``width`` is a caller
        bug and raises."""
        with self._lock:
            table = list(self._tables.get(seq_id, ()))
        if len(table) > width:
            raise ValueError(f"sequence {seq_id!r} holds {len(table)} "
                             f"pages > table width {width}")
        out = np.zeros(width, np.int32)
        out[:len(table)] = table
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            in_use = (self.num_pages - 1) - len(self._free)
            seqs = len(self._tables)
        return {"num_pages": self.num_pages, "page_size": self.page_size,
                "pages_in_use": in_use,
                "pages_free": (self.num_pages - 1) - in_use,
                "sequences": seqs, "page_bytes": self.page_bytes}

    def __repr__(self) -> str:
        s = self.snapshot()
        return (f"KVPagePool(layers={self.num_layers}, "
                f"pages={self.num_pages}x{self.page_size}, "
                f"embed={self.embed_dim}, in_use={s['pages_in_use']})")


def suggest_num_pages(page_bytes: int, *, fraction: float = 0.2,
                      default: int = 64, cap: int = 4096,
                      registry=None) -> int:
    """Size the page pool off live HBM headroom: ``fraction`` of
    (limit − in-use) from :func:`~dcnn_tpu.obs.xla.sample_hbm`, in units
    of ``page_bytes`` (:attr:`KVPagePool.page_bytes`), clamped to
    ``[2, cap]``. Backends without memory stats (CPU) get ``default`` —
    an explicit number, not a guess dressed up as telemetry."""
    if page_bytes < 1:
        raise ValueError(f"page_bytes must be >= 1, got {page_bytes}")
    if not 0 < fraction <= 1:
        raise ValueError(f"fraction must be in (0, 1], got {fraction}")
    from ..obs.xla import sample_hbm

    hbm = sample_hbm(registry)
    if not hbm or not hbm.get("hbm_bytes_limit"):
        return default
    headroom = max(hbm["hbm_bytes_limit"] - hbm.get("hbm_bytes_in_use", 0.0),
                   0.0)
    return int(min(max(headroom * fraction // page_bytes, 2), cap))
