"""dcnn_tpu — a TPU-native deep-learning framework.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the reference
C++/CUDA framework tungphambasement/DCNN (``tnn``): an NCHW CNN layer library
with Sequential container + builder + JSON config, optimizers/losses/schedulers,
data loaders + augmentations, checkpointing, per-layer profiling, and — as the
distributed core — microbatched pipeline parallelism (sync / semi-async /
compiled 1F1B over a TPU mesh) plus data-parallel sharding via ``jax.sharding``.

Design stance (see SURVEY.md §7): idiomatic JAX — jit-compiled pure functions,
pytree parameters, functional optimizers, ``shard_map`` over a device Mesh with
XLA collectives over ICI — not a translation of the reference's mutable
object-per-layer CUDA design.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("DCNN_PLATFORM"):
    # Select the JAX backend ("tpu", "cpu", …) before any computation. Set via
    # config, not JAX_PLATFORMS: PJRT plugins registered from sitecustomize may
    # force their own jax_platforms value, and the config update wins.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["DCNN_PLATFORM"])

from .utils.env import get_env as _get_env

if _get_env("DCNN_DEBUG", False):
    # the 'debug build' switch (reference ENABLE_DEBUG -> ASan,
    # CMakeLists.txt:22): numeric sanitizers on for the whole process
    from .core.debug import enable_debug_mode as _edm

    _edm()

from . import core, nn, obs, ops, optim

__all__ = ["core", "nn", "obs", "ops", "optim", "__version__"]
