"""Accuracy metrics.

Reference equivalent: argmax-match count/accuracy kernels on CPU and GPU with
a device dispatch (``include/utils/utils_extended.hpp:11-40``,
``src/utils/accuracy_impl/{cpu,cuda}/accuracy.*``). On TPU both are one fused
argmax-compare-reduce that stays on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def correct_count(predictions: jax.Array, targets: jax.Array) -> jax.Array:
    """Number of rows where argmax(pred) == argmax(target). Targets may be
    one-hot (rank 2) or integer class labels (rank 1)."""
    pred_cls = jnp.argmax(predictions, axis=-1)
    target_cls = targets if targets.ndim == 1 else jnp.argmax(targets, axis=-1)
    return jnp.sum(pred_cls == target_cls)


def accuracy(predictions: jax.Array, targets: jax.Array) -> jax.Array:
    return correct_count(predictions, targets) / predictions.shape[0]
