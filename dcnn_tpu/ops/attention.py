"""Scaled-dot-product attention ops: naive, blockwise (flash-style), Pallas.

No reference analog — the reference is a CNN-only framework with no attention
anywhere (SURVEY.md §5.7 verified absence). Attention is nonetheless
first-class here because it is the op whose memory behaviour defines
long-context scaling on TPU: the blockwise/online-softmax formulation keeps
the S×S score matrix out of HBM, and is also the local compute step of ring
attention (``dcnn_tpu/parallel/sequence.py``).

Shapes follow (B, H, S, D): batch, heads, sequence, head dim. All functions
are jittable with static shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.precision import get_precision, precision_keyed_jit

NEG_INF = -1e30


def _check_mask_rank(mask: jax.Array) -> jax.Array:
    """Masks must be 2-D (Sq, Sk) or 4-D (B|1, H|1, Sq, Sk). 3-D masks are
    rejected: a (B, Sq, Sk) key-padding mask would silently broadcast as
    (1, H=B, Sq, Sk) — head-aligned, not batch-aligned — whenever B == H
    (ADVICE r2 #5). Callers with a batch mask must add the head axis
    explicitly: ``mask[:, None]``."""
    mask = jnp.asarray(mask, bool)   # accept 0/1 float masks like jnp.where did
    if mask.ndim == 3:
        raise ValueError(
            "3-D attention masks are ambiguous (batch- vs head-aligned); "
            "pass (Sq, Sk) or (B|1, H|1, Sq, Sk) — for a batch key-padding "
            "mask use mask[:, None].")
    if mask.ndim > 4:
        raise ValueError(
            f"attention mask rank {mask.ndim} > 4; expected (Sq, Sk) or "
            f"(B|1, H|1, Sq, Sk)")
    while mask.ndim < 4:
        mask = mask[None]
    return mask


def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = False, mask: Optional[jax.Array] = None,
              scale: Optional[float] = None) -> jax.Array:
    """Reference (materialising) attention: ``softmax(q·kᵀ·scale)·v``.

    ``mask``: (Sq, Sk) or (B|1, H|1, Sq, Sk); True = attend (3-D rejected —
    see :func:`_check_mask_rank`). O(S²) memory — the numerics oracle for the
    blockwise/pallas/ring variants.

    Fully-masked rows return 0 (zero softmax mass), the same convention as
    :func:`blockwise_attention` / :func:`flash_attention` — NOT the uniform
    average a plain softmax over all-NEG_INF scores would produce.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        precision=get_precision()) * scale
    allowed = None
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        allowed = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
    if mask is not None:
        mask = _check_mask_rank(mask)
        allowed = mask if allowed is None else (allowed & mask)
    if allowed is not None:
        scores = jnp.where(allowed, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    if allowed is not None:
        # zero fully-masked rows (softmax of all-NEG_INF is uniform 1/Sk)
        any_allowed = jnp.any(jnp.broadcast_to(allowed, scores.shape),
                              axis=-1, keepdims=True)
        weights = jnp.where(any_allowed, weights, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v,
                      precision=get_precision())


def _online_block(acc, m, l, q, k_blk, v_blk, scale, score_mask):
    """One online-softmax accumulation step for query block against one
    K/V block. Returns updated (acc, m, l). score_mask: (Sq, Skb) or None.

    The running state (acc, m, l) is float32 regardless of input dtype —
    bf16 statistics lose 8+ bits of softmax mass and fp16 can't even hold
    the -1e30 mask sentinel — matching the Pallas kernel's fp32 VMEM
    scratch. Callers cast the final normalised output back to input dtype.
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k_blk,
                   precision=get_precision(),
                   preferred_element_type=jnp.float32) * scale
    if score_mask is not None:
        s = jnp.where(score_mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    p = jnp.exp(s - m_new[..., None])
    if score_mask is not None:
        p = jnp.where(score_mask, p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v_blk.dtype), v_blk,
        precision=get_precision(), preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = False, block_kv: int = 512,
                        scale: Optional[float] = None,
                        mask: Optional[jax.Array] = None) -> jax.Array:
    """Flash-style attention: online softmax over K/V blocks via ``lax.scan``
    — never materialises the (Sq, Sk) score matrix. Exact (not approximate);
    matches :func:`attention` to float tolerance.

    Masking: ``causal`` plus an optional ``mask`` of rank 2 (Sq, Sk) or 4
    (B|1, H|1, Sq, Sk), True = attend (padding/segment masks; 3-D rejected —
    see :func:`_check_mask_rank`). The mask is consumed one K/V block at a
    time, so this path keeps its O(Sq·block_kv) working set (the caller's
    mask array itself may of course be O(Sq·Sk) — pass broadcastable
    singleton dims where possible). Fully-masked rows return 0 (zero softmax
    mass), the same convention as :func:`attention`. The Pallas
    :func:`flash_attention` kernel remains causal-only; masked calls route
    here.
    """
    if mask is not None:
        mask = _check_mask_rank(mask)
    return _blockwise_attention_jit(q, k, v, mask, causal=causal,
                                    block_kv=block_kv, scale=scale)


@functools.partial(precision_keyed_jit,
                   static_argnames=("causal", "block_kv", "scale"))
def _blockwise_attention_jit(q, k, v, mask, causal, block_kv, scale):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_kv = min(block_kv, sk)
    nblk = -(-sk // block_kv)
    pad = nblk * block_kv - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, nblk, block_kv, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblk, block_kv, d).transpose(2, 0, 1, 3, 4)

    if mask is not None:
        mask = _check_mask_rank(mask)  # idempotent; guards direct callers
        if mask.shape[-1] not in (1, sk):
            raise ValueError(
                f"mask last dim {mask.shape[-1]} must be 1 or Sk={sk}")
        if pad and mask.shape[-1] == sk:
            mask = jnp.pad(mask, ((0, 0),) * 3 + ((0, pad),))

    q_pos = jnp.arange(sq)                       # global query positions
    diag_offset = sk - sq                        # causal diag when Sq != Sk

    def body(carry, blk):
        acc, m, l = carry
        k_blk, v_blk, blk_idx = blk
        kv_pos = blk_idx * block_kv + jnp.arange(block_kv)
        valid = kv_pos < sk                      # padding mask
        if causal:
            allowed = kv_pos[None, :] <= (q_pos[:, None] + diag_offset)
            score_mask = (allowed & valid[None, :])[None, None]
        else:
            score_mask = jnp.broadcast_to(valid[None, :],
                                          (sq, block_kv))[None, None]
        if mask is not None:
            mask_blk = (mask if mask.shape[-1] == 1 else
                        jax.lax.dynamic_slice_in_dim(
                            mask, blk_idx * block_kv, block_kv, axis=-1))
            score_mask = score_mask & mask_blk
        acc, m, l = _online_block(acc, m, l, q, k_blk, v_blk, scale,
                                  score_mask)
        return (acc, m, l), None

    # fp32 online-softmax state irrespective of q.dtype (see _online_block)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0), (kb, vb, jnp.arange(nblk)))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward kernel
# ---------------------------------------------------------------------------

def _tile_geometry(q_start, kv_start, block_q, block_kv, sk, sq, causal):
    """Shared (live, mask) for one (q, kv) tile — used identically by the
    forward and both backward kernels so their masking can never diverge.
    ``live``: causal block-skip predicate (False = tile strictly above the
    q tile's diagonal band, all FLOPs skippable). ``mask``: kv-padding
    validity & the per-element causal triangle (diag offset sk-sq)."""
    live = (jnp.asarray(True) if not causal
            else kv_start <= q_start + block_q - 1 + (sk - sq))
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)
    kv_pos = kv_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    mask = kv_pos < sk
    if causal:
        mask &= kv_pos <= (q_pos + (sk - sq))
    return live, mask


def _tile_scores(q, k_blk, scale, precision):
    """scale·(q·k_blkᵀ) in fp32 — the QKᵀ tile every kernel starts from."""
    return jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                               precision=precision,
                               preferred_element_type=jnp.float32) * scale


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, nkv: int, sk: int, sq: int, causal: bool, scale: float,
                  precision):
    """One (batch·head, q-block, kv-block) program. K/V are *streamed*: each
    program sees one (block_kv, d) tile (grid's innermost axis walks the kv
    blocks), so VMEM holds one K and one V tile — never the whole sequence.
    Online-softmax running state (acc, m, l) lives in VMEM scratch carried
    across the kv axis; the output block AND the per-row logsumexp (saved for
    the Pallas backward) are written on the last kv step.
    Refs carry a leading size-1 batch·head block dim."""
    t = pl.program_id(2)
    q = q_ref[0]
    block_q, d = q.shape
    block_kv = k_ref.shape[1]

    @pl.when(t == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = pl.program_id(1) * block_q
    # causal block skip: a kv tile strictly above the diagonal band of this
    # q tile contributes nothing — skip its FLOPs entirely (the DMA still
    # runs; the kernel is compute-bound so this ~halves causal time)
    live, mask = _tile_geometry(q_start, t * block_kv, block_q, block_kv,
                                sk, sq, causal)

    @pl.when(live)
    def _accumulate():
        k_blk, v_blk = k_ref[0], v_ref[0]
        s = jnp.where(mask, _tile_scores(q, k_blk, scale, precision), NEG_INF)
        m = m_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)

    @pl.when(t == nkv - 1)
    def _finalize():
        l_fin = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / l_fin[:, None]).astype(o_ref.dtype)
        # logsumexp per row; fully-masked rows get ~NEG_INF (the backward
        # masks their probabilities to 0 explicitly, never via exp)
        lse_ref[0] = (m_ref[:, :1] + jnp.log(l_fin)[:, None])


try:  # pallas is TPU/interpret-only in some builds; degrade gracefully
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _flash_forward(q, k, v, *, causal, block_q, block_kv, scale, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, sk)
    pad_q = -sq % block_q
    pad_kv = -sk % block_kv
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_kv), (0, 0))) if pad_kv else k
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_kv), (0, 0))) if pad_kv else v
    sq_p, sk_p = sq + pad_q, sk + pad_kv
    nkv = sk_p // block_kv
    qf = qp.reshape(b * h, sq_p, d)
    kf = kp.reshape(b * h, sk_p, d)
    vf = vp.reshape(b * h, sk_p, d)
    kernel = functools.partial(_flash_kernel, nkv=nkv, sk=sk, sq=sq,
                               causal=causal, scale=scale,
                               precision=get_precision())
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, sq_p, 1), jnp.float32)],
        # kv axis innermost: TPU grids run sequentially with the last axis
        # fastest, so scratch accumulators carry across kv steps per q block
        grid=(b * h, sq_p // block_q, nkv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, t: (i, t, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, t: (i, t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, t: (i, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq_p, d)[:, :, :sq], lse.reshape(b, h, sq_p)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, nkv: int, sk: int, sq: int,
                         causal: bool, scale: float, precision):
    """dQ program: grid (batch·head, q-block, kv-block), kv innermost.
    For each kv tile: P = exp(S - lse), dS = P*(dO·Vᵀ - Δ), dQ += dS·K·scale
    where Δ = rowsum(dO*O) (precomputed). All accumulation in fp32 VMEM."""
    t = pl.program_id(2)
    q, k_blk, v_blk, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    block_q = q.shape[0]
    block_kv = k_blk.shape[0]

    @pl.when(t == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = pl.program_id(1) * block_q
    live, mask = _tile_geometry(q_start, t * block_kv, block_q, block_kv,
                                sk, sq, causal)

    @pl.when(live)
    def _accumulate():
        s = _tile_scores(q, k_blk, scale, precision)
        # mask FIRST (never rely on exp of a masked sentinel: fully-masked
        # rows carry lse ~ NEG_INF and exp(s - lse) would overflow)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 precision=precision,
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(ds.astype(k_blk.dtype), k_blk,
                                         (((1,), (0,)), ((), ())),
                                         precision=precision,
                                         preferred_element_type=jnp.float32)

    @pl.when(t == nkv - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, nq: int, sk: int,
                          sq: int, causal: bool, scale: float, precision):
    """dK/dV program: grid (batch·head, kv-block, q-block), q innermost.
    dV += Pᵀ·dO ; dK += dSᵀ·Q·scale. Zero-padded dO rows contribute exactly
    zero (their Δ is also zero), so sq padding needs no special casing."""
    j = pl.program_id(2)
    q, k_blk, v_blk, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    block_q = q.shape[0]
    block_kv = k_blk.shape[0]

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    kv_start = pl.program_id(1) * block_kv
    # causal skip: a q tile strictly left of this kv tile's diagonal band
    # (q_max + offset < kv_start) contributes nothing to dK/dV
    live, mask = _tile_geometry(j * block_q, kv_start, block_q, block_kv,
                                sk, sq, causal)

    @pl.when(live)
    def _accumulate():
        s = _tile_scores(q, k_blk, scale, precision)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         precision=precision,
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 precision=precision,
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         precision=precision,
                                         preferred_element_type=jnp.float32)

    @pl.when(j == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, *, causal, block_q, block_kv, scale,
                    interpret):
    """Pallas flash backward: two sequential-grid kernels (dQ over kv tiles;
    dK/dV over q tiles), FlashAttention-2 math — P is recomputed from the
    saved logsumexp, never materialised in HBM."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, sk)
    # q-side padding MUST use the forward's block_q: the saved lse is
    # already padded to that length (see _flash_forward). Tile shrinking
    # below only halves, so any smaller tile still divides sq_p evenly.
    pad_q = -sq % block_q
    pad_kv = -sk % block_kv
    sq_p, sk_p = sq + pad_q, sk + pad_kv
    # Scoped-VMEM guard (measured on v5e, 16M limit): the backward kernels
    # hold ~5 (block_q × block_kv) fp32 intermediates; at the tuned
    # 1024×512 tiles the largest geometries overflow marginally — observed
    # "scoped allocation 16.70M > 16.00M" at b·h=64, S=8192, d=64, while
    # b·h=16 at S=8192 and b·h=32 at S=4096 fit. Beyond that measured
    # frontier, halve tiles (kv first) until the working set is safely
    # under the limit; tuned-good configs keep their blocks.
    if b * h * max(sq, sk) >= (1 << 19):
        while block_q * block_kv > 1024 * 256 and block_kv > 128:
            block_kv //= 2
        while block_q * block_kv > 1024 * 256 and block_q > 128:
            block_q //= 2
        pad_kv = -sk % block_kv
        sk_p = sk + pad_kv

    # Δ = rowsum(dO * O), fp32 (a cheap fused elementwise+reduce in XLA)
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    def padq(a):
        return jnp.pad(a, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else a

    def padkv(a):
        return jnp.pad(a, ((0, 0), (0, 0), (0, pad_kv), (0, 0))) if pad_kv else a

    qf = padq(q).reshape(b * h, sq_p, d)
    gf = padq(g).reshape(b * h, sq_p, d)
    kf = padkv(k).reshape(b * h, sk_p, d)
    vf = padkv(v).reshape(b * h, sk_p, d)
    # forward and backward derive sq_p from the same nondiff (block_q, sq),
    # so the saved lse is already padded-length — reshape only
    lse_f = lse.reshape(b * h, sq_p, 1)
    delta_f = (jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q))) if pad_q
               else delta).reshape(b * h, sq_p, 1)

    nq = sq_p // block_q
    nkv = sk_p // block_kv
    prec = get_precision()

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, nkv=nkv, sk=sk, sq=sq,
                          causal=causal, scale=scale, precision=prec),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        grid=(b * h, nq, nkv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, t: (i, t, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, t: (i, t, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, t: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse_f, delta_f)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, nq=nq, sk=sk, sq=sq,
                          causal=causal, scale=scale, precision=prec),
        out_shape=[jax.ShapeDtypeStruct((b * h, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk_p, d), v.dtype)],
        grid=(b * h, nkv, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, t, j: (i, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, t, j: (i, t, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, t, j: (i, t, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, t, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, t, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, t, j: (i, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, d), lambda i, t, j: (i, t, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, t, j: (i, t, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_kv, d), jnp.float32)] * 2,
        interpret=interpret,
    )(qf, kf, vf, gf, lse_f, delta_f)

    unflat = lambda a, s_p, s: a.reshape(b, h, s_p, d)[:, :, :s]
    return unflat(dq, sq_p, sq), unflat(dk, sk_p, sk), unflat(dv, sk_p, sk)


def _flash_geometry_safe(b: int, h: int, sq: int, sk: int, d: int) -> bool:
    """Can the Pallas backward kernels run this geometry without VMEM
    overflow? Mosaic lane-pads the trailing head dim to 128; for d >= 32 the
    blocked pipeline streams tiles and any length fits, but at very small
    head dims (measured: d=16, S=8192, b·h=16 on v5e) Mosaic falls back to a
    layout that materialises whole lane-padded (b·h, S, 128) operands in
    VMEM — "scoped allocation exceeded 16M" at compile time. Gate on the
    padded whole-operand footprint with a safety margin so those shapes take
    the numerically-equivalent blockwise path instead of failing to
    compile."""
    if d >= 32:
        return True
    padded_bytes = b * h * max(sq, sk) * 128 * 4
    return padded_bytes <= 12 * 2**20


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, block_q, block_kv, scale, interpret):
    out, _ = _flash_forward(q, k, v, causal=causal, block_q=block_q,
                            block_kv=block_kv, scale=scale,
                            interpret=interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_kv, scale, interpret):
    out, lse = _flash_forward(q, k, v, causal=causal, block_q=block_q,
                              block_kv=block_kv, scale=scale,
                              interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_kv, scale, interpret, res, g):
    # Pallas flash backward (dq/dk/dv kernels) — replaces the r2
    # recompute-through-blockwise VJP (VERDICT r2 #5): the probability matrix
    # is rebuilt tile-by-tile from the saved logsumexp instead of re-running
    # the whole forward online-softmax scan.
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal=causal,
                           block_q=block_q, block_kv=block_kv, scale=scale,
                           interpret=interpret)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, block_q: int = 1024,
                    block_kv: int = 512, scale: Optional[float] = None,
                    interpret: Optional[bool] = None,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """Pallas flash-attention forward (online softmax, scores stay in VMEM),
    differentiable via Pallas dq/dk/dv backward kernels (FlashAttention-2
    math: probabilities rebuilt per tile from the saved O + logsumexp
    residuals — see :func:`_flash_backward`). Causal-only masking in the kernel
    (see :func:`blockwise_attention` docstring); ``mask`` routes to the
    blockwise path. Falls back to :func:`blockwise_attention` — numerically
    equivalent, same memory profile — when Pallas is unavailable *or* the
    backend is not TPU; pass ``interpret=True`` explicitly to force the
    (slow) Pallas interpreter off-TPU for kernel tests.

    Default block sizes are the measured v5e optimum (causal S=4096 b4·h8·
    d64 sweep: q1024/kv512 = 7.35 TFLOP/s vs 6.22 for the XLA blockwise scan
    and 5.46 for the previous 256/256 blocks); both are clamped to the
    sequence length, so short sequences are unaffected.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if mask is not None:
        # the Pallas kernel is causal-only; arbitrary masks take the
        # numerically-equivalent blockwise path (same memory profile)
        return blockwise_attention(q, k, v, causal=causal,
                                   block_kv=block_kv, scale=scale, mask=mask)
    if not _HAVE_PALLAS:
        if interpret:
            raise RuntimeError(
                "interpret=True requested but Pallas is unavailable in this "
                "jax build — cannot run the Pallas kernel")
        return blockwise_attention(q, k, v, causal=causal,
                                   block_kv=block_kv, scale=scale)
    if interpret is None and jax.default_backend() != "tpu":
        return blockwise_attention(q, k, v, causal=causal,
                                   block_kv=block_kv, scale=scale)
    b, h, sq, _ = q.shape
    if not interpret and not _flash_geometry_safe(b, h, sq, k.shape[2],
                                                  q.shape[-1]):
        # tiny head dims at long S overflow VMEM in the Pallas backward
        # (see _flash_geometry_safe) — auto-fallback, same math. The limit
        # is a Mosaic TPU-lowering property, so an explicit interpret=True
        # (kernel debugging) bypasses the gate.
        return blockwise_attention(q, k, v, causal=causal,
                                   block_kv=block_kv, scale=scale)
    return _flash_attention(q, k, v, causal, block_q, block_kv, float(scale),
                            bool(interpret))
