"""Loss functions — value and explicit gradient, matching reference reductions.

Reference equivalent: the six Loss classes + kernels
(``include/nn/loss.hpp:59-401``, ``src/nn/loss_impl/cpu/loss_ops.cpp``,
``cuda/loss_ops.cu``). Semantics reproduced exactly:

- targets are one-hot (or dense regression targets), same as the reference's
  data loaders produce;
- classification losses reduce as mean over the batch; regression losses as
  mean over all elements (loss_ops.cpp: ``/ batch_size`` vs ``/ total_size``);
- each loss exposes ``*_grad`` with the same scaling the reference's
  ``compute_gradient`` kernels apply (e.g. softmax-CE grad =
  ``(softmax - target)/batch``) so pipeline coordinators can inject the initial
  backward tensor exactly like the reference does
  (``sync_pipeline_coordinator.cpp:144-156``).

In the single-device trainer the gradient versions are unused — autodiff
differentiates the loss value — but they are tested against autodiff.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp


def _f32(a: jax.Array) -> jax.Array:
    """Upcast to at-least-fp32 (fp64 inputs stay fp64 — the fp64 precision
    mode must not lose bits at the loss boundary)."""
    a = jnp.asarray(a)
    if jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != jnp.float64:
        return a.astype(jnp.float32)
    return a


def upcast_logits(logits: jax.Array) -> jax.Array:
    """Model outputs -> loss/metric dtype: fp32 for fp32/bf16 activations,
    fp64 preserved (the fp64 mode must not quantize the loss boundary).
    The canonical cast for every trainer/eval path."""
    return _f32(logits)


def _loss_fp32(fn):
    """Loss math always runs in fp32: under the bf16 mixed-precision mode
    (core/precision.py) models emit bf16 predictions, and logsumexp/softmax
    in bf16 costs real accuracy. Every consumer (trainer, pipeline
    coordinators, user code calling get_loss) gets the fp32 boundary here,
    at the loss itself."""
    @functools.wraps(fn)
    def wrapped(pred, targets, *args, **kw):
        return fn(_f32(pred), _f32(targets), *args, **kw)
    return wrapped


def _grad_fp32(fn):
    """Gradient twins compute in fp32 but cast the result back to the
    prediction dtype, so a pipeline backward seed matches the stage's
    compute dtype (the coordinator feeds it straight into a vjp)."""
    @functools.wraps(fn)
    def wrapped(pred, targets, *args, **kw):
        out = fn(_f32(pred), _f32(targets), *args, **kw)
        return out.astype(jnp.asarray(pred).dtype)
    return wrapped


# ---------------- classification ----------------

@_loss_fp32
def cross_entropy(probs: jax.Array, targets: jax.Array, eps: float = 1e-15) -> jax.Array:
    """CE over probability inputs, clamped to [eps, 1-eps]
    (reference ``CrossEntropyLoss``, loss.hpp:59; eps 1e-15)."""
    p = jnp.clip(probs, eps, 1.0 - eps)
    per_sample = -jnp.sum(targets * jnp.log(p), axis=-1)
    return jnp.mean(per_sample)


@_grad_fp32
def cross_entropy_grad(probs: jax.Array, targets: jax.Array) -> jax.Array:
    """Reference grad kernel is ``(pred - target)/batch``
    (loss_ops.cpp compute_crossentropy_gradient). NOTE: this is the *fused*
    softmax-CE shortcut, not ∂loss/∂probs — it already folds in the softmax
    jacobian, assuming the producing layer's softmax backward is treated as
    identity (which is how the reference wires it). Kept verbatim for pipeline
    parity; single-device training autodiffs the loss value instead."""
    return (probs - targets) / probs.shape[0]


@_loss_fp32
def softmax_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Stable fused softmax+CE over logits (reference
    ``SoftmaxCrossEntropyLoss``, loss.hpp:122): loss = logsumexp(x) - x[target],
    mean over batch."""
    lse = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    per_sample = jnp.sum(targets * (lse - logits), axis=-1)
    return jnp.mean(per_sample)


@_grad_fp32
def softmax_cross_entropy_grad(logits: jax.Array, targets: jax.Array) -> jax.Array:
    return (jax.nn.softmax(logits, axis=-1) - targets) / logits.shape[0]


@_loss_fp32
def log_softmax_cross_entropy(log_probs: jax.Array, targets: jax.Array) -> jax.Array:
    """CE over log-probability inputs (reference ``LogSoftmaxCrossEntropyLoss``,
    loss.hpp:180) — the model's last layer applies log-softmax."""
    per_sample = -jnp.sum(targets * log_probs, axis=-1)
    return jnp.mean(per_sample)


@_grad_fp32
def log_softmax_cross_entropy_grad(log_probs: jax.Array, targets: jax.Array) -> jax.Array:
    """Fused like the reference kernel: ``(exp(logp) - t)/batch`` equals the
    end-to-end gradient at the *logits* feeding the log-softmax — i.e. the
    log-softmax jacobian is folded in (see ``cross_entropy_grad`` note)."""
    return (jnp.exp(log_probs) - targets) / log_probs.shape[0]


# ---------------- regression ----------------

@_loss_fp32
def mse_loss(pred: jax.Array, targets: jax.Array) -> jax.Array:
    return jnp.mean(jnp.square(pred - targets))


@_grad_fp32
def mse_grad(pred: jax.Array, targets: jax.Array) -> jax.Array:
    return 2.0 * (pred - targets) / pred.size


@_loss_fp32
def mae_loss(pred: jax.Array, targets: jax.Array) -> jax.Array:
    return jnp.mean(jnp.abs(pred - targets))


@_grad_fp32
def mae_grad(pred: jax.Array, targets: jax.Array) -> jax.Array:
    return jnp.sign(pred - targets) / pred.size


@_loss_fp32
def huber_loss(pred: jax.Array, targets: jax.Array, delta: float = 1.0) -> jax.Array:
    """Huber with delta 1.0 default (reference loss.hpp:345)."""
    d = pred - targets
    a = jnp.abs(d)
    quad = 0.5 * jnp.square(d)
    lin = delta * (a - 0.5 * delta)
    return jnp.mean(jnp.where(a <= delta, quad, lin))


@_grad_fp32
def huber_grad(pred: jax.Array, targets: jax.Array, delta: float = 1.0) -> jax.Array:
    d = pred - targets
    g = jnp.where(jnp.abs(d) <= delta, d, delta * jnp.sign(d))
    return g / pred.size


# ---------------- registry (reference LossFactory, loss.hpp:403) ----------------

LossFn = Callable[[jax.Array, jax.Array], jax.Array]

LOSSES: Dict[str, Tuple[LossFn, LossFn]] = {
    "crossentropy": (cross_entropy, cross_entropy_grad),
    "softmax_crossentropy": (softmax_cross_entropy, softmax_cross_entropy_grad),
    "logsoftmax_crossentropy": (log_softmax_cross_entropy, log_softmax_cross_entropy_grad),
    "mse": (mse_loss, mse_grad),
    "mae": (mae_loss, mae_grad),
    "huber": (huber_loss, huber_grad),
}


def get_loss(name: str) -> LossFn:
    try:
        return LOSSES[name.lower()][0]
    except KeyError:
        raise ValueError(f"unknown loss {name!r}; known: {sorted(LOSSES)}") from None


def get_loss_grad(name: str) -> LossFn:
    return LOSSES[name.lower()][1]
