"""Pooling ops.

Reference equivalent: MaxPool/AvgPool forward + backward-scatter kernels with
an argmax-index cache per microbatch (``src/nn/layers_impl/cpu/maxpool_ops.cpp``,
``avgpool_ops.cpp`` and CUDA twins; layers ``maxpool2d_layer.tpp:264``,
``avgpool2d_layer.tpp:253``).

On TPU both are ``lax.reduce_window`` — XLA generates the backward scatter from
the autodiff transpose rule, so no argmax cache is needed (its job is done by
the VJP residuals).
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

IntOrPair = Union[int, Tuple[int, int]]


def _pair(v: IntOrPair) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else (int(v[0]), int(v[1]))


def _window(kernel, stride, padding, data_format):
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    ph, pw = _pair(padding)
    if data_format == "NCHW":
        dims = (1, 1, kh, kw)
        strides = (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    elif data_format == "NHWC":
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
    else:
        raise ValueError(f"unsupported data_format {data_format!r}")
    return dims, strides, pads


def max_pool2d(
    x: jax.Array,
    kernel: IntOrPair,
    stride: IntOrPair | None = None,
    padding: IntOrPair = 0,
    *,
    data_format: str = "NCHW",
) -> jax.Array:
    dims, strides, pads = _window(kernel, stride, padding, data_format)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, init, lax.max, dims, strides, pads)


def avg_pool2d(
    x: jax.Array,
    kernel: IntOrPair,
    stride: IntOrPair | None = None,
    padding: IntOrPair = 0,
    *,
    data_format: str = "NCHW",
    count_include_pad: bool = True,
) -> jax.Array:
    """Average pool. The reference divides by the full window size including
    padded cells (``avgpool_ops.cpp``), i.e. ``count_include_pad=True`` — keep
    that default for parity."""
    dims, strides, pads = _window(kernel, stride, padding, data_format)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    kh, kw = _pair(kernel)
    if count_include_pad:
        return summed / (kh * kw)
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
    return summed / counts


def global_avg_pool2d(x: jax.Array, *, data_format: str = "NCHW") -> jax.Array:
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    return jnp.mean(x, axis=axes, keepdims=True)


def pool_output_shape(
    input_hw: Tuple[int, int],
    kernel: IntOrPair,
    stride: IntOrPair | None = None,
    padding: IntOrPair = 0,
) -> Tuple[int, int]:
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    ph, pw = _pair(padding)
    return ((input_hw[0] + 2 * ph - kh) // sh + 1, (input_hw[1] + 2 * pw - kw) // sw + 1)
