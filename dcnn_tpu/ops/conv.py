"""2-D convolution ops.

Reference equivalent: the im2col→GEMM→layout-fix forward and the three
backward kernels (weight-grad GEMM, input-grad GEMM→col2im, bias reduce) in
``include/nn/layers_impl/conv2d_layer.tpp:140-241`` +
``src/nn/layers_impl/{cpu,cuda}/conv2d_ops.*``, and the cuDNN fast path
(``cudnn_conv2d_ops.cu``).

On TPU there is no im2col: ``lax.conv_general_dilated`` lowers directly onto
the MXU and XLA picks the tiling, so the whole reference kernel family
collapses to one primitive per direction. Explicit ``conv2d_weight_grad`` /
``conv2d_input_grad`` are still exported so kernel-level tests can check each
direction against autodiff (the reference tests each CUDA kernel against a
naive CPU reference the same way, SURVEY.md §4.2).

Weights are stored OIHW (reference layout) regardless of activation layout;
activations may be NCHW (API default, reference parity) or NHWC (TPU-preferred
tiling, the fast path).
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..core.precision import get_precision

IntOrPair = Union[int, Tuple[int, int], Sequence[int]]


def _pair(v: IntOrPair) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    a, b = v
    return (int(a), int(b))


def _dims(data_format: str) -> lax.ConvDimensionNumbers:
    if data_format == "NCHW":
        return lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1), ("NCHW", "OIHW", "NCHW"))
    if data_format == "NHWC":
        return lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1), ("NHWC", "OIHW", "NHWC"))
    raise ValueError(f"unsupported data_format {data_format!r}")


def conv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    *,
    stride: IntOrPair = 1,
    padding: IntOrPair = 0,
    data_format: str = "NCHW",
) -> jax.Array:
    """Forward conv. ``w`` is OIHW; ``padding`` is symmetric int(s) like the
    reference (conv2d_layer.hpp pad_h/pad_w), not a string."""
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    out = lax.conv_general_dilated(
        x, w,
        window_strides=(sh, sw),
        padding=((ph, ph), (pw, pw)),
        dimension_numbers=_dims(data_format),
        precision=get_precision(),
    )
    if b is not None:
        if data_format == "NCHW":
            out = out + b.reshape(1, -1, 1, 1)
        else:
            out = out + b.reshape(1, 1, 1, -1)
    return out


def conv2d_int8(
    x_q: jax.Array,
    w_q: jax.Array,
    *,
    stride: IntOrPair = 1,
    padding: IntOrPair = 0,
    data_format: str = "NCHW",
) -> jax.Array:
    """int8 × int8 → int32 convolution on the MXU's int8 path (~2× the bf16
    peak on v5e; measured in ``benchmarks/bench_int8.py``). Same geometry
    contract as :func:`conv2d` (OIHW weights, symmetric int padding); the
    caller owns the scales — dequantization is a per-channel multiply on the
    int32 output (``nn/quantize.py``). No ``precision`` arg: precision
    selects float MXU passes and does not apply to integer convs."""
    if x_q.dtype != jnp.int8 or w_q.dtype != jnp.int8:
        raise TypeError(f"conv2d_int8 expects int8 operands, got "
                        f"{x_q.dtype}/{w_q.dtype}")
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    return lax.conv_general_dilated(
        x_q, w_q,
        window_strides=(sh, sw),
        padding=((ph, ph), (pw, pw)),
        dimension_numbers=_dims(data_format),
        preferred_element_type=jnp.int32,
    )


def conv2d_weight_grad(
    x: jax.Array,
    grad_out: jax.Array,
    kernel_hw: Tuple[int, int],
    *,
    stride: IntOrPair = 1,
    padding: IntOrPair = 0,
    data_format: str = "NCHW",
) -> jax.Array:
    """dL/dW — reference ``compute_weight_gradients``
    (``src/nn/layers_impl/cpu/conv2d_ops.cpp``). Implemented via the
    transpose rule of the forward conv so numerics match autodiff exactly."""
    kh, kw = kernel_hw
    c_axis = 1 if data_format == "NCHW" else 3
    cin = x.shape[c_axis]
    cout = grad_out.shape[c_axis]
    w_shape = (cout, cin, kh, kw)
    _, vjp = jax.vjp(
        lambda w: conv2d(x, w, None, stride=stride, padding=padding, data_format=data_format),
        jnp.zeros(w_shape, x.dtype),
    )
    return vjp(grad_out)[0]


def conv2d_input_grad(
    w: jax.Array,
    grad_out: jax.Array,
    input_shape: Tuple[int, ...],
    *,
    stride: IntOrPair = 1,
    padding: IntOrPair = 0,
    data_format: str = "NCHW",
) -> jax.Array:
    """dL/dX — reference ``compute_input_gradients`` (GEMM→col2im)."""
    _, vjp = jax.vjp(
        lambda x: conv2d(x, w, None, stride=stride, padding=padding, data_format=data_format),
        jnp.zeros(input_shape, w.dtype),
    )
    return vjp(grad_out)[0]


def conv2d_bias_grad(grad_out: jax.Array, *, data_format: str = "NCHW") -> jax.Array:
    """dL/db — reference ``compute_bias_gradients`` (reduce over N,H,W)."""
    axes = (0, 2, 3) if data_format == "NCHW" else (0, 1, 2)
    return jnp.sum(grad_out, axis=axes)


def conv2d_output_shape(
    input_hw: Tuple[int, int],
    kernel_hw: Tuple[int, int],
    stride: IntOrPair = 1,
    padding: IntOrPair = 0,
) -> Tuple[int, int]:
    """Spatial output size, same formula as the reference
    ``compute_output_shape`` (conv2d_layer.hpp)."""
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    h = (input_hw[0] + 2 * ph - kernel_hw[0]) // sh + 1
    w = (input_hw[1] + 2 * pw - kernel_hw[1]) // sw + 1
    return (h, w)
