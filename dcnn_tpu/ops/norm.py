"""Normalization ops.

Reference equivalent: fused BatchNorm forward (mean/inv-std/running-stat
update/normalize), fused backward, inference path
(``src/nn/layers_impl/cpu/batchnorm_ops.cpp``, ``cuda/batchnorm_ops.cu``,
layer ``batchnorm_layer.tpp``) and the per-group GroupNorm twins
(``groupnorm_ops.cpp``/``.cu``). Defaults for parity: eps 1e-5, BN momentum
0.1 (``batchnorm_layer.hpp:67``, ``groupnorm_layer.hpp:56``).

XLA fuses the normalize-scale-shift chain into neighboring ops, so these are
plain jnp expressions; backward comes from autodiff (numerically the same
reduction tree as the reference's hand-fused backward).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def batch_norm(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    *,
    training: bool,
    momentum: float = 0.1,
    eps: float = 1e-5,
    data_format: str = "NCHW",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (y, new_running_mean, new_running_var).

    Training mode normalizes with batch statistics over (N,H,W) and updates
    running stats as ``running = (1-momentum)*running + momentum*batch``
    (reference semantics: batchnorm_layer.tpp, momentum 0.1). Eval mode uses
    running stats. The reference computes BN per microbatch independently
    (SURVEY.md §7 hard part 4); callers get that behavior for free by invoking
    this once per microbatch.
    """
    c_axis = 1 if data_format == "NCHW" else 3
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]

    # Statistics always accumulate in at-least-fp32, whatever the activation
    # dtype — with bf16 activations (mixed-precision mode) a bf16 mean/var
    # over N*H*W elements would lose most of its mantissa; fp64 inputs (the
    # fp64 mode) keep full double statistics. XLA fuses the upcast into the
    # reduction, so no widened copy of x is materialized.
    stat_dt = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
    xf = x.astype(stat_dt)
    if training:
        # ONE-pass statistics: sum and sum-of-squares reduce together, so XLA
        # emits a single multi-output reduction over x. The naive
        # mean-then-var form costs two full HBM reads; measured on v5e
        # [1024,64,64,64] bf16: 758 GB/s effective (93% HBM peak) vs
        # 373 GB/s for mean/var — 2.0x. (A hand-written Pallas one-pass
        # stats kernel was also measured and LOSES to this: 378 GB/s best —
        # same conclusion as the r2 epilogue-fusion study: restructure for
        # XLA, don't replace it.)
        #
        # Cancellation control: raw E[x2]-mean^2 loses precision when
        # |mean| >> std (the reference's two-pass kernel is immune,
        # batchnorm_ops.cpp:62-85, at 2x the HBM cost). The sums are
        # therefore taken over x - running_mean: the pivot is an *independent
        # input* (not derived from x), so the subtract fuses into the same
        # single reduction pass — measured identical to the raw form
        # (1.43 ms vs 1.42 on the shape above), while any x-derived pivot
        # (e.g. first-sample mean) forces XLA to materialize the centered
        # tensor (3x slower, measured). Once running_mean tracks the batch
        # mean (~10 steps at momentum 0.1) the residual cancellation term
        # ((mean-rm)/std)^2 is O(1) and fp32 error is ~1e-7 relative.
        # Residual caveat: during the first few steps on inputs with
        # |mean|/std > ~1e3 the variance is imprecise (clamped >= 0, outputs
        # finite) — the same regime cuDNN's single-pass BN accepts; steady
        # state matches the reference's stable kernel.
        n = x.size // x.shape[c_axis]
        pivot = running_mean.astype(stat_dt)
        xs = xf - pivot.reshape(shape)
        s1 = jnp.sum(xs, axis=reduce_axes)
        s2 = jnp.sum(xs * xs, axis=reduce_axes)
        mean_c = s1 / n
        var = jnp.maximum(s2 / n - mean_c * mean_c, 0.0)
        mean = mean_c + pivot
        unbiased = var * (n / max(n - 1, 1))
        new_mean = ((1 - momentum) * running_mean + momentum * mean).astype(running_mean.dtype)
        new_var = ((1 - momentum) * running_var + momentum * unbiased).astype(running_var.dtype)
    else:
        mean, var = (running_mean.astype(stat_dt),
                     running_var.astype(stat_dt))
        new_mean, new_var = running_mean, running_var

    inv = jax.lax.rsqrt(var + eps)
    y = (xf - mean.reshape(shape)) * inv.reshape(shape)
    y = y * gamma.astype(stat_dt).reshape(shape) + beta.astype(stat_dt).reshape(shape)
    return y.astype(x.dtype), new_mean, new_var


def group_norm(
    x: jax.Array,
    gamma: Optional[jax.Array],
    beta: Optional[jax.Array],
    num_groups: int,
    *,
    eps: float = 1e-5,
    data_format: str = "NCHW",
) -> jax.Array:
    """Per-sample, per-group normalization over (C/G, H, W)
    (reference ``groupnorm_ops.cpp``; eps 1e-5)."""
    if data_format == "NHWC":
        x_nchw = jnp.transpose(x, (0, 3, 1, 2))
        y = group_norm(x_nchw, gamma, beta, num_groups, eps=eps, data_format="NCHW")
        return jnp.transpose(y, (0, 2, 3, 1))

    n, c, h, w = x.shape
    if c % num_groups != 0:
        raise ValueError(f"channels {c} not divisible by groups {num_groups}")
    stat_dt = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
    xg = x.astype(stat_dt).reshape(n, num_groups, c // num_groups, h, w)
    # GroupNorm keeps the stable two-pass mean/var: unlike BN there is no
    # independent pivot (running stats) to center the one-pass sum/sumsq on,
    # and an x-derived pivot forces XLA to materialize the centered tensor
    # (measured 3x slower than two-pass on v5e — see batch_norm's note).
    mean = jnp.mean(xg, axis=(2, 3, 4), keepdims=True)
    var = jnp.var(xg, axis=(2, 3, 4), keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(n, c, h, w)
    if gamma is not None:
        y = y * gamma.astype(stat_dt).reshape(1, c, 1, 1)
    if beta is not None:
        y = y + beta.astype(stat_dt).reshape(1, c, 1, 1)
    return y.astype(x.dtype)
