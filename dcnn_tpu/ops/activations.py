"""Activation functions.

Reference equivalent: the 7 activation kernel families (apply + in-place
gradient, CPU+CUDA pairs) under ``src/nn/activations_impl/`` with class
wrappers and an ``ActivationFactory`` (``include/nn/activations.hpp``,
``base_activation.hpp:13-23``). Defaults for parity: LeakyReLU slope 0.01,
ELU alpha 1.0 (``activations_impl/leaky_relu.hpp:17``, ``elu.hpp:17``).

Gradients come from autodiff; the string registry replaces the factory so JSON
model configs can name activations the same way the reference does.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


def relu(x: jax.Array) -> jax.Array:
    return jnp.maximum(x, 0)


def leaky_relu(x: jax.Array, negative_slope: float = 0.01) -> jax.Array:
    return jnp.where(x >= 0, x, negative_slope * x)


def elu(x: jax.Array, alpha: float = 1.0) -> jax.Array:
    safe = jnp.minimum(x, 0.0)  # avoid overflow in exp for large positives
    return jnp.where(x > 0, x, alpha * (jnp.exp(safe) - 1.0))


def sigmoid(x: jax.Array) -> jax.Array:
    return jax.nn.sigmoid(x)


def tanh(x: jax.Array) -> jax.Array:
    return jnp.tanh(x)


def softmax(x: jax.Array, axis: int = -1) -> jax.Array:
    """Numerically-stable softmax (reference subtracts the row max the same
    way, ``softmax_kernels.cpp``)."""
    return jax.nn.softmax(x, axis=axis)


def linear(x: jax.Array) -> jax.Array:
    return x


ACTIVATIONS: Dict[str, Callable] = {
    "relu": relu,
    "leaky_relu": leaky_relu,
    "elu": elu,
    "sigmoid": sigmoid,
    "tanh": tanh,
    "softmax": softmax,
    "linear": linear,
    "none": linear,
}


def apply_activation(name: Optional[str], x: jax.Array, **kwargs) -> jax.Array:
    """String-keyed dispatch (reference ``ActivationFactory``,
    ``include/nn/activations.hpp``)."""
    if name is None:
        return x
    try:
        fn = ACTIVATIONS[name.lower()]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(ACTIVATIONS)}") from None
    return fn(x, **kwargs)
