"""Elementwise / reduction op set, name-for-name with the reference.

Reference: ``include/ops/ops.hpp:18-945`` — add, sub, mul, div, fused
multiply-adds, scalar variants, set/axpy/sqrt/rsqrt/rcp/abs/min/max/
scalar_max/clamp/equal/greater/copy/zero, reductions (sum, dot_product,
sum_squared_diff, norm_squared), RNG fills, transpose_2d, nchw↔cnhw layout
moves. There each op hand-dispatches to an AVX2 or CUDA kernel and returns a
``Task``; here each is a pure function that XLA fuses — keeping the names
makes the component inventory auditable and gives kernel-level tests a target.

All functions are jit-safe and dtype-preserving.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


# -- binary elementwise (ops.hpp:18-120) --
def add(a, b):
    return a + b


def sub(a, b):
    return a - b


def mul(a, b):
    return a * b


def div(a, b):
    return a / b


# -- fused multiply ops (ops.hpp; AVX2 fmadd/fmsub/fnmadd kernels) --
def fmadd(a, b, c):
    """a*b + c."""
    return a * b + c


def fmsub(a, b, c):
    """a*b - c."""
    return a * b - c


def fnmadd(a, b, c):
    """-(a*b) + c."""
    return c - a * b


# -- scalar variants --
def add_scalar(a, s):
    return a + s


def sub_scalar(a, s):
    return a - s


def mul_scalar(a, s):
    return a * s


def div_scalar(a, s):
    return a / s


def set_scalar(a, s):
    return jnp.full_like(a, s)


def mul_add_scalar(a, mul_s, add_s):
    """a*mul_s + add_s."""
    return a * mul_s + add_s


def sub_mul_scalar(a, sub_s, mul_s):
    """(a - sub_s) * mul_s."""
    return (a - sub_s) * mul_s


def axpy(alpha, x, y):
    """alpha*x + y (BLAS axpy; reference ops.hpp axpy)."""
    return alpha * x + y


# -- unary --
def sqrt(a):
    return jnp.sqrt(a)


def rsqrt(a):
    return jax.lax.rsqrt(a)


def rcp(a):
    return 1.0 / a


def abs(a):  # noqa: A001 - name-for-name with reference
    return jnp.abs(a)


def copy(a):
    return jnp.asarray(a).copy()


def zero(a):
    return jnp.zeros_like(a)


# -- binary comparisons / clamping --
def min(a, b):  # noqa: A001
    return jnp.minimum(a, b)


def max(a, b):  # noqa: A001
    return jnp.maximum(a, b)


def scalar_max(a, s):
    return jnp.maximum(a, s)


def clamp(a, lo, hi):
    return jnp.clip(a, lo, hi)


def equal(a, b):
    return (a == b).astype(a.dtype)


def greater(a, b):
    return (a > b).astype(a.dtype)


# -- reductions (ops.hpp sum/dot_product/sum_squared_diff/norm_squared) --
def sum(a):  # noqa: A001
    return jnp.sum(a)


def dot_product(a, b):
    return jnp.vdot(a, b)


def sum_squared_diff(a, b):
    d = a - b
    return jnp.sum(d * d)


def norm_squared(a):
    return jnp.sum(a * a)


# -- RNG fills (ops.hpp:809-860); explicit PRNG keys, the JAX way --
def fill_random_uniform(key, shape, lo, hi, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype=dtype, minval=lo, maxval=hi)


def fill_random_normal(key, shape, mean=0.0, std=1.0, dtype=jnp.float32):
    return mean + std * jax.random.normal(key, shape, dtype=dtype)


# -- layout ops (ops.hpp:890-945) --
def transpose_2d(a):
    return jnp.swapaxes(a, -1, -2)


def nchw_to_cnhw(a):
    """(N,C,H,W) → (C,N,H,W) — the reference's GEMM-output layout fix
    (ops.hpp:890)."""
    return jnp.transpose(a, (1, 0, 2, 3))


def cnhw_to_nchw(a):
    return jnp.transpose(a, (1, 0, 2, 3))


def nchw_to_nhwc(a):
    return jnp.transpose(a, (0, 2, 3, 1))


def nhwc_to_nchw(a):
    return jnp.transpose(a, (0, 3, 1, 2))
