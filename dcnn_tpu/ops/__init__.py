"""Functional op layer — the TPU analog of the reference kernel layer.

Reference equivalent: ``include/ops/ops.hpp`` (~35 elementwise/reduction ops
dispatched CPU-vs-CUDA, each returning an async ``Task``) plus the per-layer
kernel files under ``src/nn/layers_impl/{cpu,cuda}/`` (SURVEY.md §2.2).

On TPU every op here is a pure jittable function: XLA fuses elementwise chains
into matmul/conv epilogues, so the reference's hand-written AVX2/CUDA kernels
collapse to ``jnp`` expressions, and its Task/Flow async model collapses to
XLA's async dispatch. Pallas kernels live in ``dcnn_tpu.ops.pallas`` and are
used only where fusion measurably falls short.
"""

from . import elementwise
from .activations import (
    elu, leaky_relu, linear, relu, sigmoid, softmax, tanh,
    ACTIVATIONS, apply_activation,
)
from . import quant
from .conv import conv2d, conv2d_input_grad, conv2d_int8, conv2d_weight_grad
from .pool import avg_pool2d, max_pool2d
from .norm import batch_norm, group_norm
from .losses import (
    cross_entropy, softmax_cross_entropy, log_softmax_cross_entropy,
    mse_loss, mae_loss, huber_loss, LOSSES,
)
from .metrics import accuracy, correct_count
from .attention import attention, blockwise_attention, flash_attention

__all__ = [
    "elementwise",
    "relu", "leaky_relu", "elu", "sigmoid", "tanh", "softmax", "linear",
    "ACTIVATIONS", "apply_activation",
    "quant",
    "conv2d", "conv2d_input_grad", "conv2d_int8", "conv2d_weight_grad",
    "max_pool2d", "avg_pool2d",
    "batch_norm", "group_norm",
    "cross_entropy", "softmax_cross_entropy", "log_softmax_cross_entropy",
    "mse_loss", "mae_loss", "huber_loss", "LOSSES",
    "accuracy", "correct_count",
    "attention", "blockwise_attention", "flash_attention",
]
