"""Pallas TPU kernels.

Policy (SURVEY.md §7 stage 4): XLA's fusion already covers the reference's
hand-written kernel inventory (elementwise chains fuse into conv/matmul
epilogues; reductions fuse with normalize steps), so Pallas is reserved for
ops where measured profiles show fusion falling short. Kernels here must
match their XLA-composed references bit-for-bit in tests (run in interpret
mode on CPU, compiled on TPU).

Current kernels:
- ``fused_scale_bias_relu`` — y = max(x*scale + bias, 0) per channel, the
  BN-inference + ReLU epilogue (reference fuses this on CPU/CUDA in
  ``batchnorm_ops`` + activation kernels).
"""

from .fused import fused_scale_bias_relu

__all__ = ["fused_scale_bias_relu"]
