"""Fused per-channel scale/bias/ReLU Pallas kernel.

The BN-inference epilogue ``y = max(x * scale + bias, 0)`` — the op chain the
reference fuses by hand in its CPU/CUDA batchnorm+activation kernels
(``src/nn/layers_impl/cpu/batchnorm_ops.cpp`` inference path + relu kernel).
XLA usually fuses this too; the kernel exists as the template for the
framework's Pallas surface (grid/block layout, NHWC channel-lane tiling) and
is validated bit-for-bit against the jnp composition in tests.

Measured (v5e, [1024,16,16,256] fp32, chained-iteration timing): XLA's
automatic fusion reaches 658 GB/s vs 327 GB/s for this kernel — so the
production path deliberately uses the jnp composition and lets XLA fuse;
Pallas earns its keep where XLA can't restructure the computation (see the
flash-attention kernel, which beats the XLA blockwise scan by 18% with tuned
block shapes). This matches SURVEY §7 Stage 4's profile-first doctrine.

Layout: NHWC with C on the lane dimension (128-wide) — the TPU-native choice;
callers in NCHW transpose at the boundary (XLA folds the transpose).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl


def _kernel(x_ref, scale_ref, bias_ref, o_ref):
    o_ref[:] = jnp.maximum(x_ref[:] * scale_ref[:] + bias_ref[:], 0.0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_scale_bias_relu(x: jax.Array, scale: jax.Array, bias: jax.Array,
                          *, interpret: bool | None = None) -> jax.Array:
    """``max(x*scale + bias, 0)`` with scale/bias broadcast over the last
    (channel) axis. ``x``: (..., C); ``scale``/``bias``: (C,)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    orig_shape = x.shape
    c = x.shape[-1]
    x2 = x.reshape(-1, c)
    n = x2.shape[0]
    # row-block the flattened batch; full channel width per block
    block_rows = min(n, 512)
    grid = (pl.cdiv(n, block_rows),)
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((n, c), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
            pl.BlockSpec((c,), lambda i: (0,)),
            pl.BlockSpec((c,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, scale, bias)
    return out.reshape(orig_shape)
