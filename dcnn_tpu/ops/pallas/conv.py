"""Implicit-GEMM Pallas conv kernels (3x3, stride 1, NHWC).

The VERDICT-r3 experiment: ResNet-18's dominant cost is 3x3 stride-1 convs
at 64x64..8x8 spatial, which XLA runs at ~55% MXU while active. This kernel
races XLA's `lax.conv_general_dilated` on exactly that shape class
(reference kernel family: ``src/nn/layers_impl/cuda/conv2d_ops.cu`` +
``include/nn/layers_impl/conv2d_layer.tpp:140-241`` — the hand conv path).

Formulation: one grid step processes a batch tile. The input tile lives in
VMEM; it is zero-padded IN VMEM (vector copy, no HBM pad materialization),
and the 3x3 window becomes 9 static shifted views, each feeding one MXU
matmul of shape (H*W, C) x (C, K) accumulated in fp32 — implicit GEMM with
zero im2col materialization. HBM traffic is exactly x once + out once +
weights once (weights block is revisited, so the pipeline skips its DMA).

An optional fused input epilogue applies per-channel scale/shift + ReLU to
the patch values at load (the BN-normalize + activation of the PREVIOUS
layer, which is what XLA's conv fusions absorb in the profiled step).

Whether this beats XLA is an empirical question the benchmark answers
(`benchmarks/bench_pallas_conv.py`); per the Stage-4 doctrine the winner —
either way — gets recorded in RESULTS.md with numbers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _conv3x3_kernel(x_ref, w_ref, o_ref, *, bt, h, w, cin, cout):
    for b in range(bt):
        xb = x_ref[b]
        xp = jnp.pad(xb, ((1, 1), (1, 1), (0, 0)))
        acc = jnp.zeros((h * w, cout), jnp.float32)
        for kh in range(3):
            for kw in range(3):
                patch = xp[kh:kh + h, kw:kw + w, :].reshape(h * w, cin)
                acc = acc + jnp.dot(patch, w_ref[kh, kw],
                                    preferred_element_type=jnp.float32)
        o_ref[b] = acc.reshape(h, w, cout).astype(o_ref.dtype)


def _conv3x3_bn_kernel(x_ref, w_ref, scale_ref, shift_ref, o_ref, *,
                       bt, h, w, cin, cout):
    """Same implicit GEMM with the previous layer's BN-apply + ReLU fused
    into the input read: patch = relu(x * scale + shift)."""
    scale = scale_ref[:].astype(jnp.float32)
    shift = shift_ref[:].astype(jnp.float32)
    for b in range(bt):
        xb = x_ref[b].astype(jnp.float32)
        xb = jnp.maximum(xb * scale + shift, 0.0).astype(x_ref.dtype)
        xp = jnp.pad(xb, ((1, 1), (1, 1), (0, 0)))
        acc = jnp.zeros((h * w, cout), jnp.float32)
        for kh in range(3):
            for kw in range(3):
                patch = xp[kh:kh + h, kw:kw + w, :].reshape(h * w, cin)
                acc = acc + jnp.dot(patch, w_ref[kh, kw],
                                    preferred_element_type=jnp.float32)
        o_ref[b] = acc.reshape(h, w, cout).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "interpret", "out_dtype"))
def conv3x3_s1(x: jax.Array, w: jax.Array, *, batch_tile: int = 1,
               interpret: bool | None = None, out_dtype=None) -> jax.Array:
    """3x3 stride-1 SAME conv, NHWC. ``x``: (N, H, W, Cin); ``w``:
    (3, 3, Cin, Cout). Returns (N, H, W, Cout)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, h, ww, cin = x.shape
    kh, kw, wcin, cout = w.shape
    if (kh, kw) != (3, 3) or wcin != cin or n % batch_tile:
        raise ValueError(f"conv3x3_s1: bad shapes {x.shape} {w.shape} "
                         f"batch_tile={batch_tile}")
    out_dtype = out_dtype or x.dtype
    kern = functools.partial(_conv3x3_kernel, bt=batch_tile, h=h, w=ww,
                             cin=cin, cout=cout)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, h, ww, cout), out_dtype),
        grid=(n // batch_tile,),
        in_specs=[
            pl.BlockSpec((batch_tile, h, ww, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, cout), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((batch_tile, h, ww, cout),
                               lambda i: (i, 0, 0, 0)),
        interpret=interpret,
    )(x, w)


def _conv3x3_pairs_kernel(x_ref, w2_ref, o_ref, *, bt, h, w, cin, cout):
    """Output-column-pair formulation for narrow Cout: two adjacent output
    columns share 4 input columns, so each kh row becomes ONE matmul of
    (H*W/2, 4C) x (4C, 2K) — N = 2K fills the 128-wide MXU that K=64 alone
    would leave half idle. The block-sparse fused weights cost 4/3 the
    FLOPs; the 2x width utilization nets ~1.5x ceiling on K=64 shapes."""
    # x_ref: (bt, 1, 2, TH+2, W/2+1, C) — one H-tile of the padded even/odd
    # column planes, pre-split and pre-tiled OUTSIDE the kernel (in-kernel
    # pad + sublane-split reshapes compile pathologically slowly in Mosaic,
    # and a full 64x64 image plus the dot temporaries overflows the 16 MB
    # VMEM scope). Pair p needs padded cols 2p..2p+3 = even[p], odd[p],
    # even[p+1], odd[p+1]; the kernel is just static slices + 12 MXU dots.
    half = w // 2
    th = h  # rows in this tile (h == tile height here)
    for b in range(bt):
        ev = x_ref[b, 0, 0]                            # (TH+2, W/2+1, C)
        od = x_ref[b, 0, 1]
        acc = jnp.zeros((th, half, 2 * cout), jnp.float32)
        for kh in range(3):
            cols = (ev[kh:kh + th, 0:half], od[kh:kh + th, 0:half],
                    ev[kh:kh + th, 1:half + 1], od[kh:kh + th, 1:half + 1])
            for j in range(4):
                # 3D dot_general (free dims TH, W/2) — Mosaic flattens them
                acc = acc + jax.lax.dot_general(
                    cols[j], w2_ref[kh, j],
                    dimension_numbers=(((2,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        # (TH, W/2, 2K) == (TH, W, K) row-major — un-paired OUTSIDE (XLA
        # folds the reshape)
        o_ref[b, 0] = acc.astype(o_ref.dtype)


def fuse_pair_weights(w: jax.Array) -> jax.Array:
    """(3, 3, C, K) -> (3, 4, C, 2K) block-sparse fused weights for the
    output-column-pair kernel: window offset j contributes kernel col j to
    the even output (first K lanes, j < 3) and kernel col j-1 to the odd
    output (last K lanes, j >= 1)."""
    _, _, c, k = w.shape
    w2 = jnp.zeros((3, 4, c, 2 * k), w.dtype)
    for kw in range(3):
        w2 = w2.at[:, kw, :, :k].set(w[:, kw])
        w2 = w2.at[:, kw + 1, :, k:].set(w[:, kw])
    return w2


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "h_tile", "interpret",
                                    "out_dtype"))
def conv3x3_s1_pairs(x: jax.Array, w: jax.Array, *, batch_tile: int = 1,
                     h_tile: int | None = None,
                     interpret: bool | None = None,
                     out_dtype=None) -> jax.Array:
    """3x3 stride-1 SAME conv via the output-column-pair implicit GEMM —
    the narrow-Cout (K < 128) specialization. Requires even W."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, h, ww, cin = x.shape
    kh, kw, wcin, cout = w.shape
    if (kh, kw) != (3, 3) or wcin != cin or n % batch_tile or ww % 2:
        raise ValueError(f"conv3x3_s1_pairs: bad shapes {x.shape} {w.shape} "
                         f"batch_tile={batch_tile}")
    out_dtype = out_dtype or x.dtype
    w2 = fuse_pair_weights(w)
    half = ww // 2
    th = h_tile or min(h, 16)
    if h % th:
        raise ValueError(f"h_tile {th} must divide H {h}")
    nt = h // th
    # pad + even/odd column split + overlapped H-tiling as fused XLA
    # relayouts (HBM cost: ~2 extra x passes at (TH+2)/TH inflation — paid
    # for by the ~1.5x MXU-width win; the kernel itself stays slice+dot)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    xeo = xp.reshape(n, h + 2, half + 1, 2, cin).transpose(0, 3, 1, 2, 4)
    tiles = jnp.stack([xeo[:, :, i * th:i * th + th + 2] for i in range(nt)],
                      axis=1)            # (N, nt, 2, TH+2, W/2+1, C)
    kern = functools.partial(_conv3x3_pairs_kernel, bt=batch_tile, h=th, w=ww,
                             cin=cin, cout=cout)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, nt, th, half, 2 * cout),
                                       out_dtype),
        grid=(n // batch_tile, nt),
        in_specs=[
            pl.BlockSpec((batch_tile, 1, 2, th + 2, half + 1, cin),
                         lambda i, j: (i, j, 0, 0, 0, 0)),
            pl.BlockSpec((3, 4, cin, 2 * cout), lambda i, j: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((batch_tile, 1, th, half, 2 * cout),
                               lambda i, j: (i, j, 0, 0, 0)),
        interpret=interpret,
    )(tiles, w2)
    return out.reshape(n, h, ww, cout)


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "interpret", "out_dtype"))
def conv3x3_s1_bnrelu_in(x: jax.Array, w: jax.Array, scale: jax.Array,
                         shift: jax.Array, *, batch_tile: int = 1,
                         interpret: bool | None = None,
                         out_dtype=None) -> jax.Array:
    """``conv3x3_s1(relu(x * scale + shift), w)`` with the per-channel
    BN-apply + ReLU fused into the kernel's input read. ``scale``/``shift``:
    (Cin,)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, h, ww, cin = x.shape
    kh, kw, wcin, cout = w.shape
    if (kh, kw) != (3, 3) or wcin != cin or n % batch_tile:
        raise ValueError(f"conv3x3_s1_bnrelu_in: bad shapes {x.shape} "
                         f"{w.shape} batch_tile={batch_tile}")
    out_dtype = out_dtype or x.dtype
    kern = functools.partial(_conv3x3_bn_kernel, bt=batch_tile, h=h, w=ww,
                             cin=cin, cout=cout)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, h, ww, cout), out_dtype),
        grid=(n // batch_tile,),
        in_specs=[
            pl.BlockSpec((batch_tile, h, ww, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, cout), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((cin,), lambda i: (0,)),
            pl.BlockSpec((cin,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((batch_tile, h, ww, cout),
                               lambda i: (i, 0, 0, 0)),
        interpret=interpret,
    )(x, w, scale, shift)
