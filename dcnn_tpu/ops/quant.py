"""int8 quantization kernels (symmetric, MXU-targeted).

Beyond the reference: the reference has no quantized path (its inference is
the training graph minus update). On TPU v5e the MXU's int8 mode doubles the
bf16 peak (~394 TOP/s vs ~197 TFLOP/s), and XLA lowers int8
``conv_general_dilated`` / ``dot_general`` with ``preferred_element_type=
int32`` straight onto it — roughly parity-to-+13% per compute-bound kernel
on chained ResNet-body convs, and 1.62× end-to-end on ResNet-18 inference
where the bandwidth-bound layers also gain from halved operand bytes
(``benchmarks/bench_int8.py``, RESULTS.md "int8 PTQ inference" for the
artifact numbers and the measurement-spread postmortem). These kernels are the compute half of
``nn.quantize_model`` (post-training quantization of the folded inference
graph).

Design: symmetric scales only (no zero points) — the asymmetric correction
terms cost extra reductions per matmul and buy nothing after BN folding,
because folded-CNN activations are near-zero-centered. Weights are quantized
per output channel (the standard w8 recipe — per-tensor weight scales lose
whole channels to one outlier filter); activations per tensor with a static
calibrated scale, so the quantize op is a pure elementwise chain XLA fuses
into the surrounding graph.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

# int8 symmetric range. -128 is excluded (the asymmetric extra value would
# make the negative range one step wider than the positive and break
# w_q * x_q >= -127*127 symmetry for no measurable accuracy gain).
QMAX = 127.0


def quantize_symmetric(x: jax.Array, scale: jax.Array) -> jax.Array:
    """Quantize ``x`` to int8 with symmetric scale(s): round(x/scale) clipped
    to [-127, 127]. ``scale`` broadcasts against ``x`` (scalar for per-tensor
    activations, per-channel vector for weights)."""
    q = jnp.round(x.astype(jnp.float32) / scale)
    return jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)


def channel_scales(w: jax.Array, *, floor: float = 1e-8) -> jax.Array:
    """Per-output-channel symmetric scales for a weight tensor whose leading
    axis is the output channel (OIHW conv / (out, in) dense — the package's
    storage layout). ``floor`` guards all-zero channels (scale 0 would emit
    NaNs on dequant)."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)),
                     axis=tuple(range(1, w.ndim)))
    return jnp.maximum(absmax, floor) / QMAX


def tensor_scale(x: jax.Array, *, floor: float = 1e-8,
                 quantile: float | None = None) -> jax.Array:
    """Per-tensor symmetric scale from an activation sample (calibration).

    ``quantile=None`` (default) uses absmax — exact coverage of the sample's
    range. A quantile (e.g. 0.9999) clips the top outliers instead: one
    stray activation otherwise stretches the scale and coarsens every other
    value's resolution. Calibration is offline, so the O(n log n) quantile
    sort costs nothing at inference."""
    a = jnp.abs(x.astype(jnp.float32))
    amax = jnp.max(a) if quantile is None else jnp.quantile(
        a.ravel(), quantile)
    return jnp.maximum(amax, floor) / QMAX


def quantize_weight(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(w_q int8, w_scale f32 per leading-axis channel)."""
    s = channel_scales(w)
    return quantize_symmetric(w, s.reshape((-1,) + (1,) * (w.ndim - 1))), s


def dense_int8(x_q: jax.Array, w_q: jax.Array) -> jax.Array:
    """int8 × int8 → int32 GEMM: y = x_q · w_qᵀ with ``w_q`` stored
    (out, in) like ``DenseLayer``. ``preferred_element_type=int32`` keeps the
    MXU accumulating in int32 (no int8 overflow: |sum| ≤ K·127² needs K ≲
    1.3e5 to stay in int32 — true for every model in the zoo)."""
    return lax.dot_general(
        x_q, w_q,
        dimension_numbers=(((x_q.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
