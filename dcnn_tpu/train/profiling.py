"""Per-layer profiling.

Reference equivalent: the µs-per-named-layer forward/backward maps +
``print_profiling_summary`` table in ``Sequential``
(``sequential.hpp:54-55,461-498,323-418``) with NORMAL (clear per batch) vs
CUMULATIVE modes (``train.hpp:37,160-162``).

On TPU, timing *inside* a jitted step is meaningless (XLA fuses across layer
boundaries), so per-layer timing runs the layer chain eagerly layer-by-layer
with a hard device fence — the same numbers the reference's
per-layer-sync profiling produces, at the same cost model (a profiling run,
not the training fast path). The fence is a device->host transfer
(``core.fence.hard_fence``), not ``block_until_ready``, which on tunnelled
TPU backends can return before execution completes and silently produce
garbage timings. For production tracing, ``trace()`` wraps
``jax.profiler`` for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from collections import defaultdict
from typing import Dict

import jax

from ..core.config import ProfilerType
from ..core.fence import hard_fence
from ..core.precision import cast_to_compute, get_precision_mode
from ..nn.sequential import Sequential


class LayerProfiler:
    def __init__(self, mode: ProfilerType = ProfilerType.NORMAL):
        self.mode = mode
        self.forward_us: Dict[str, float] = defaultdict(float)
        self.backward_us: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        # (direction, model, x.shape, x.dtype, training, precision-mode)
        # tuples already warmed — everything that changes the compiled
        # executable gets its own warm pass. Holding the model object (not
        # id()) also pins it against GC id-reuse aliasing.
        self._warmed: set = set()

    def clear(self) -> None:
        self.forward_us.clear()
        self.backward_us.clear()
        self.counts.clear()

    def maybe_clear_per_batch(self) -> None:
        if self.mode == ProfilerType.NORMAL:
            self.clear()

    def profile_forward(self, model: Sequential, params, state, x, *,
                        training: bool = False, rng=None):
        """Run the model layer-by-layer, timing each (device-synced).

        An untimed warm pass runs first so the timed pass measures steady
        state: the first call to each layer executable AND to the fence's
        tiny slice executable otherwise pays XLA compile time inside the
        timed region (the reference profiles steady-state kernels too —
        CUDA context/module load happens before its timers start)."""
        def run(record: bool):
            # Mirror Sequential.apply's precision policy (input + per-layer
            # param casts) so bf16-mode timings measure the bf16 path, not
            # the fp32 one the mode exists to avoid.
            h = cast_to_compute(x)
            new_state = []
            for i, layer in enumerate(model.layers):
                sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
                t0 = time.perf_counter()
                h, s = layer.apply(cast_to_compute(params[i]), state[i], h,
                                   training=training, rng=sub_rng)
                hard_fence(h)
                if record:
                    self.forward_us[layer.name] += (time.perf_counter() - t0) * 1e6
                    self.counts[layer.name] += 1
                new_state.append(s)
            return h, tuple(new_state)

        # Key on the model object itself (not id(): reuse after GC would alias)
        # plus everything that changes the compiled executable — shape, dtype,
        # mode, and the precision policy (a bf16 re-profile must re-warm).
        warm_key = ("fwd", model, tuple(x.shape), str(x.dtype), training,
                    get_precision_mode())
        if warm_key not in self._warmed:
            run(record=False)
            self._warmed.add(warm_key)
        return run(record=True)

    def profile_backward(self, model: Sequential, params, state, x, grad_out, *,
                         training: bool = True, rng=None):
        """Per-layer backward timing via per-layer vjp (mirrors the
        reference's reverse loop timing, sequential.hpp:562-572)."""
        # forward pass saving per-layer inputs (compute-dtype path, like
        # Sequential.apply)
        h = cast_to_compute(x)
        inputs = []
        for i, layer in enumerate(model.layers):
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
            inputs.append(h)
            h, _ = layer.apply(cast_to_compute(params[i]), state[i], h,
                               training=training, rng=sub_rng)
        def run(record: bool):
            g = grad_out.astype(h.dtype)
            for i in reversed(range(len(model.layers))):
                layer = model.layers[i]
                sub_rng = jax.random.fold_in(rng, i) if rng is not None else None

                def fwd(p, xin, _layer=layer, _i=i, _rng=sub_rng):
                    y, _ = _layer.apply(cast_to_compute(p), state[_i], xin,
                                        training=training, rng=_rng)
                    return y

                t0 = time.perf_counter()
                _, vjp = jax.vjp(fwd, params[i], inputs[i])
                gp, g = vjp(g)
                hard_fence(g)
                if record:
                    self.backward_us[layer.name] += (time.perf_counter() - t0) * 1e6
            return g

        warm_key = ("bwd", model, tuple(x.shape), str(x.dtype), training,
                    get_precision_mode())
        if warm_key not in self._warmed:
            run(record=False)
            self._warmed.add(warm_key)
        return run(record=True)

    def summary(self) -> str:
        """Printable table (reference ``print_profiling_summary``,
        sequential.hpp:323-418)."""
        names = list(self.forward_us.keys())
        for n in self.backward_us:
            if n not in names:
                names.append(n)
        lines = [f"{'layer':<28} {'fwd µs':>12} {'bwd µs':>12} {'calls':>7}"]
        tf = tb = 0.0
        for n in names:
            f, b = self.forward_us.get(n, 0.0), self.backward_us.get(n, 0.0)
            tf += f
            tb += b
            lines.append(f"{n:<28} {f:>12.1f} {b:>12.1f} {self.counts.get(n, 0):>7}")
        lines.append(f"{'TOTAL':<28} {tf:>12.1f} {tb:>12.1f}")
        return "\n".join(lines)


_trace_lock = threading.Lock()
_trace_active = False
_trace_seq = itertools.count()


def _try_claim() -> bool:
    """Test-and-set the one-capture-per-process flag."""
    global _trace_active
    with _trace_lock:
        if _trace_active:
            return False
        _trace_active = True
        return True


@contextlib.contextmanager
def _owned_capture(log_dir: str):
    """The capture body; assumes the claim is already held and releases
    it on exit (including the never-entered error paths)."""
    global _trace_active
    try:
        path = os.path.join(
            log_dir, f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
                     f"-{next(_trace_seq):03d}")
        os.makedirs(path, exist_ok=True)
        from ..obs import get_tracer
        with get_tracer().span("profiler.xprof", track="profiler",
                               log_dir=path):
            jax.profiler.start_trace(path)
            try:
                yield path
            finally:
                jax.profiler.stop_trace()
    finally:
        with _trace_lock:
            _trace_active = False


def trace(log_dir: str = "/tmp/dcnn_tpu_trace"):
    """XLA-level trace for xprof/tensorboard (the TPU-native answer to the
    reference's profiling commands, SURVEY.md §5.1).

    ``log_dir`` is the PARENT: every call captures into its own
    timestamped subdir (``<log_dir>/<YYYYmmdd-HHMMSS>-<pid>-<seq>``,
    yielded to the caller), so back-to-back traces never clobber each
    other's capture — the old single hard-coded dir made the second
    trace of a process overwrite the first. Nested use raises a clear
    ``RuntimeError`` up front: ``jax.profiler`` supports one capture per
    process, and the error it raises mid-capture is cryptic.

    The capture is also recorded as a ``profiler.xprof`` span on the
    shared tracer (``dcnn_tpu.obs``), so an xprof capture shows up on the
    span timeline and both can run together.
    """
    if not _try_claim():
        raise RuntimeError(
            "profiling.trace() does not nest: an xprof capture is "
            "already active in this process (jax.profiler supports one "
            "trace at a time); finish it before starting another")
    return _owned_capture(log_dir)


def try_trace(log_dir: str = "/tmp/dcnn_tpu_trace"):
    """Non-raising :func:`trace`: returns the capture context manager, or
    ``None`` when a capture is already active (counted on
    ``profiler_trace_busy_total``). The anomaly-capture path
    (``obs/anomaly.py``) uses this so an operator's manual trace always
    wins the race instead of one side crashing.

    The claim is taken HERE, not at ``__enter__`` — a non-None return
    means the capture slot is yours, so you must enter (and exit) the
    returned context manager to release it.
    """
    if _try_claim():
        return _owned_capture(log_dir)
    from ..obs import get_registry
    get_registry().counter(
        "profiler_trace_busy_total",
        "try_trace() calls that found a capture already active").inc()
    return None
