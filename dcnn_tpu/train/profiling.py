"""Per-layer profiling.

Reference equivalent: the µs-per-named-layer forward/backward maps +
``print_profiling_summary`` table in ``Sequential``
(``sequential.hpp:54-55,461-498,323-418``) with NORMAL (clear per batch) vs
CUMULATIVE modes (``train.hpp:37,160-162``).

On TPU, timing *inside* a jitted step is meaningless (XLA fuses across layer
boundaries), so per-layer timing runs the layer chain eagerly layer-by-layer
with ``block_until_ready`` — the same numbers the reference's
per-layer-sync profiling produces, at the same cost model (a profiling run,
not the training fast path). For production tracing, ``trace()`` wraps
``jax.profiler`` for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Optional

import jax

from ..core.config import ProfilerType
from ..nn.sequential import Sequential


class LayerProfiler:
    def __init__(self, mode: ProfilerType = ProfilerType.NORMAL):
        self.mode = mode
        self.forward_us: Dict[str, float] = defaultdict(float)
        self.backward_us: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    def clear(self) -> None:
        self.forward_us.clear()
        self.backward_us.clear()
        self.counts.clear()

    def maybe_clear_per_batch(self) -> None:
        if self.mode == ProfilerType.NORMAL:
            self.clear()

    def profile_forward(self, model: Sequential, params, state, x, *,
                        training: bool = False, rng=None):
        """Run the model layer-by-layer, timing each (device-synced)."""
        h = x
        new_state = []
        for i, layer in enumerate(model.layers):
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
            t0 = time.perf_counter()
            h, s = layer.apply(params[i], state[i], h, training=training, rng=sub_rng)
            jax.block_until_ready(h)
            self.forward_us[layer.name] += (time.perf_counter() - t0) * 1e6
            self.counts[layer.name] += 1
            new_state.append(s)
        return h, tuple(new_state)

    def profile_backward(self, model: Sequential, params, state, x, grad_out, *,
                         training: bool = True, rng=None):
        """Per-layer backward timing via per-layer vjp (mirrors the
        reference's reverse loop timing, sequential.hpp:562-572)."""
        # forward pass saving per-layer inputs
        h = x
        inputs = []
        for i, layer in enumerate(model.layers):
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
            inputs.append(h)
            h, _ = layer.apply(params[i], state[i], h, training=training, rng=sub_rng)
        g = grad_out
        for i in reversed(range(len(model.layers))):
            layer = model.layers[i]
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None

            def fwd(p, xin):
                y, _ = layer.apply(p, state[i], xin, training=training, rng=sub_rng)
                return y

            t0 = time.perf_counter()
            _, vjp = jax.vjp(fwd, params[i], inputs[i])
            gp, g = vjp(g)
            jax.block_until_ready(g)
            self.backward_us[layer.name] += (time.perf_counter() - t0) * 1e6
        return g

    def summary(self) -> str:
        """Printable table (reference ``print_profiling_summary``,
        sequential.hpp:323-418)."""
        names = list(self.forward_us.keys())
        for n in self.backward_us:
            if n not in names:
                names.append(n)
        lines = [f"{'layer':<28} {'fwd µs':>12} {'bwd µs':>12} {'calls':>7}"]
        tf = tb = 0.0
        for n in names:
            f, b = self.forward_us.get(n, 0.0), self.backward_us.get(n, 0.0)
            tf += f
            tb += b
            lines.append(f"{n:<28} {f:>12.1f} {b:>12.1f} {self.counts.get(n, 0):>7}")
        lines.append(f"{'TOTAL':<28} {tf:>12.1f} {tb:>12.1f}")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/dcnn_tpu_trace"):
    """XLA-level trace for xprof/tensorboard (the TPU-native answer to the
    reference's profiling commands, SURVEY.md §5.1)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
