"""Training loops, checkpointing, profiling (reference ``include/nn/train.hpp``)."""

from .checkpoint import load_checkpoint, save_checkpoint
from .profiling import LayerProfiler
from .trainer import (
    Trainer, TrainState, evaluate_classification, make_eval_step,
    make_multi_step, make_train_step, train_classification_model,
)

__all__ = [
    "save_checkpoint", "load_checkpoint",
    "LayerProfiler",
    "Trainer", "TrainState", "make_train_step", "make_eval_step",
    "make_multi_step", "train_classification_model", "evaluate_classification",
]
