"""Checkpoint save/load.

Reference equivalent: architecture→JSON + raw binary weights
(``sequential.hpp:832-915,1001-1037``; ``tensor.hpp:625-653``), auto-snapshot
on best validation accuracy (``train.hpp:254-264``). Two deliberate
improvements over the reference (SURVEY.md §5.4 lists these as gaps):

- **optimizer state is checkpointed** (Adam m/v/t survive resume);
- BN running stats (model ``state``) are checkpointed alongside params.

Format: ``<dir>/model.json`` (model config + optimizer config + user
metadata) and ``<dir>/arrays.msgpack`` (params/state/opt_state pytrees via
flax.serialization). Loading rebuilds the model through the LayerFactory from
JSON — the exact machinery a pipeline worker uses to materialize a stage.

Durability: each file is committed atomically (tmp sibling + fsync +
``os.replace`` — ``resilience/atomic.py``), so a preemption mid-save can
never leave a torn, half-written file: the previous checkpoint's bytes
survive intact until the instant a complete replacement lands. Arrays are
replaced before the config that describes them, so the one cross-file crash
window (between the two renames) yields new arrays + old config — identical
in-run (the config doesn't change between epochs), and a *loud* template
mismatch rather than silent corruption if the architecture changed. Runs
that need step history, checksums, retention, or async saves use the v2
layer on top: ``dcnn_tpu.resilience.CheckpointManager``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from ..nn.sequential import Sequential
from ..optim.optimizers import Optimizer, OptimizerFactory
from ..resilience import faults as _faults
from ..resilience.atomic import write_file_atomic

_ARRAYS = "arrays.msgpack"
_MODEL = "model.json"


def save_checkpoint(path: str, model: Sequential, params, state, opt_state=None,
                    optimizer: Optional[Optimizer] = None,
                    metadata: Optional[Dict[str, Any]] = None) -> None:
    os.makedirs(path, exist_ok=True)
    manifest = {
        "model": model.get_config(),
        "optimizer": optimizer.get_config() if optimizer is not None else None,
        "metadata": metadata or {},
        "has_opt_state": opt_state is not None,
    }
    tree = {"params": params, "state": state}
    if opt_state is not None:
        tree["opt_state"] = opt_state
    # to_bytes state-dict-ifies the tree (tuples → indexed dicts), which
    # msgpack can carry; from_bytes restores against the typed template.
    array_bytes = serialization.to_bytes(
        jax.tree_util.tree_map(lambda x: jax.device_get(x), tree))
    # fault-injection point: a "preemption" here models dying mid-save,
    # before anything replaced the previous checkpoint's files
    _faults.trip("ckpt.write", path=path)
    write_file_atomic(os.path.join(path, _ARRAYS), array_bytes)
    write_file_atomic(os.path.join(path, _MODEL),
                      json.dumps(manifest, indent=2).encode("utf-8"))


def load_checkpoint(path: str, seed: int = 0,
                    ) -> Tuple[Sequential, Any, Any, Any, Optional[Optimizer], Dict[str, Any]]:
    """Returns (model, params, state, opt_state, optimizer, metadata).

    The model is rebuilt from its JSON config and template-initialized to
    recover the exact pytree structure, then the stored arrays are restored
    into it (tuple-vs-list structure preserved via ``from_state_dict`` against
    the template)."""
    with open(os.path.join(path, _MODEL), "r", encoding="utf-8") as f:
        manifest = json.load(f)
    model = Sequential.from_config(manifest["model"])
    if model.input_shape is None:
        raise ValueError("checkpoint model config lacks input_shape")
    t_params, t_state = model.init(jax.random.PRNGKey(seed), model.input_shape)

    optimizer = (OptimizerFactory.create_from_config(manifest["optimizer"])
                 if manifest.get("optimizer") else None)
    template: Dict[str, Any] = {"params": t_params, "state": t_state}
    if manifest.get("has_opt_state"):
        if optimizer is None:
            raise ValueError("checkpoint has optimizer state but no optimizer config")
        template["opt_state"] = optimizer.init(t_params)

    with open(os.path.join(path, _ARRAYS), "rb") as f:
        restored = serialization.from_bytes(template, f.read())
    # from_bytes leaves are np.frombuffer views into the msgpack blob —
    # they pin the whole file's bytes alive, and worse: the CPU runtime
    # zero-copy *aliases* 64-byte-aligned host numpy buffers on
    # device_put, so when a restored leaf lands in a donating jitted
    # step (resume / guard rollback) the donated output can reuse host
    # memory whose lifetime numpy still controls — allocation-dependent
    # use-after-free (observed: denormal garbage in resumed params).
    # jnp.array(copy=True) is the one constructor guaranteed to land in
    # an XLA-owned buffer, never an alias.
    restored = jax.tree_util.tree_map(
        lambda x: jnp.array(x, copy=True) if isinstance(x, np.ndarray)
        else x, restored)
    return (model, restored["params"], restored["state"],
            restored.get("opt_state"), optimizer, manifest.get("metadata", {}))
