"""Single-device (and data-parallel) training loops.

Reference equivalent: ``include/nn/train.hpp`` — ``TrainingConfig`` (:46),
``train_class_epoch`` (:108), ``validate_class_model`` (:172),
``train_classification_model`` (:202: epoch loop, best-val snapshot save,
per-epoch LR decay), regression twins (:311-481).

TPU-native shape: one jitted ``train_step`` closes over the model spec /
loss / optimizer; params/state/opt-state live in a ``TrainState`` pytree.
Optional microbatch gradient accumulation runs as a ``lax.scan`` inside the
step — BN statistics are computed per microbatch sequentially, matching the
reference's per-microbatch BN semantics (SURVEY.md §7 hard part 4).
"""

from __future__ import annotations

import functools
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.config import ProfilerType, TrainingConfig
from ..nn.sequential import Sequential
from ..obs import get_registry, get_tracer
from ..resilience import faults as _faults
from ..ops.losses import get_loss, upcast_logits
from ..ops.metrics import correct_count
from ..optim.optimizers import Optimizer
from ..optim.schedulers import Scheduler
from .checkpoint import save_checkpoint
from .profiling import LayerProfiler


@dataclass
class TrainState:
    """Everything that changes during training, as one pytree."""

    params: Any
    state: Any        # per-layer mutable state (BN running stats)
    opt_state: Any
    step: jax.Array   # int32 scalar

    def tree_flatten(self):
        return (self.params, self.state, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def create_train_state(model: Sequential, optimizer: Optimizer, key: jax.Array,
                       input_shape=None) -> TrainState:
    params, state = model.init(key, input_shape)
    return TrainState(params=params, state=state,
                      opt_state=optimizer.init(params),
                      step=jnp.zeros((), jnp.int32))


def make_train_step(model: Sequential, loss_fn: Callable, optimizer: Optimizer,
                    num_microbatches: int = 1, donate: bool = True,
                    jit: bool = True, reduce_axis: Optional[str] = None,
                    guard: bool = False):
    """Returns jitted ``step(ts, x, y, rng, lr) -> (ts, loss, logits)``.

    With ``num_microbatches > 1`` the batch is split on the leading axis and
    grads are accumulated with ``lax.scan`` (the single-jit analog of the
    reference's microbatch streaming, tensor_ops.hpp:193-225).

    ``reduce_axis``: name of a mapped mesh axis (shard_map/pmap body) to
    ``pmean`` grads, loss, and the updated layer state over before the
    optimizer update — the canonical data-parallel step; every DP wrapper
    reuses this instead of reimplementing fwd/bwd/update. Logits stay local
    to the shard.

    ``guard=True``: the step additionally returns a scalar bool ``bad`` —
    the in-graph non-finite detector (``~isfinite(loss) | ~isfinite(Σ‖g‖²)``)
    — and when it fires the returned TrainState is the *incoming* one
    (params/state/opt_state/step selected untouched via ``jnp.where``), so
    a poisoned batch can never contaminate training state; host-side
    policy (raise / skip / rollback) lives in ``resilience.StepGuard``."""

    def forward_loss(params, state, x, y, rng):
        logits, new_state = model.apply(params, state, x, training=True, rng=rng)
        # The repo losses upcast internally (ops/losses._loss_fp32 is the
        # boundary); this cast covers *custom* loss_fns and fixes the dtype
        # of the logits handed back to callers. fp64 stays fp64 (the fp64
        # precision mode must not quantize the loss/cotangent boundary).
        logits = upcast_logits(logits)
        return loss_fn(logits, y), (logits, new_state)

    grad_fn = jax.value_and_grad(forward_loss, has_aux=True)

    def step(ts: TrainState, x, y, rng, lr):
        # Shapes are static at trace time: a trailing partial batch (any
        # drop_last=False loader) that doesn't divide evenly falls back to
        # one whole-batch microbatch rather than crashing the reshape. The
        # fallback changes BN batch-statistics semantics (one big batch vs
        # N small ones), so it warns — once per traced shape.
        if num_microbatches > 1 and x.shape[0] % num_microbatches != 0:
            import warnings
            warnings.warn(
                f"batch size {x.shape[0]} not divisible by num_microbatches="
                f"{num_microbatches}: training this batch unmicrobatched "
                f"(different BN statistics semantics)", stacklevel=2)
        if num_microbatches == 1 or x.shape[0] % num_microbatches != 0:
            (loss, (logits, new_state)), grads = grad_fn(ts.params, ts.state, x, y, rng)
        else:
            mb_x = x.reshape(num_microbatches, x.shape[0] // num_microbatches, *x.shape[1:])
            mb_y = y.reshape(num_microbatches, y.shape[0] // num_microbatches, *y.shape[1:])

            def body(carry, mb):
                state, grad_acc, loss_acc = carry
                xi, yi, i = mb
                (loss, (logits, new_state)), grads = grad_fn(
                    ts.params, state, xi, yi, jax.random.fold_in(rng, i))
                grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, grads)
                return (new_state, grad_acc, loss_acc + loss), logits

            zero_grads = jax.tree_util.tree_map(jnp.zeros_like, ts.params)
            idx = jnp.arange(num_microbatches)
            (new_state, grads, loss_sum), logits_all = jax.lax.scan(
                body, (ts.state, zero_grads, 0.0), (mb_x, mb_y, idx))
            grads = jax.tree_util.tree_map(lambda g: g / num_microbatches, grads)
            loss = loss_sum / num_microbatches
            logits = logits_all.reshape(x.shape[0], -1)

        if reduce_axis is not None:
            grads = jax.lax.pmean(grads, reduce_axis)
            loss = jax.lax.pmean(loss, reduce_axis)
            # per-shard batch statistics, mesh-averaged (EMA is linear, so
            # this equals an EMA of shard-mean statistics)
            new_state = jax.lax.pmean(new_state, reduce_axis)
        new_params, new_opt = optimizer.update(grads, ts.opt_state, ts.params, lr)
        if not guard:
            return (TrainState(new_params, new_state, new_opt, ts.step + 1),
                    loss, logits)
        from ..resilience.guards import global_norm_sq
        bad = jnp.logical_not(jnp.isfinite(loss)
                              & jnp.isfinite(global_norm_sq(grads)))
        keep = lambda new, old: jnp.where(bad, old, new)  # noqa: E731
        guarded = TrainState(
            jax.tree_util.tree_map(keep, new_params, ts.params),
            jax.tree_util.tree_map(keep, new_state, ts.state),
            jax.tree_util.tree_map(keep, new_opt, ts.opt_state),
            jnp.where(bad, ts.step, ts.step + 1))
        return guarded, loss, logits, bad

    if not jit:
        return step
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_multi_step(model: Sequential, loss_fn: Callable, optimizer: Optimizer,
                    num_microbatches: int = 1, donate: bool = True):
    """Returns jitted ``multi_step(ts, xs, ys, rng, lr) -> (ts, mean_loss)``
    running ``xs.shape[0]`` full train steps in ONE device dispatch via
    ``lax.scan`` (``xs``: [K, B, ...], ``ys``: [K, B, classes]).

    The TPU-idiomatic "train loop inside jit": one executable launch per K
    batches amortizes host dispatch latency (significant on remote/tunnelled
    TPU hosts), and pairs with a prefetching loader that stages K batches
    into HBM while the previous chunk trains. Semantics are identical to K
    sequential ``make_train_step`` calls (per-batch BN stats, per-batch
    optimizer updates, per-step folded rng) — only the dispatch granularity
    changes. The reference has no analog (its CUDA stream dispatch is local
    and cheap); this is pure TPU-runtime design.

    ``lr`` may be a scalar or a [K] vector (one lr per inner step) — the
    latter keeps per-batch LR schedules exact under chunked dispatch."""
    base = make_train_step(model, loss_fn, optimizer,
                           num_microbatches=num_microbatches, jit=False)

    def multi_step(ts: TrainState, xs, ys, rng, lr):
        lrs = jnp.broadcast_to(jnp.asarray(lr, jnp.float32), (xs.shape[0],))

        def body(carry, xyi):
            x, y, i, lr_i = xyi
            new_ts, loss, _ = base(carry, x, y, jax.random.fold_in(rng, i), lr_i)
            return new_ts, loss

        ts, losses = jax.lax.scan(
            body, ts, (xs, ys, jnp.arange(xs.shape[0]), lrs))
        return ts, jnp.mean(losses)

    return jax.jit(multi_step, donate_argnums=(0,) if donate else ())


def make_eval_step(model: Sequential, loss_fn: Callable):
    """Jitted ``eval_step(params, state, x, y) -> (loss, correct)``
    (reference ``validate_class_model``, train.hpp:172). Memoized on
    (model, loss_fn, precision-mode) so per-epoch validation reuses one
    compiled step — and a ``set_precision`` change re-traces instead of
    silently serving the old mode's executable."""
    from ..core.precision import get_precision_mode
    return _make_eval_step_cached(model, loss_fn, get_precision_mode())


@functools.lru_cache(maxsize=64)
def _make_eval_step_cached(model: Sequential, loss_fn: Callable, _mode: str):
    @jax.jit
    def eval_step(params, state, x, y):
        logits, _ = model.apply(params, state, x, training=False)
        logits = upcast_logits(logits)
        return loss_fn(logits, y), correct_count(logits, y)

    return eval_step


def evaluate_classification(model, params, state, loss_fn, loader,
                            eval_step=None) -> Tuple[float, float]:
    from ..data.device_dataset import (
        DeviceDataset, ShardedDeviceDataset, resident_eval)
    if isinstance(loader, ShardedDeviceDataset):
        raise TypeError(
            "validation over a ShardedDeviceDataset is not supported — val "
            "splits are small: stage them replicated with DeviceDataset "
            "(whole-split eval is one dispatch either way)")
    if isinstance(loader, DeviceDataset):
        # HBM-resident split: one device dispatch for the whole validation
        # pass (full batches + exact remainder — see data/device_dataset.py)
        ev = resident_eval(model, loss_fn, loader)
        loss_sum, correct, n = ev(params, state, loader.x, loader.y,
                                  scale=loader.scale)
        n = int(n)  # jit canonicalizes to Array; history/snapshots need floats
        return float(loss_sum) / n, int(correct) / n
    eval_step = eval_step if eval_step is not None else make_eval_step(model, loss_fn)
    total_loss, total_correct, total_n = 0.0, 0, 0
    # host loaders ship wire-dtype batches (uint8 pixels): decode after
    # the put, per the loader's scale contract (identity for float input)
    from ..data.wire import decode_batch, wire_scale
    scale = wire_scale(loader)
    for x, y in loader:
        loss, correct = eval_step(params, state,
                                  decode_batch(jnp.asarray(x), scale),
                                  jnp.asarray(y))
        total_loss += float(loss) * x.shape[0]
        total_correct += int(correct)
        total_n += x.shape[0]
    if total_n == 0:
        return 0.0, 0.0
    return total_loss / total_n, total_correct / total_n


class Trainer:
    """Epoch-loop driver (reference ``train_classification_model``,
    train.hpp:202-308): per-epoch train/validate, best-val snapshot, LR decay
    or scheduler, progress prints, optional per-layer profiling."""

    def __init__(self, model: Sequential, optimizer: Optimizer,
                 loss: Callable | str, config: Optional[TrainingConfig] = None,
                 scheduler: Optional[Scheduler] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = get_loss(loss) if isinstance(loss, str) else loss
        self.config = config or TrainingConfig()
        if self.config.debug:
            # the 'debug build' (reference ENABLE_DEBUG -> ASan): sanitize
            # NaN/Inf production across every jitted step of this process
            from ..core.debug import enable_debug_mode
            enable_debug_mode()
        self.scheduler = scheduler
        self.profiler = (LayerProfiler(self.config.profiler)
                         if self.config.profiler != ProfilerType.NONE else None)
        # failure flight recorder (obs/flight.py): flight_dir enables the
        # PROCESS-GLOBAL recorder so every trigger site this trainer
        # touches — the non-finite guard, the stall watchdog, the
        # telemetry server's healthz 503 edge — dumps postmortem bundles
        # there without per-site plumbing (same semantics as the
        # DCNN_FLIGHT_DIR env var, applied at construction)
        if self.config.flight_dir:
            from ..obs.flight import configure_flight
            configure_flight(self.config.flight_dir)
        # non-finite step guard (resilience/guards.py): "off" keeps the
        # exact pre-guard graph; any policy compiles the guarded step that
        # returns (and neutralizes) the bad flag in-graph
        self._guard_on = self.config.nonfinite_policy != "off"
        if self._guard_on:
            if self.config.steps_per_dispatch > 1:
                raise ValueError(
                    "nonfinite_policy guards the per-batch step loop; with "
                    "steps_per_dispatch > 1 losses never reach the host "
                    "per-step — use steps_per_dispatch=1 or policy 'off'")
            if (self.config.nonfinite_policy == "rollback"
                    and not self.config.checkpoint_dir):
                raise ValueError(
                    "nonfinite_policy='rollback' needs checkpoint_dir set "
                    "(and checkpoint_every > 0) so there is a checkpoint "
                    "to roll back to — a rollback that can only abort is "
                    "a delayed crash, not a recovery policy")
            from ..resilience.guards import StepGuard
            self.guard = StepGuard(self.config.nonfinite_policy,
                                   rollback_after=self.config.rollback_after)
        else:
            self.guard = None
        # periodic atomic checkpointing + resume (resilience/checkpoint.py)
        if self.config.checkpoint_dir:
            from ..resilience.checkpoint import CheckpointManager
            self.checkpoints = CheckpointManager(
                self.config.checkpoint_dir, keep=self.config.checkpoint_keep)
        else:
            self.checkpoints = None
        self.watchdog = None  # created per fit() when stall_timeout_s > 0
        self.telemetry = None  # TelemetryServer, per fit() (metrics_port)
        self._tsdb = None      # TsdbSampler, per fit() (rides metrics_port)
        self._goodput = None   # GoodputMonitor, per fit() (rides metrics_port)
        self._global_step = 0
        self.train_step = make_train_step(model, self.loss_fn, optimizer,
                                          self.config.num_microbatches,
                                          guard=self._guard_on)
        # chunked fast path: one device dispatch per K train steps. The
        # loader must yield [K, B, ...] stacks (PrefetchLoader with
        # stage_batches=K); per-batch logits/accuracy are not materialized
        # in this mode (the loss is the per-chunk mean).
        self.multi_step = (make_multi_step(model, self.loss_fn, optimizer,
                                           self.config.num_microbatches)
                           if self.config.steps_per_dispatch > 1 else None)
        self.eval_step = make_eval_step(model, self.loss_fn)
        self._wire_aot()
        self.lr = self.config.learning_rate
        self.history: list = []

    def _wire_aot(self) -> None:
        """Warm-start the train/multi step from the persistent executable
        cache (dcnn_tpu/aot): on a hit the first step deserializes a
        prior process's compiled executable instead of paying the XLA
        compile wall (149.9 s on the r05 capture). Off unless
        ``TrainingConfig.aot_cache_dir`` / ``AOT_CACHE`` is set; any
        wiring failure leaves the plain jitted steps in place — the
        cache accelerates, never gates."""
        try:
            from ..aot import WarmCallable, digest, get_cache
            from ..aot.keys import train_step_key_material

            cache = get_cache(self.config.aot_cache_dir)
            if cache is None:
                return
            # train_step_key_material digests everything the jitted step
            # closes over that shapes the compiled program (keys.py
            # documents the contract); lr and the batch ride in as
            # arguments so they are NOT key material — the same helper
            # keys the bench `aot` phase and the CLI --prewarm, so a
            # prewarmed entry hits here by construction
            def material(kind):
                return train_step_key_material(
                    self.model, self.optimizer, self.loss_fn,
                    num_microbatches=self.config.num_microbatches,
                    guard=self._guard_on, kind=kind)

            self.train_step = WarmCallable(
                self.train_step, cache, what="train",
                config=digest(material("train_step")), donate=(0,))
            if self.multi_step is not None:
                self.multi_step = WarmCallable(
                    self.multi_step, cache, what="train",
                    config=digest(material("multi_step")),
                    donate=(0,))
        except Exception:
            pass

    @staticmethod
    def _epoch_samples(loader) -> Optional[int]:
        """Best-effort samples-per-epoch for the throughput gauge. None
        (gauge skipped) when the loader exposes no length — telemetry never
        guesses."""
        # steps*batch first: it is what an epoch actually consumes — a
        # drop-last loader's num_samples would overcount the tail
        spe = getattr(loader, "steps_per_epoch", None)
        bs = getattr(loader, "batch_size", None)
        if spe and bs:
            return int(spe) * int(bs)
        n = getattr(loader, "num_samples", None)
        if n:
            return int(n)
        x = getattr(loader, "x", None)
        if x is not None and hasattr(x, "shape"):
            return int(x.shape[0])
        return None

    def _rollback(self, ts: TrainState) -> TrainState:
        """'rollback' guard policy: restore training state from the newest
        valid checkpoint (the run's state may already be poisoned — one
        skipped step was not enough)."""
        if self.checkpoints is None:
            raise RuntimeError(
                "nonfinite_policy='rollback' needs checkpoint_dir set so "
                "there is a checkpoint to roll back to")
        self.checkpoints.wait()  # queued async saves must land first
        restored = self.checkpoints.restore_latest(seed=self.config.seed)
        if restored is None:
            raise RuntimeError(
                f"rollback requested but no valid checkpoint under "
                f"{self.checkpoints.directory}")
        print(f"  guard rollback: restored checkpoint step {restored.step} "
              f"from {restored.path}", flush=True)
        return TrainState(
            restored.params, restored.state, restored.opt_state,
            jnp.asarray(restored.metadata.get("global_step", 0), jnp.int32))

    def train_epoch(self, ts: TrainState, loader, rng: jax.Array,
                    epoch: int = 0) -> Tuple[TrainState, float, float]:
        from ..data.device_dataset import DeviceDataset, ShardedDeviceDataset
        if isinstance(loader, (DeviceDataset, ShardedDeviceDataset)) \
                and self.guard is not None:
            raise ValueError(
                "nonfinite_policy guards the per-batch step loop; resident "
                "datasets run whole epochs in one dispatch (losses never "
                "reach the host per-step) — use a host loader or policy "
                "'off'")
        if isinstance(loader, ShardedDeviceDataset):
            return self._train_epoch_resident(ts, loader, rng, epoch, dp=True)
        if isinstance(loader, DeviceDataset):
            return self._train_epoch_resident(ts, loader, rng, epoch)
        if self.multi_step is not None:
            return self._train_epoch_chunked(ts, loader, rng, epoch)
        tracer = get_tracer()
        total_loss, total_correct, total_n, batches = 0.0, 0, 0, 0
        t0 = time.perf_counter()
        # wire-dtype contract: the put above ships the loader's wire
        # dtype (uint8 pixels); decode to model domain on device, after
        # the transfer (identity for float batches)
        from ..data.wire import decode_batch, wire_scale
        scale = wire_scale(loader)
        for bi, (x, y) in enumerate(loader):
            x, y = decode_batch(jnp.asarray(x), scale), jnp.asarray(y)
            step_rng = jax.random.fold_in(rng, bi)
            self._global_step += 1
            if self.watchdog is not None:
                self.watchdog.beat()
            if _faults.active() is not None:
                # fault harness: an armed "train.nonfinite_input" poisons
                # this batch so loss/grads go NaN (same shape/dtype — no
                # retrace), proving the guard path end to end; armed as an
                # InjectedCrash it kills the run here instead (the
                # mid-epoch-preemption simulation resume tests restart from)
                try:
                    _faults.trip("train.nonfinite_input",
                                 step=self._global_step)
                except _faults.InjectedCrash:
                    raise
                except _faults.InjectedFault:
                    x = jnp.full_like(x, jnp.nan)
            # the float(loss)/correct_count reads inside the span block on
            # the device result, so step spans tile the epoch wall truthfully
            t_step = time.perf_counter()
            with tracer.span("train.step", track="train", epoch=epoch,
                             batch=bi):
                if self.guard is not None:
                    ts, loss, logits, bad = self.train_step(
                        ts, x, y, step_rng, self.lr)
                    action = self.guard.observe(
                        self._global_step, bool(bad), float(loss))
                    if action == "rollback":
                        ts = self._rollback(ts)
                        continue  # skipped-step metrics excluded below too
                    if action == "skipped":
                        continue  # NaN loss must not poison the epoch mean
                else:
                    ts, loss, logits = self.train_step(
                        ts, x, y, step_rng, self.lr)
                total_loss += float(loss) * x.shape[0]
                total_correct += int(correct_count(logits, y))
            if self._goodput is not None:
                self._goodput.observe_step(time.perf_counter() - t_step)
            total_n += x.shape[0]
            batches += 1
            if (self.scheduler is not None
                    and self.config.scheduler_step == "batch"):
                # per-batch cadence: what OneCycleLR/WarmupCosine are sized
                # for (total_steps = epochs * batches_per_epoch); the metric
                # is the running train loss (val loss doesn't exist mid-epoch;
                # max() guards an all-steps-skipped start under the guard)
                self.lr = self.scheduler.step(total_loss / max(total_n, 1))
            if self.config.progress_interval and (bi + 1) % self.config.progress_interval == 0:
                dt = time.perf_counter() - t0
                n = max(total_n, 1)
                print(f"  epoch {epoch} batch {bi + 1}: loss {total_loss / n:.4f} "
                      f"acc {total_correct / n:.4f} "
                      f"({total_n / dt:.1f} samples/s)", flush=True)
        return ts, (total_loss / max(total_n, 1)), (total_correct / max(total_n, 1))

    def _train_epoch_resident(self, ts: TrainState, ds, rng: jax.Array,
                              epoch: int = 0, dp: bool = False,
                              ) -> Tuple[TrainState, float, float]:
        """HBM-resident epoch: ONE device dispatch runs shuffle + gather +
        decode + augment + every train step (data/device_dataset.py). Zero
        steady-state H2D; train accuracy is not materialized (NaN — validation
        measures real accuracy), matching the chunked path's contract.
        Per-batch LR schedules ship as a [steps] vector; metric-driven
        schedulers see the previous epoch's mean train loss (per-epoch
        granularity — mid-epoch losses never reach the host in this mode).
        ``dp=True`` (ShardedDeviceDataset): the data-parallel variant — the
        dataset lives sharded over the mesh and every device runs the epoch
        with grad pmean (data/device_dataset.py:make_resident_epoch_dp);
        the scalar-lr path only (per-batch lr vectors not yet threaded)."""
        if dp:
            from ..data.device_dataset import resident_epoch_dp
            epoch_fn = resident_epoch_dp(self.model, self.loss_fn,
                                         self.optimizer, ds,
                                         self.config.num_microbatches)
            if (self.scheduler is not None
                    and self.config.scheduler_step == "batch"):
                raise NotImplementedError(
                    "per-batch LR scheduling with ShardedDeviceDataset: the "
                    "DP epoch takes a scalar lr; use scheduler_step='epoch'")
            with get_tracer().span("train.resident_epoch", track="train",
                                   epoch=epoch, dp=True):
                ts, mean_loss = epoch_fn(ts, ds.x, ds.y,
                                         jax.random.fold_in(rng, epoch),
                                         self.lr)
                mean_loss = float(mean_loss)
            return ts, mean_loss, float("nan")
        from ..data.device_dataset import resident_epoch
        epoch_fn = resident_epoch(self.model, self.loss_fn, self.optimizer, ds,
                                  self.config.num_microbatches)
        k = ds.steps_per_epoch
        if self.scheduler is not None and self.config.scheduler_step == "batch":
            metric = self.history[-1]["train_loss"] if self.history else None
            lrs = []
            for si in range(k):
                lrs.append(self.lr)
                # one metric evaluation per epoch (cf. chunked path: one per
                # chunk) — plateau patience is measured in epochs here
                self.lr = self.scheduler.step(metric if si == 0 else None)
            lr_arg = jnp.asarray(lrs, jnp.float32)
        else:
            lr_arg = self.lr
        # one dispatch runs the whole epoch; float() fences, so the span is
        # the true epoch device wall
        with get_tracer().span("train.resident_epoch", track="train",
                               epoch=epoch):
            ts, mean_loss = epoch_fn(ts, ds.x, ds.y,
                                     jax.random.fold_in(rng, epoch), lr_arg)
            mean_loss = float(mean_loss)
        return ts, mean_loss, float("nan")

    def _train_epoch_chunked(self, ts: TrainState, loader, rng: jax.Array,
                             epoch: int = 0) -> Tuple[TrainState, float, float]:
        """K train steps per device dispatch over [K, B, ...] chunks.
        Per-batch logits are not materialized, so train accuracy is reported
        as NaN (validation still measures real accuracy). Per-batch LR
        schedules stay exact: the K per-step lrs are precomputed on the host
        and shipped as a vector into the scan (metric-driven schedulers see
        the pre-chunk running loss instead of intermediate losses — the one
        documented approximation)."""
        sample_ndim = len(self.model.input_shape)
        total_loss, total_n = 0.0, 0
        t0 = time.perf_counter()
        # decode after the put, per the loader's wire contract (identity
        # for float chunks and for PrefetchLoader's auto-decoded output)
        from ..data.wire import decode_batch, wire_scale
        scale = wire_scale(loader)
        for ci, (xs, ys) in enumerate(loader):
            if self.watchdog is not None:
                self.watchdog.beat()
            xs, ys = decode_batch(jnp.asarray(xs), scale), jnp.asarray(ys)
            if xs.ndim != sample_ndim + 2:
                raise ValueError(
                    f"steps_per_dispatch={self.config.steps_per_dispatch} "
                    f"needs [K, B, ...] chunks (got shape {xs.shape}); wrap "
                    f"the loader in PrefetchLoader(stage_batches=K) / "
                    f"examples.common.with_prefetch")
            chunk_rng = jax.random.fold_in(rng, ci)
            per_batch_sched = (self.scheduler is not None
                               and self.config.scheduler_step == "batch")
            if per_batch_sched:
                # No loss exists yet for the first chunk: pass None so
                # metric-driven schedulers (ReduceLROnPlateau) skip the
                # update instead of seeing a spurious 0.0 "perfect" loss.
                metric = (total_loss / total_n) if total_n > 0 else None
                lrs = []
                for si in range(xs.shape[0]):
                    lrs.append(self.lr)
                    # one metric evaluation per chunk: feeding the same value
                    # K times would count K-1 spurious "no improvement" steps
                    # per chunk in plateau schedulers (patience is therefore
                    # measured in chunks when steps_per_dispatch > 1)
                    self.lr = self.scheduler.step(metric if si == 0 else None)
                lr_arg = jnp.asarray(lrs, jnp.float32)
            else:
                lr_arg = self.lr
            t_chunk = time.perf_counter()
            with get_tracer().span("train.chunk", track="train",
                                   epoch=epoch, chunk=ci,
                                   steps=int(xs.shape[0])):
                ts, mean_loss = self.multi_step(ts, xs, ys, chunk_rng, lr_arg)
                n = xs.shape[0] * xs.shape[1]
                total_loss += float(mean_loss) * n
            if self._goodput is not None:
                # per-step anomaly granularity: a chunk is K fused steps
                self._goodput.observe_step(
                    (time.perf_counter() - t_chunk) / max(xs.shape[0], 1))
            total_n += n
            if self.config.progress_interval and (ci + 1) % max(
                    self.config.progress_interval // max(xs.shape[0], 1), 1) == 0:
                dt = time.perf_counter() - t0
                print(f"  epoch {epoch} chunk {ci + 1}: loss "
                      f"{total_loss / total_n:.4f} "
                      f"({total_n / dt:.1f} samples/s)", flush=True)
        return ts, total_loss / max(total_n, 1), float("nan")

    def fit(self, ts: TrainState, train_loader, val_loader=None,
            epochs: Optional[int] = None, seed: Optional[int] = None) -> TrainState:
        cfg = self.config
        if cfg.elastic:
            # generation-aware elastic DP fit: the membership/heartbeat
            # layer, lockstep gradient exchange, and the
            # reconfiguration-on-peer-loss protocol live in
            # parallel/elastic.py; this loop delegates so a single config
            # knob (ELASTIC=1 + ELASTIC_PEERS) turns a normal run into
            # one that survives losing a host mid-epoch. Lazy import:
            # train.trainer must stay importable without the parallel
            # package (which itself imports this module).
            from ..parallel.elastic import elastic_fit
            return elastic_fit(self, ts, train_loader, val_loader, epochs,
                               seed=seed)
        epochs = epochs or cfg.epochs
        rng = jax.random.PRNGKey(seed if seed is not None else cfg.seed)
        best_val = -1.0
        tracer = get_tracer()
        reg = get_registry()
        start_epoch = 1
        if self.checkpoints is not None and cfg.resume == "auto":
            # resume contract (docs/reliability.md): epoch rng is
            # fold_in(PRNGKey(seed), epoch) and loaders shuffle by epoch, so
            # restarting at the restored epoch+1 with restored
            # params/state/opt_state/lr replays the exact uninterrupted
            # loss trajectory (metric-driven scheduler internals are the one
            # documented exception — they see the restored history only)
            restored = self.checkpoints.restore_latest(seed=cfg.seed)
            if restored is not None:
                md = restored.metadata
                ts = TrainState(
                    restored.params, restored.state, restored.opt_state,
                    jnp.asarray(md.get("global_step", 0), jnp.int32))
                start_epoch = restored.step + 1
                self.lr = md.get("lr", self.lr)
                self.history = md.get("history", self.history) or []
                self._global_step = int(md.get("global_step", 0))
                best_val = md.get("best_val", -1.0)
                print(f"resumed from checkpoint step {restored.step} "
                      f"({restored.path}); continuing at epoch {start_epoch}",
                      flush=True)
        if cfg.stall_timeout_s > 0:
            from ..resilience.guards import StallWatchdog
            self.watchdog = StallWatchdog(cfg.stall_timeout_s).start()
        try:
            if cfg.metrics_port >= 0:
                # external telemetry plane (obs/server.py): /metrics
                # scrape + /healthz (watchdog stall and rotting-checkpoint
                # states flip it to 503) + /snapshot, live for the whole
                # fit. Inside the try: a failed bind (port in use) must
                # still stop the watchdog below
                from ..obs import (TelemetryServer, checkpoint_check,
                                   get_flight_recorder, watchdog_check)
                from ..obs.tsdb import TimeSeriesStore, TsdbSampler
                srv = TelemetryServer(registry=reg, tracer=tracer,
                                      port=cfg.metrics_port)
                srv.set_identity(component="trainer")
                srv.attach_flight(get_flight_recorder())
                if self.watchdog is not None:
                    srv.add_check("watchdog",
                                  watchdog_check(self.watchdog))
                if self.checkpoints is not None:
                    srv.add_check("checkpoint",
                                  checkpoint_check(self.checkpoints))
                self.telemetry = srv.start()
                # monitoring-plane history (obs/tsdb.py): sample the
                # registry at a cadence for the whole fit, so flight
                # bundles carry the minutes before a trigger and
                # /snapshot shows the store's shape. Telemetry off =
                # zero threads, zero per-step cost.
                store = TimeSeriesStore()
                self._tsdb = TsdbSampler(
                    store, registry=reg,
                    interval_s=float(os.environ.get(
                        "DCNN_TSDB_INTERVAL", "1.0"))).start()
                srv.add_snapshot("tsdb", store.summary)
                get_flight_recorder().attach_tsdb(store)
                # goodput plane (obs/goodput.py): every sampler pass
                # attributes the trailing window of tracer spans to
                # buckets, publishes the gauges, classifies the
                # bottleneck, and — on an EWMA step-time breach or a
                # verdict flip — fires exactly one flight bundle +
                # xprof capture (obs/anomaly.py). /goodput serves the
                # live doc. No-op attribution when tracing is disabled
                # (empty span stream ⇒ zero-wall windows).
                from ..obs.anomaly import AnomalyMonitor
                from ..obs.goodput import GoodputMonitor
                from ..obs.rules import (RuleEngine, goodput_alert_rules,
                                         gray_failure_alert_rules,
                                         rules_check)
                self._goodput = GoodputMonitor(
                    tracer=tracer, registry=reg, store=store,
                    window_s=float(os.environ.get(
                        "DCNN_GOODPUT_WINDOW", "30.0")),
                    samples_per_step=cfg.batch_size,
                    anomaly=AnomalyMonitor(
                        registry=reg,
                        profile_dir=os.environ.get("DCNN_ANOMALY_XPROF"))
                ).attach(srv)
                self._tsdb.add_after_sample(self._goodput.poll)
                engine = RuleEngine(store, registry=reg)
                for rule in goodput_alert_rules():
                    engine.add_alert(rule)
                for rule in gray_failure_alert_rules():
                    engine.add_alert(rule)
                self._tsdb.add_after_sample(lambda s: engine.evaluate())
                srv.add_check("alerts", rules_check(engine))
                print(f"telemetry: {srv.url}/metrics /healthz /snapshot"
                      f" /goodput", flush=True)
            return self._fit_loop(ts, train_loader, val_loader, epochs,
                                  start_epoch, rng, best_val, tracer, reg)
        finally:
            if self._goodput is not None:
                self._goodput.close()  # end any open anomaly xprof capture
                self._goodput = None
            if self._tsdb is not None:
                # detach OUR store only: a later bundle must not dump
                # this dead run's frozen history as if it were current,
                # but another component's newer attachment must survive
                from ..obs import get_flight_recorder
                rec = get_flight_recorder()
                if getattr(rec, "_tsdb", None) is self._tsdb.store:
                    rec.attach_tsdb(None)
                self._tsdb.stop()
                self._tsdb = None
            if self.telemetry is not None:
                self.telemetry.stop()
                self.telemetry = None
            if self.watchdog is not None:
                self.watchdog.stop()
                self.watchdog = None
            if self.checkpoints is not None:
                # abandoning queued async saves would silently lose the
                # newest checkpoint; surface any saver-thread failure here
                self.checkpoints.wait()

    def _fit_loop(self, ts, train_loader, val_loader, epochs, start_epoch,
                  rng, best_val, tracer, reg) -> TrainState:
        cfg = self.config
        for epoch in range(start_epoch, epochs + 1):
            if self.watchdog is not None:
                self.watchdog.beat()
            if hasattr(train_loader, "shuffle"):
                train_loader.shuffle(epoch)
            epoch_rng = jax.random.fold_in(rng, epoch)
            t0 = time.perf_counter()
            with tracer.span("train.epoch", track="train", epoch=epoch):
                ts, train_loss, train_acc = self.train_epoch(
                    ts, train_loader, epoch_rng, epoch)
            dt = time.perf_counter() - t0
            # per-epoch telemetry rollups on the shared registry — O(1),
            # once per epoch, live whether or not tracing is enabled
            n_epoch = self._epoch_samples(train_loader)
            reg.counter("train_epochs_total", "completed epochs").inc()
            if n_epoch:
                reg.counter("train_samples_total",
                            "samples trained on").inc(n_epoch)
                reg.gauge("train_throughput_ips",
                          "last epoch samples/sec").set(n_epoch / dt)
            reg.histogram("train_epoch_seconds",
                          "wall per epoch").observe(dt)
            # epoch-boundary HBM watermark (obs/xla): a latched no-op on
            # backends without memory stats (CPU), gauges + peak elsewhere
            from ..obs.xla import sample_hbm
            sample_hbm(reg)
            reg.gauge("train_lr", "current learning rate").set(
                float(self.lr))
            reg.gauge("train_loss", "last epoch mean train loss").set(
                float(train_loss))

            if self.profiler is not None:
                # One profiled layer-by-layer fwd/bwd per epoch (device-synced
                # per layer — a measurement pass outside the jitted fast path,
                # reference print cadence: print_profiling_summary per run,
                # sequential.hpp:323-418).
                self.profiler.maybe_clear_per_batch()
                from ..data.device_dataset import (
                    DeviceDataset, ShardedDeviceDataset)
                _DD = (DeviceDataset, ShardedDeviceDataset)
                if isinstance(train_loader, _DD):
                    # resident mode: profile one decoded batch off the staged
                    # split (augmentation excluded — it's fused in-step there)
                    b = train_loader.batch_size
                    xb = (train_loader.x[:b].astype(jnp.float32)
                          * train_loader.scale)
                    yb = jax.nn.one_hot(train_loader.y[:b],
                                        train_loader.num_classes,
                                        dtype=jnp.float32)
                    batches = [(xb, yb)]
                else:
                    batches = train_loader
                for x, y in batches:
                    # LayerProfiler runs its own untimed warm pass per
                    # (model, shape, dtype, precision) before timing, so one
                    # profiled fwd/bwd here is steady-state.
                    if (self.multi_step is not None
                            and not isinstance(train_loader, _DD)):
                        # chunked loader yields [K, B, ...]: profile one batch
                        x, y = x[0], y[0]
                    from ..data.wire import decode_batch, wire_scale
                    x = decode_batch(jnp.asarray(x), wire_scale(train_loader))
                    logits, _ = self.profiler.profile_forward(
                        self.model, ts.params, ts.state, x,
                        training=True, rng=epoch_rng)
                    grad = jax.grad(
                        lambda out, _y=y: self.loss_fn(
                            out, jnp.asarray(_y)))(logits)
                    self.profiler.profile_backward(
                        self.model, ts.params, ts.state, x, grad,
                        rng=epoch_rng)
                    break
                print(self.profiler.summary(), flush=True)

            val_loss = val_acc = None
            if val_loader is not None:
                with tracer.span("train.eval", track="train", epoch=epoch):
                    val_loss, val_acc = evaluate_classification(
                        self.model, ts.params, ts.state, self.loss_fn,
                        val_loader, eval_step=self.eval_step)
                reg.gauge("train_val_acc", "last validation accuracy").set(
                    float(val_acc))
                # best-val snapshot (reference train.hpp:254-264)
                if cfg.snapshot_dir and val_acc > best_val:
                    best_val = val_acc
                    save_checkpoint(
                        os.path.join(cfg.snapshot_dir, self.model.name),
                        self.model, ts.params, ts.state, ts.opt_state,
                        self.optimizer,
                        {"epoch": epoch, "val_acc": val_acc, "val_loss": val_loss})

            self.history.append({"epoch": epoch, "train_loss": train_loss,
                                 "train_acc": train_acc, "val_loss": val_loss,
                                 "val_acc": val_acc, "seconds": dt, "lr": self.lr})
            msg = (f"epoch {epoch}/{epochs}: train loss {train_loss:.4f} "
                   f"acc {train_acc:.4f}")
            if val_acc is not None:
                msg += f" | val loss {val_loss:.4f} acc {val_acc:.4f}"
            print(msg + f" | {dt:.1f}s lr {self.lr:.2e}", flush=True)

            # LR schedule: scheduler wins; else multiplicative decay
            # (reference train.hpp:282-288). Per-batch schedulers already
            # stepped inside train_epoch.
            if self.scheduler is not None and cfg.scheduler_step == "epoch":
                self.lr = self.scheduler.step(val_loss if val_loss is not None else train_loss)
            elif cfg.lr_decay_factor != 1.0 and epoch % cfg.lr_decay_interval == 0:
                self.lr *= cfg.lr_decay_factor

            # periodic preemption-safe checkpoint (resilience/checkpoint.py),
            # AFTER the lr schedule so the saved lr is exactly what epoch+1
            # trains with — resume replays the uninterrupted run bit-exact.
            # Async mode's only step-loop cost is the device_get snapshot.
            if (self.checkpoints is not None and cfg.checkpoint_every
                    and epoch % cfg.checkpoint_every == 0):
                md = {"epoch": epoch, "lr": float(self.lr),
                      "history": self.history, "best_val": best_val,
                      "global_step": self._global_step}
                # fail fast on an earlier save that already failed — a run
                # whose checkpoints silently rot isn't preemption-safe
                self.checkpoints.check()
                save = (self.checkpoints.save_async if cfg.checkpoint_async
                        else self.checkpoints.save)
                save(epoch, self.model, ts.params, ts.state, ts.opt_state,
                     self.optimizer, md)
        return ts


def _make_regression_eval_step(model: Sequential, loss_fn: Callable):
    from ..core.precision import get_precision_mode
    return _make_regression_eval_step_cached(model, loss_fn, get_precision_mode())


@functools.lru_cache(maxsize=64)
def _make_regression_eval_step_cached(model: Sequential, loss_fn: Callable,
                                      _mode: str):
    @jax.jit
    def eval_step(params, state, x, y):
        pred, _ = model.apply(params, state, x, training=False)
        return loss_fn(pred, y)

    return eval_step


def evaluate_regression(model, params, state, loss_fn, loader) -> float:
    """Mean loss over a regression loader (reference
    ``validate_regression_model``, train.hpp:311-380)."""
    eval_step = _make_regression_eval_step(model, loss_fn)
    total_loss, total_n = 0.0, 0
    for x, y in loader:
        loss = eval_step(params, state, jnp.asarray(x), jnp.asarray(y))
        total_loss += float(loss) * x.shape[0]
        total_n += x.shape[0]
    return total_loss / max(total_n, 1)


def train_regression_model(model: Sequential, optimizer: Optimizer,
                           loss: Callable | str, train_loader, val_loader=None,
                           config: Optional[TrainingConfig] = None,
                           scheduler: Optional[Scheduler] = None,
                           key: Optional[jax.Array] = None) -> Tuple[TrainState, list]:
    """Regression twin of the classification loop (reference
    ``train_regression_model``, train.hpp:389-481)."""
    config = config or TrainingConfig()
    loss_fn = get_loss(loss) if isinstance(loss, str) else loss
    key = key if key is not None else jax.random.PRNGKey(config.seed)
    ts = create_train_state(model, optimizer, key)
    step = make_train_step(model, loss_fn, optimizer, config.num_microbatches)
    lr = config.learning_rate
    history = []
    sched = scheduler
    for epoch in range(1, config.epochs + 1):
        if hasattr(train_loader, "shuffle"):
            train_loader.shuffle(epoch)
        total_loss, total_n = 0.0, 0
        for bi, (x, y) in enumerate(train_loader):
            ts, loss_v, _ = step(ts, jnp.asarray(x), jnp.asarray(y),
                                 jax.random.fold_in(key, epoch * 100003 + bi), lr)
            total_loss += float(loss_v) * x.shape[0]
            total_n += x.shape[0]
        train_loss = total_loss / max(total_n, 1)
        val_loss = (evaluate_regression(model, ts.params, ts.state, loss_fn, val_loader)
                    if val_loader is not None else None)
        history.append({"epoch": epoch, "train_loss": train_loss, "val_loss": val_loss,
                        "lr": lr})
        msg = f"epoch {epoch}/{config.epochs}: train loss {train_loss:.6f}"
        if val_loss is not None:
            msg += f" | val loss {val_loss:.6f}"
        print(msg, flush=True)
        if sched is not None:
            lr = sched.step(val_loss if val_loss is not None else train_loss)
        elif config.lr_decay_factor != 1.0 and epoch % config.lr_decay_interval == 0:
            lr *= config.lr_decay_factor
    return ts, history


def train_classification_model(model: Sequential, optimizer: Optimizer,
                               loss: Callable | str, train_loader,
                               val_loader=None,
                               config: Optional[TrainingConfig] = None,
                               scheduler: Optional[Scheduler] = None,
                               key: Optional[jax.Array] = None) -> Tuple[TrainState, Trainer]:
    """Function-style entry matching the reference's
    ``train_classification_model`` (train.hpp:202)."""
    config = config or TrainingConfig()
    trainer = Trainer(model, optimizer, loss, config, scheduler)
    key = key if key is not None else jax.random.PRNGKey(config.seed)
    ts = create_train_state(model, optimizer, key)
    ts = trainer.fit(ts, train_loader, val_loader)
    return ts, trainer
