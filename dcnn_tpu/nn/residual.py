"""Residual block.

Reference equivalent: ``ResidualBlock``
(``include/nn/blocks_impl/residual_block.hpp:30-170``): main path = arbitrary
layer list, shortcut = identity or projection layer list,
``out = final_activation(F(x) + s(x))``. The reference caches the
pre-activation sum and input shape per microbatch for its hand-written
backward (:36-40, :145-152); here those residuals are owned by autodiff.

JSON serialization recurses into nested layer configs exactly like the
reference's recursive ``residual_block`` handling in the factory
(``layers.hpp:228-287``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax

from ..ops import activations as act_ops
from .factory import layer_from_config, register_layer
from .layer import Layer


@register_layer("residual_block")
class ResidualBlock(Layer):
    has_params = True

    def __init__(self, layers: Sequence[Layer], shortcut: Sequence[Layer] = (),
                 activation: str = "relu", name: Optional[str] = None):
        super().__init__(name)
        self.layers: List[Layer] = list(layers)
        self.shortcut: List[Layer] = list(shortcut)
        self.activation = activation.lower()
        if self.activation not in act_ops.ACTIVATIONS:
            raise ValueError(f"unknown activation {activation!r}")

    # -- functional interface --
    def init(self, key, input_shape):
        keys = jax.random.split(key, len(self.layers) + max(len(self.shortcut), 1))
        main_params, main_state = [], []
        shape = input_shape
        for i, layer in enumerate(self.layers):
            p, s = layer.init(keys[i], shape)
            main_params.append(p)
            main_state.append(s)
            shape = layer.output_shape(shape)
        short_params, short_state = [], []
        sshape = input_shape
        for i, layer in enumerate(self.shortcut):
            p, s = layer.init(keys[len(self.layers) + i], sshape)
            short_params.append(p)
            short_state.append(s)
            sshape = layer.output_shape(sshape)
        if sshape != shape:
            raise ValueError(
                f"{self.name}: main path output {shape} != shortcut output {sshape}")
        return ({"main": tuple(main_params), "shortcut": tuple(short_params)},
                {"main": tuple(main_state), "shortcut": tuple(short_state)})

    def apply(self, params, state, x, *, training=False, rng=None):
        h = x
        new_main = []
        for i, layer in enumerate(self.layers):
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
            h, s = layer.apply(params["main"][i], state["main"][i], h,
                               training=training, rng=sub_rng)
            new_main.append(s)
        s_out = x
        new_short = []
        for i, layer in enumerate(self.shortcut):
            sub_rng = jax.random.fold_in(rng, 1000 + i) if rng is not None else None
            s_out, s = layer.apply(params["shortcut"][i], state["shortcut"][i], s_out,
                                   training=training, rng=sub_rng)
            new_short.append(s)
        out = act_ops.ACTIVATIONS[self.activation](h + s_out)
        return out, {"main": tuple(new_main), "shortcut": tuple(new_short)}

    # -- metadata --
    def output_shape(self, input_shape):
        shape = input_shape
        for layer in self.layers:
            shape = layer.output_shape(shape)
        return shape

    def forward_complexity(self, input_shape):
        total = 0
        shape = input_shape
        for layer in self.layers:
            total += layer.forward_complexity(shape)
            shape = layer.output_shape(shape)
        sshape = input_shape
        for layer in self.shortcut:
            total += layer.forward_complexity(sshape)
            sshape = layer.output_shape(sshape)
        n = 1
        for d in shape:
            n *= d
        return total + 2 * n  # add + activation

    def param_count(self, input_shape):
        total = 0
        shape = input_shape
        for layer in self.layers:
            total += layer.param_count(shape)
            shape = layer.output_shape(shape)
        sshape = input_shape
        for layer in self.shortcut:
            total += layer.param_count(sshape)
            sshape = layer.output_shape(sshape)
        return total

    # -- config --
    def get_config(self) -> Dict[str, Any]:
        return {
            "type": self.type_name, "name": self.name,
            "activation": self.activation,
            "layers": [l.get_config() for l in self.layers],
            "shortcut": [l.get_config() for l in self.shortcut],
        }

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "ResidualBlock":
        return cls(
            layers=[layer_from_config(c) for c in cfg["layers"]],
            shortcut=[layer_from_config(c) for c in cfg.get("shortcut", [])],
            activation=cfg.get("activation", "relu"),
            name=cfg.get("name"),
        )
