"""NN library: layers, residual blocks, Sequential container, builder, factory.

Reference equivalent: ``include/nn/`` (SURVEY.md §2.3) — ``Layer<T>`` virtual
base with hand-written forward/backward, ``Sequential`` container,
``SequentialBuilder``/``LayerBuilder`` fluent API, string-keyed
``LayerFactory`` for JSON config round-trips.

TPU-native design: a layer is an immutable *spec* object; parameters and
mutable state (BN running stats, dropout counters) live in pytrees threaded
functionally through jit-compiled ``apply`` functions. Backward is autodiff —
the reference's hand-written ``backward`` methods have no analog because
``jax.vjp`` of ``apply`` *is* the backward, including the per-microbatch
activation caches the reference manages by hand (vjp residuals).
"""

from .layer import Layer, ParameterizedLayer, StatelessLayer
from .layers import (
    ActivationLayer, AvgPool2DLayer, BatchNormLayer, Conv2DLayer, DenseLayer,
    DropoutLayer, FlattenLayer, GroupNormLayer, MaxPool2DLayer,
)
from .attention_layer import MultiHeadAttentionLayer
from .residual import ResidualBlock
from .sequential import Sequential
from .factory import LayerFactory, register_layer, layer_from_config
from .builder import SequentialBuilder
from .fold import fold_batchnorm
from .quantize import (QuantConv2DLayer, QuantDenseLayer,
                       QuantMultiHeadAttentionLayer, quantize_model)
from .export import export_inference, load_inference

__all__ = [
    "Layer", "ParameterizedLayer", "StatelessLayer",
    "Conv2DLayer", "DenseLayer", "BatchNormLayer", "GroupNormLayer",
    "MaxPool2DLayer", "AvgPool2DLayer", "DropoutLayer", "FlattenLayer",
    "ActivationLayer", "ResidualBlock", "MultiHeadAttentionLayer",
    "Sequential", "SequentialBuilder",
    "LayerFactory", "register_layer", "layer_from_config",
    "fold_batchnorm",
    "QuantConv2DLayer", "QuantDenseLayer", "QuantMultiHeadAttentionLayer",
    "quantize_model",
    "export_inference", "load_inference",
]
