"""Fluent model builder with shape inference.

Reference equivalent: ``SequentialBuilder`` / ``LayerBuilder``
(``include/nn/sequential.hpp:1154-1341``, ``include/nn/layers.hpp:298-483``):
chainable ``.input().conv2d().batchnorm().activation()…`` calls tracking the
current shape, plus ``basic_residual_block`` (two 3×3 conv+BN, ReLU between;
projection shortcut when stride≠1 or channels change — sequential.hpp:1258)
and ``bottleneck_residual_block`` (1×1→3×3→1×1 conv+BN, biasless — :1293;
the reference uses BN eps 1e-3 inside bottleneck blocks, reproduced here).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from .layer import Layer
from .layers import (
    ActivationLayer, AvgPool2DLayer, BatchNormLayer, Conv2DLayer, DenseLayer,
    DropoutLayer, FlattenLayer, GroupNormLayer, LogSoftmaxLayer, MaxPool2DLayer,
)
from .residual import ResidualBlock
from .sequential import Sequential


class SequentialBuilder:
    def __init__(self, name: str = "sequential", data_format: str = "NCHW"):
        self.model = Sequential(name=name)
        self.data_format = data_format
        self._shape: Optional[Tuple[int, ...]] = None

    # -- shape tracking --
    def input(self, shape: Sequence[int]) -> "SequentialBuilder":
        """Per-sample input shape: (C,H,W) for NCHW, (H,W,C) for NHWC, or
        (features,)."""
        self._shape = tuple(int(d) for d in shape)
        self.model.input_shape = self._shape
        return self

    @property
    def current_shape(self) -> Tuple[int, ...]:
        if self._shape is None:
            raise RuntimeError("call .input(shape) first")
        return self._shape

    def _channels(self) -> int:
        shape = self.current_shape
        return shape[0] if self.data_format == "NCHW" else shape[-1]

    def add_layer(self, layer: Layer) -> "SequentialBuilder":
        shape = self.current_shape
        self.model.add(layer)
        self._shape = layer.output_shape(shape)
        return self

    # -- layer shorthands (reference builder methods) --
    def conv2d(self, out_channels: int, kernel_size, stride=1, padding=0,
               use_bias: bool = True, name: str = "") -> "SequentialBuilder":
        return self.add_layer(Conv2DLayer(
            out_channels, kernel_size, stride, padding, use_bias,
            in_channels=self._channels(), data_format=self.data_format,
            name=name or f"conv2d_{len(self.model)}"))

    def dense(self, out_features: int, use_bias: bool = True, name: str = "") -> "SequentialBuilder":
        return self.add_layer(DenseLayer(
            out_features, use_bias, in_features=self.current_shape[0],
            name=name or f"dense_{len(self.model)}"))

    def batchnorm(self, epsilon: float = 1e-5, momentum: float = 0.1,
                  affine: bool = True, name: str = "") -> "SequentialBuilder":
        return self.add_layer(BatchNormLayer(
            num_features=self._channels() if len(self.current_shape) == 3 else self.current_shape[0],
            epsilon=epsilon, momentum=momentum, affine=affine,
            data_format=self.data_format, name=name or f"batchnorm_{len(self.model)}"))

    def groupnorm(self, num_groups: int, epsilon: float = 1e-5, affine: bool = True,
                  name: str = "") -> "SequentialBuilder":
        return self.add_layer(GroupNormLayer(
            num_groups, num_channels=self._channels(), epsilon=epsilon, affine=affine,
            data_format=self.data_format, name=name or f"groupnorm_{len(self.model)}"))

    def activation(self, activation_name: str, name: str = "") -> "SequentialBuilder":
        return self.add_layer(ActivationLayer(
            activation_name, name=name or f"activation_{len(self.model)}"))

    def maxpool2d(self, kernel_size, stride=None, padding=0, name: str = "") -> "SequentialBuilder":
        return self.add_layer(MaxPool2DLayer(
            kernel_size, stride, padding, data_format=self.data_format,
            name=name or f"maxpool2d_{len(self.model)}"))

    def avgpool2d(self, kernel_size, stride=None, padding=0, name: str = "") -> "SequentialBuilder":
        return self.add_layer(AvgPool2DLayer(
            kernel_size, stride, padding, data_format=self.data_format,
            name=name or f"avgpool2d_{len(self.model)}"))

    def dropout(self, rate: float, name: str = "") -> "SequentialBuilder":
        return self.add_layer(DropoutLayer(rate, name=name or f"dropout_{len(self.model)}"))

    def flatten(self, name: str = "") -> "SequentialBuilder":
        return self.add_layer(FlattenLayer(name=name or f"flatten_{len(self.model)}"))

    def log_softmax(self, name: str = "") -> "SequentialBuilder":
        return self.add_layer(LogSoftmaxLayer(name=name or f"log_softmax_{len(self.model)}"))

    def residual(self, layers: Sequence[Layer], shortcut: Sequence[Layer] = (),
                 activation: str = "relu", name: str = "") -> "SequentialBuilder":
        return self.add_layer(ResidualBlock(
            layers, shortcut, activation, name=name or f"residual_block_{len(self.model)}"))

    # -- residual-block helpers (reference sequential.hpp:1253-1320) --
    def basic_residual_block(self, in_channels: int, out_channels: int, stride: int = 1,
                             name: str = "") -> "SequentialBuilder":
        df = self.data_format
        main = [
            Conv2DLayer(out_channels, 3, stride, 1, True, in_channels, df, name="conv0"),
            BatchNormLayer(out_channels, 1e-5, 0.1, True, df, name="bn0"),
            ActivationLayer("relu", name="relu0"),
            Conv2DLayer(out_channels, 3, 1, 1, True, out_channels, df, name="conv1"),
            BatchNormLayer(out_channels, 1e-5, 0.1, True, df, name="bn1"),
        ]
        shortcut = []
        if stride != 1 or in_channels != out_channels:
            shortcut = [
                Conv2DLayer(out_channels, 1, stride, 0, False, in_channels, df, name="proj"),
                BatchNormLayer(out_channels, 1e-5, 0.1, True, df, name="proj_bn"),
            ]
        return self.residual(main, shortcut, "relu",
                             name=name or f"basic_residual_block_{len(self.model)}")

    def bottleneck_residual_block(self, in_channels: int, mid_channels: int,
                                  out_channels: int, stride: int = 1,
                                  name: str = "") -> "SequentialBuilder":
        df = self.data_format
        # Reference bottleneck uses biasless convs and BN eps 1e-3
        # (sequential.hpp:1300-1310).
        main = [
            Conv2DLayer(mid_channels, 1, 1, 0, False, in_channels, df, name="conv0"),
            BatchNormLayer(mid_channels, 1e-3, 0.1, True, df, name="bn0"),
            ActivationLayer("relu", name="relu0"),
            Conv2DLayer(mid_channels, 3, stride, 1, False, mid_channels, df, name="conv1"),
            BatchNormLayer(mid_channels, 1e-3, 0.1, True, df, name="bn1"),
            ActivationLayer("relu", name="relu1"),
            Conv2DLayer(out_channels, 1, 1, 0, False, mid_channels, df, name="conv2"),
            BatchNormLayer(out_channels, 1e-3, 0.1, True, df, name="bn2"),
        ]
        shortcut = []
        if stride != 1 or in_channels != out_channels:
            shortcut = [
                Conv2DLayer(out_channels, 1, stride, 0, False, in_channels, df, name="proj"),
                BatchNormLayer(out_channels, 1e-3, 0.1, True, df, name="proj_bn"),
            ]
        return self.residual(main, shortcut, "relu",
                             name=name or f"bottleneck_residual_block_{len(self.model)}")

    def build(self) -> Sequential:
        if self._shape is None:
            raise RuntimeError("Input shape must be set before building model. Use .input().")
        return self.model
