"""Sequential model container.

Reference equivalent: ``Sequential<T>``
(``include/nn/sequential.hpp:39-1152``): ordered layer container with
double-buffered forward/backward, ``split(partitions)`` → stage models
(:967-986), JSON architecture (de)serialization (:1001-1125), binary weight
save/load (:832-915), and per-layer profiling maps (:54-55).

TPU-native differences: forward is a pure function over a params/state pytree
(the reference's ping-pong buffer discipline is XLA's job now); backward is
``jax.grad``; weights save/load lives in ``dcnn_tpu.train.checkpoint``
(checkpoints include optimizer state — an improvement over the reference,
which drops it, SURVEY.md §5.4).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from .factory import layer_from_config
from .layer import Layer, Shape

Params = Tuple[Dict[str, Any], ...]
State = Tuple[Dict[str, Any], ...]


class Sequential:
    def __init__(self, layers: Sequence[Layer] = (), name: str = "sequential",
                 input_shape: Optional[Shape] = None):
        self.name = name
        self.layers: List[Layer] = []
        self.input_shape: Optional[Tuple[int, ...]] = (
            tuple(input_shape) if input_shape is not None else None)
        for l in layers:
            self.add(l)

    # -- construction --
    def add(self, layer: Layer) -> "Sequential":
        base = layer.name
        names = {l.name for l in self.layers}
        if base in names:
            i = 1
            while f"{base}_{i}" in names:
                i += 1
            layer.name = f"{base}_{i}"
        self.layers.append(layer)
        return self

    def __len__(self) -> int:
        return len(self.layers)

    def __getitem__(self, idx):
        return self.layers[idx]

    # -- functional interface --
    def init(self, key: jax.Array, input_shape: Optional[Shape] = None) -> Tuple[Params, State]:
        """Initialize all layer params/state. ``input_shape`` is per-sample
        (C,H,W)/(features,), like the reference builder's input shape."""
        shape = tuple(input_shape) if input_shape is not None else self.input_shape
        if shape is None:
            raise ValueError("input_shape required (not set at construction)")
        self.input_shape = shape
        keys = jax.random.split(key, max(len(self.layers), 1))
        params, state = [], []
        for i, layer in enumerate(self.layers):
            p, s = layer.init(keys[i], shape)
            params.append(p)
            state.append(s)
            shape = layer.output_shape(shape)
        return tuple(params), tuple(state)

    def apply(self, params: Params, state: State, x: jax.Array, *,
              training: bool = False, rng: Optional[jax.Array] = None,
              ) -> Tuple[jax.Array, State]:
        """Chain layers (reference forward loop ``sequential.hpp:459-466``).
        Per-layer rng derived with ``fold_in(rng, i)`` so dropout masks are
        deterministic given one step key.

        Under the ``bf16`` precision mode (core.precision) the input and each
        layer's params are cast to bfloat16 at point of use; layer state (BN
        running statistics) stays fp32, and batch_norm computes its reductions
        in fp32 internally."""
        from ..core.precision import cast_to_compute

        h = cast_to_compute(x)
        new_state = []
        for i, layer in enumerate(self.layers):
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
            # named_scope tags every op with its layer in profiler traces, so
            # xprof framework-op stats aggregate per layer (the fused-step
            # ground truth the replay profiler is compared against in
            # RESULTS.md "profiling skew"); zero runtime cost outside tracing
            with jax.named_scope(getattr(layer, "name", None)
                                 or f"layer{i}"):
                h, s = layer.apply(cast_to_compute(params[i]), state[i], h,
                                   training=training, rng=sub_rng)
            new_state.append(s)
        return h, tuple(new_state)

    def __call__(self, params, state, x, **kw):
        return self.apply(params, state, x, **kw)

    # -- shape / cost metadata --
    def output_shape(self, input_shape: Optional[Shape] = None) -> Shape:
        shape = tuple(input_shape) if input_shape is not None else self.input_shape
        if shape is None:
            raise ValueError("input_shape unknown")
        for layer in self.layers:
            shape = layer.output_shape(shape)
        return shape

    def layer_shapes(self, input_shape: Optional[Shape] = None) -> List[Shape]:
        """Per-layer *input* shapes; index i is what layer i receives."""
        shape = tuple(input_shape) if input_shape is not None else self.input_shape
        if shape is None:
            raise ValueError("input_shape unknown")
        shapes = []
        for layer in self.layers:
            shapes.append(shape)
            shape = layer.output_shape(shape)
        return shapes

    def forward_complexity(self, input_shape: Optional[Shape] = None) -> int:
        total = 0
        for layer, shape in zip(self.layers, self.layer_shapes(input_shape)):
            total += layer.forward_complexity(shape)
        return total

    def param_count(self, input_shape: Optional[Shape] = None) -> int:
        total = 0
        for layer, shape in zip(self.layers, self.layer_shapes(input_shape)):
            total += layer.param_count(shape)
        return total

    # -- pipeline split (reference sequential.hpp:967-986) --
    def split(self, partitions: Sequence[Tuple[int, int]]) -> List["Sequential"]:
        """Split into stage models by [start, end) layer ranges, as produced by
        a Partitioner. Stage input shapes are propagated so each stage can be
        initialized/deployed standalone (the reference ships stage configs as
        JSON to workers, ``coordinator.hpp:524-555``)."""
        stages = []
        shapes = self.layer_shapes() if self.input_shape is not None else None
        for si, (start, end) in enumerate(partitions):
            if not (0 <= start < end <= len(self.layers)):
                raise ValueError(f"bad partition range ({start}, {end})")
            stage = Sequential(name=f"{self.name}_stage{si}")
            stage.layers = self.layers[start:end]
            if shapes is not None:
                stage.input_shape = shapes[start]
            stages.append(stage)
        return stages

    def split_params(self, params: Sequence, partitions: Sequence[Tuple[int, int]]) -> List[Tuple]:
        """Partition an existing params (or state) tuple alongside ``split``."""
        return [tuple(params[start:end]) for (start, end) in partitions]

    # -- config round-trip (reference sequential.hpp:1001-1125) --
    def get_config(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "input_shape": list(self.input_shape) if self.input_shape else None,
            "layers": [l.get_config() for l in self.layers],
        }

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "Sequential":
        model = cls(name=cfg.get("name", "sequential"),
                    input_shape=tuple(cfg["input_shape"]) if cfg.get("input_shape") else None)
        for lc in cfg["layers"]:
            model.add(layer_from_config(lc))
        return model

    # -- introspection --
    def summary(self, input_shape: Optional[Shape] = None) -> str:
        """Printable architecture table (reference ``print_profiling_summary``
        prints a similar per-layer table, sequential.hpp:323-418)."""
        shapes = self.layer_shapes(input_shape)
        lines = [f"Sequential '{self.name}'",
                 f"{'#':>3} {'layer':<24} {'output shape':<20} {'params':>12} {'MFLOPs':>10}"]
        total_p = 0
        for i, (layer, shape) in enumerate(zip(self.layers, shapes)):
            out = layer.output_shape(shape)
            p = layer.param_count(shape)
            fl = layer.forward_complexity(shape) / 1e6
            total_p += p
            lines.append(f"{i:>3} {layer.name:<24} {str(out):<20} {p:>12,} {fl:>10.2f}")
        lines.append(f"total params: {total_p:,}")
        return "\n".join(lines)

    def __repr__(self):
        return f"Sequential(name={self.name!r}, layers={len(self.layers)})"
