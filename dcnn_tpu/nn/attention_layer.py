"""Multi-head self-attention layer.

No reference analog (the reference is CNN-only, SURVEY.md §5.7); provided so
attention/long-context models are first-class citizens of the same
``Sequential``/factory/pipeline machinery as the CNN layers. Per-sample
shape convention: ``(S, E)`` — sequence length × embed dim (batched apply
sees ``(B, S, E)``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.precision import get_precision
from ..ops.attention import attention, blockwise_attention, flash_attention
from . import initializers as init
from .factory import register_layer
from .layer import ParameterizedLayer


class MHAGeometryMixin:
    """Geometry/config + the float attention core shared by
    ``MultiHeadAttentionLayer`` and its int8 PTQ twin (``nn/quantize.py``) —
    same non-subclassing rationale as ``Conv2DGeometryMixin``."""

    def _set_mha_geometry(self, num_heads, embed_dim, causal, impl,
                          use_bias):
        if impl not in ("flash", "blockwise", "naive"):
            raise ValueError(f"unknown attention impl {impl!r}")
        self.num_heads = int(num_heads)
        self.embed_dim = embed_dim
        self.causal = bool(causal)
        self.impl = impl
        self.use_bias = bool(use_bias)

    def _embed(self, input_shape) -> int:
        if len(input_shape) != 2:
            raise ValueError(f"{self.name}: attention expects (S, E) input, "
                             f"got {input_shape}")
        e = input_shape[1]
        if self.embed_dim is not None and self.embed_dim != e:
            raise ValueError(f"{self.name}: expected embed dim "
                             f"{self.embed_dim}, got {e}")
        if e % self.num_heads:
            raise ValueError(f"{self.name}: embed dim {e} not divisible by "
                             f"{self.num_heads} heads")
        return e

    def _attend(self, q, k, v):
        """(B, S, E) projections → heads → scaled-dot-product → (B, S, E)."""
        b_, s, e = q.shape
        h, dh = self.num_heads, e // self.num_heads

        def heads(t):
            return t.reshape(b_, s, h, dh).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if self.impl == "naive":
            o = attention(q, k, v, causal=self.causal)
        elif self.impl == "blockwise":
            o = blockwise_attention(q, k, v, causal=self.causal)
        else:
            o = flash_attention(q, k, v, causal=self.causal)
        return o.transpose(0, 2, 1, 3).reshape(b_, s, e)

    def output_shape(self, input_shape):
        return input_shape

    def forward_complexity(self, input_shape):
        s, e = input_shape
        return 4 * 2 * s * e * e + 2 * 2 * s * s * e  # projections + scores·v

    def param_count(self, input_shape):
        e = input_shape[1]
        return 4 * e * e + (4 * e if self.use_bias else 0)

    def get_config(self):
        return {"type": self.type_name, "name": self.name,
                "num_heads": self.num_heads, "embed_dim": self.embed_dim,
                "causal": self.causal, "impl": self.impl,
                "use_bias": self.use_bias}


@register_layer("multi_head_attention")
class MultiHeadAttentionLayer(MHAGeometryMixin, ParameterizedLayer):
    """Self-attention: qkv projections → scaled-dot-product → out projection.

    ``impl``: ``"flash"`` (Pallas kernel, default), ``"blockwise"``
    (lax.scan online softmax), or ``"naive"`` (materialised scores — the
    numerics oracle). All exact; choice affects memory/speed only.
    """

    def __init__(self, num_heads: int, embed_dim: Optional[int] = None,
                 causal: bool = False, impl: str = "flash",
                 use_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self._set_mha_geometry(num_heads, embed_dim, causal, impl, use_bias)

    def init(self, key, input_shape):
        e = self._embed(input_shape)
        self.embed_dim = e
        keys = jax.random.split(key, 8)
        def lin(i, shape, fan_in):
            return init.kaiming_uniform(keys[i], shape, fan_in)
        params = {
            "wq": lin(0, (e, e), e), "wk": lin(1, (e, e), e),
            "wv": lin(2, (e, e), e), "wo": lin(3, (e, e), e),
        }
        if self.use_bias:
            params.update({
                "bq": lin(4, (e,), e), "bk": lin(5, (e,), e),
                "bv": lin(6, (e,), e), "bo": lin(7, (e,), e),
            })
        return params, {}

    def _project(self, x, w, b):
        y = jnp.matmul(x, w, precision=get_precision())
        return y + b if b is not None else y

    def _qkv(self, params, x):
        """The three input projections (B, S, E) — also the calibration
        surface for the PTQ twin, which needs the attention-core input."""
        get = params.get
        return (self._project(x, params["wq"], get("bq")),
                self._project(x, params["wk"], get("bk")),
                self._project(x, params["wv"], get("bv")))

    def apply(self, params, state, x, *, training=False, rng=None):
        q, k, v = self._qkv(params, x)
        o = self._attend(q, k, v)
        return self._project(o, params["wo"], params.get("bo")), state

    # -- single-token decode path (serve/decode.py) ------------------------
    def decode_qkv(self, params, x_t):
        """Single-token projections: ``x_t (B, E)`` → ``(q, k, v)`` each
        ``(B, E)``. The ``k``/``v`` rows are what a decode step writes into
        its KV cache; ``q`` goes to :meth:`decode_attend`."""
        get = params.get
        return (self._project(x_t, params["wq"], get("bq")),
                self._project(x_t, params["wk"], get("bk")),
                self._project(x_t, params["wv"], get("bv")))

    def decode_attend(self, params, q_t, k_ctx, v_ctx, positions):
        """One causal decode step against a materialized KV context.

        ``q_t (B, E)`` attends to ``k_ctx``/``v_ctx (B, T, E)`` at absolute
        position ``positions (B,)`` int32: key slot ``j`` participates iff
        ``j <= position`` (the causal mask a token at ``position`` sees).
        Rows with ``position < 0`` are fully masked and return 0 — the
        inactive-slot convention, same zero-mass rule as
        :func:`~dcnn_tpu.ops.attention.attention`. Returns ``y_t (B, E)``
        after the out projection.
        """
        from ..ops.attention import NEG_INF

        b_, t, e = k_ctx.shape
        h, dh = self.num_heads, e // self.num_heads
        q = q_t.reshape(b_, h, dh)
        k = k_ctx.reshape(b_, t, h, dh).transpose(0, 2, 1, 3)
        v = v_ctx.reshape(b_, t, h, dh).transpose(0, 2, 1, 3)
        scale = dh ** -0.5
        s = jnp.einsum("bhd,bhtd->bht", q, k,
                       precision=get_precision()) * scale
        valid = (jnp.arange(t, dtype=positions.dtype)[None, :]
                 <= positions[:, None])           # (B, T), False row if pos<0
        s = jnp.where(valid[:, None, :], s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        # zero fully-masked rows (softmax of all-NEG_INF is uniform 1/T)
        w = jnp.where(valid[:, None, :], w, 0.0)
        o = jnp.einsum("bht,bhtd->bhd", w, v, precision=get_precision())
        return self._project(o.reshape(b_, e), params["wo"],
                             params.get("bo"))

    def decode(self, params, state, x_t, k_cache, v_cache, positions):
        """Single-token decode through an explicit dense KV cache: write
        this token's K/V rows at ``positions``, attend over the prefix,
        return ``(y_t, k_cache, v_cache)``. ``x_t (B, E)``; caches
        ``(B, T, E)``; ``positions (B,)`` int32 (``-1`` = inactive row:
        nothing attends, and the write lands on slot 0 of an all-masked
        row, which nothing ever reads). The paged serving path
        (``serve/decode.py``) does the same dance against a page pool."""
        q, k_t, v_t = self.decode_qkv(params, x_t)
        b_ = x_t.shape[0]
        rows = jnp.arange(b_)
        pos_c = jnp.maximum(positions, 0)
        k_cache = k_cache.at[rows, pos_c].set(k_t)
        v_cache = v_cache.at[rows, pos_c].set(v_t)
        return (self.decode_attend(params, q, k_cache, v_cache, positions),
                k_cache, v_cache)
