"""Multi-head self-attention layer.

No reference analog (the reference is CNN-only, SURVEY.md §5.7); provided so
attention/long-context models are first-class citizens of the same
``Sequential``/factory/pipeline machinery as the CNN layers. Per-sample
shape convention: ``(S, E)`` — sequence length × embed dim (batched apply
sees ``(B, S, E)``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.precision import get_precision
from ..ops.attention import attention, blockwise_attention, flash_attention
from . import initializers as init
from .factory import register_layer
from .layer import ParameterizedLayer


@register_layer("multi_head_attention")
class MultiHeadAttentionLayer(ParameterizedLayer):
    """Self-attention: qkv projections → scaled-dot-product → out projection.

    ``impl``: ``"flash"`` (Pallas kernel, default), ``"blockwise"``
    (lax.scan online softmax), or ``"naive"`` (materialised scores — the
    numerics oracle). All exact; choice affects memory/speed only.
    """

    def __init__(self, num_heads: int, embed_dim: Optional[int] = None,
                 causal: bool = False, impl: str = "flash",
                 use_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        if impl not in ("flash", "blockwise", "naive"):
            raise ValueError(f"unknown attention impl {impl!r}")
        self.num_heads = int(num_heads)
        self.embed_dim = embed_dim
        self.causal = bool(causal)
        self.impl = impl
        self.use_bias = bool(use_bias)

    def init(self, key, input_shape):
        if len(input_shape) != 2:
            raise ValueError(f"{self.name}: attention expects (S, E) input, "
                             f"got {input_shape}")
        e = input_shape[1]
        if self.embed_dim is not None and self.embed_dim != e:
            raise ValueError(f"{self.name}: expected embed dim "
                             f"{self.embed_dim}, got {e}")
        self.embed_dim = e
        if e % self.num_heads:
            raise ValueError(f"{self.name}: embed dim {e} not divisible by "
                             f"{self.num_heads} heads")
        keys = jax.random.split(key, 8)
        def lin(i, shape, fan_in):
            return init.kaiming_uniform(keys[i], shape, fan_in)
        params = {
            "wq": lin(0, (e, e), e), "wk": lin(1, (e, e), e),
            "wv": lin(2, (e, e), e), "wo": lin(3, (e, e), e),
        }
        if self.use_bias:
            params.update({
                "bq": lin(4, (e,), e), "bk": lin(5, (e,), e),
                "bv": lin(6, (e,), e), "bo": lin(7, (e,), e),
            })
        return params, {}

    def _project(self, x, w, b):
        y = jnp.matmul(x, w, precision=get_precision())
        return y + b if b is not None else y

    def apply(self, params, state, x, *, training=False, rng=None):
        b_, s, e = x.shape
        h, dh = self.num_heads, e // self.num_heads
        get = params.get
        q = self._project(x, params["wq"], get("bq"))
        k = self._project(x, params["wk"], get("bk"))
        v = self._project(x, params["wv"], get("bv"))
        # (B, S, E) -> (B, H, S, Dh)
        def heads(t):
            return t.reshape(b_, s, h, dh).transpose(0, 2, 1, 3)
        q, k, v = heads(q), heads(k), heads(v)
        if self.impl == "naive":
            o = attention(q, k, v, causal=self.causal)
        elif self.impl == "blockwise":
            o = blockwise_attention(q, k, v, causal=self.causal)
        else:
            o = flash_attention(q, k, v, causal=self.causal)
        o = o.transpose(0, 2, 1, 3).reshape(b_, s, e)
        return self._project(o, params["wo"], get("bo")), state

    def output_shape(self, input_shape):
        return input_shape

    def forward_complexity(self, input_shape):
        s, e = input_shape
        return 4 * 2 * s * e * e + 2 * 2 * s * s * e  # projections + scores·v

    def param_count(self, input_shape):
        e = input_shape[1]
        return 4 * e * e + (4 * e if self.use_bias else 0)

    def get_config(self):
        return {"type": self.type_name, "name": self.name,
                "num_heads": self.num_heads, "embed_dim": self.embed_dim,
                "causal": self.causal, "impl": self.impl,
                "use_bias": self.use_bias}
