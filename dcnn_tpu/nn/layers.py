"""Concrete layers.

Reference equivalents (SURVEY.md §2.3): ``Conv2DLayer``
(``conv2d_layer.tpp``), ``DenseLayer`` (``dense_layer.tpp``),
``BatchNormLayer`` (``batchnorm_layer.tpp``), ``GroupNormLayer``
(``groupnorm_layer.tpp``), ``MaxPool2DLayer``/``AvgPool2DLayer``
(``maxpool2d_layer.tpp``/``avgpool2d_layer.tpp``), ``DropoutLayer``,
``FlattenLayer``, ``ActivationLayer``.

Parity choices: Kaiming-uniform init with bound 1/√fan_in for weights *and*
biases (conv2d_layer.tpp:71-85); BN eps 1e-5 / momentum 0.1; GN eps 1e-5;
LeakyReLU 0.01 / ELU 1.0 defaults. ``in_channels``/``in_features`` may be
omitted and are inferred at ``init`` from the input shape (the reference's
SequentialBuilder does the same inference at build time,
``sequential.hpp:1154``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.precision import get_precision
from ..ops import activations as act_ops
from ..ops import conv as conv_ops
from ..ops import norm as norm_ops
from ..ops import pool as pool_ops
from . import initializers as init
from .factory import register_layer
from .layer import ParameterizedLayer, Shape, StatelessLayer


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else (int(v[0]), int(v[1]))


def _feature_axis(data_format: str) -> int:
    return 0 if data_format == "NCHW" else 2


class Conv2DGeometryMixin:
    """Geometry/config contract shared by ``Conv2DLayer`` and its int8 PTQ
    twin (``nn/quantize.py``) — one implementation so the two cannot drift.
    (The twin is deliberately NOT a subclass of ``Conv2DLayer``: the
    isinstance walks in fold/quantize must not re-capture it.)"""

    def _set_conv_geometry(self, out_channels, kernel_size, stride, padding,
                           use_bias, in_channels, data_format):
        self.out_channels = int(out_channels)
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.use_bias = bool(use_bias)
        self.in_channels = in_channels
        self.data_format = data_format

    def _cin(self, input_shape: Shape) -> int:
        cin = input_shape[_feature_axis(self.data_format)]
        if self.in_channels is not None and self.in_channels != cin:
            raise ValueError(f"{self.name}: expected {self.in_channels} input channels, got {cin}")
        return cin

    def output_shape(self, input_shape):
        if self.data_format == "NCHW":
            _, h, w = input_shape
            oh, ow = conv_ops.conv2d_output_shape((h, w), self.kernel_size, self.stride, self.padding)
            return (self.out_channels, oh, ow)
        h, w, _ = input_shape
        oh, ow = conv_ops.conv2d_output_shape((h, w), self.kernel_size, self.stride, self.padding)
        return (oh, ow, self.out_channels)

    def forward_complexity(self, input_shape):
        cin = input_shape[_feature_axis(self.data_format)]
        out = self.output_shape(input_shape)
        oh, ow = (out[1], out[2]) if self.data_format == "NCHW" else (out[0], out[1])
        return 2 * self.out_channels * cin * self.kernel_size[0] * self.kernel_size[1] * oh * ow

    def param_count(self, input_shape):
        cin = input_shape[_feature_axis(self.data_format)]
        n = self.out_channels * cin * self.kernel_size[0] * self.kernel_size[1]
        return n + (self.out_channels if self.use_bias else 0)

    def get_config(self):
        return {
            "type": self.type_name, "name": self.name,
            "out_channels": self.out_channels, "kernel_size": list(self.kernel_size),
            "stride": list(self.stride), "padding": list(self.padding),
            "use_bias": self.use_bias, "in_channels": self.in_channels,
            "data_format": self.data_format,
        }


class DenseGeometryMixin:
    """Geometry/config contract shared by ``DenseLayer`` and its int8 PTQ
    twin (same non-subclassing rationale as ``Conv2DGeometryMixin``)."""

    def _set_dense_geometry(self, out_features, use_bias, in_features):
        self.out_features = int(out_features)
        self.use_bias = bool(use_bias)
        self.in_features = in_features

    def _fan_in(self, input_shape: Shape) -> int:
        if len(input_shape) != 1:
            raise ValueError(f"{self.name}: dense expects flat input, got {input_shape}; "
                             "add a Flatten layer first")
        fan_in = input_shape[0]
        if self.in_features is not None and self.in_features != fan_in:
            raise ValueError(f"{self.name}: expected {self.in_features} features, got {fan_in}")
        return fan_in

    def output_shape(self, input_shape):
        return (self.out_features,)

    def forward_complexity(self, input_shape):
        return 2 * input_shape[0] * self.out_features

    def param_count(self, input_shape):
        return input_shape[0] * self.out_features + (self.out_features if self.use_bias else 0)

    def get_config(self):
        return {"type": self.type_name, "name": self.name,
                "out_features": self.out_features, "use_bias": self.use_bias,
                "in_features": self.in_features}


@register_layer("conv2d")
class Conv2DLayer(Conv2DGeometryMixin, ParameterizedLayer):
    """2-D convolution (reference ``conv2d_layer.tpp:140-241``): on TPU the
    im2col→GEMM→cnhw→nchw pipeline collapses to one MXU conv."""

    def __init__(self, out_channels: int, kernel_size, stride=1, padding=0,
                 use_bias: bool = True, in_channels: Optional[int] = None,
                 data_format: str = "NCHW", name: Optional[str] = None):
        super().__init__(name)
        self._set_conv_geometry(out_channels, kernel_size, stride, padding,
                                use_bias, in_channels, data_format)

    def init(self, key, input_shape):
        cin = self._cin(input_shape)
        self.in_channels = cin
        fan_in = init.conv_fan_in(cin, self.kernel_size)
        wkey, bkey = jax.random.split(key)
        params = {"w": init.kaiming_uniform(
            wkey, (self.out_channels, cin, *self.kernel_size), fan_in)}
        if self.use_bias:
            params["b"] = init.kaiming_uniform(bkey, (self.out_channels,), fan_in)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        y = conv_ops.conv2d(
            x, params["w"], params.get("b"),
            stride=self.stride, padding=self.padding, data_format=self.data_format)
        return y, state


@register_layer("dense")
class DenseLayer(DenseGeometryMixin, ParameterizedLayer):
    """Fully-connected layer (reference ``dense_layer.tpp``): y = x·Wᵀ + b.
    Weight stored (out, in) like the reference so checkpoints are auditable."""

    def __init__(self, out_features: int, use_bias: bool = True,
                 in_features: Optional[int] = None, name: Optional[str] = None):
        super().__init__(name)
        self._set_dense_geometry(out_features, use_bias, in_features)

    def init(self, key, input_shape):
        fan_in = self._fan_in(input_shape)
        self.in_features = fan_in
        wkey, bkey = jax.random.split(key)
        params = {"w": init.kaiming_uniform(wkey, (self.out_features, fan_in), fan_in)}
        if self.use_bias:
            params["b"] = init.kaiming_uniform(bkey, (self.out_features,), fan_in)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        y = jnp.matmul(x, params["w"].T, precision=get_precision())
        if self.use_bias:
            y = y + params["b"]
        return y, state


@register_layer("batchnorm")
class BatchNormLayer(ParameterizedLayer):
    """BatchNorm2d (reference ``batchnorm_layer.tpp``; eps 1e-5, momentum 0.1).
    Running stats live in ``state`` and are updated functionally."""

    def __init__(self, num_features: Optional[int] = None, epsilon: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 data_format: str = "NCHW", name: Optional[str] = None):
        super().__init__(name)
        self.num_features = num_features
        self.epsilon = float(epsilon)
        self.momentum = float(momentum)
        self.affine = bool(affine)
        self.data_format = data_format

    def init(self, key, input_shape):
        c = input_shape[_feature_axis(self.data_format)] if len(input_shape) == 3 else input_shape[0]
        if self.num_features is not None and self.num_features != c:
            raise ValueError(f"{self.name}: expected {self.num_features} features, got {c}")
        self.num_features = c
        params = {"gamma": init.ones((c,)), "beta": init.zeros((c,))} if self.affine else {}
        state = {"running_mean": init.zeros((c,)), "running_var": init.ones((c,))}
        return params, state

    def apply(self, params, state, x, *, training=False, rng=None):
        gamma = params.get("gamma", jnp.ones((x.shape[1 if self.data_format == 'NCHW' else -1],), x.dtype))
        beta = params.get("beta", jnp.zeros_like(gamma))
        if x.ndim == 2:
            # dense BN: treat features as channels over (N,)
            y, new_mean, new_var = norm_ops.batch_norm(
                x[:, :, None, None] if self.data_format == "NCHW" else x[:, None, None, :],
                gamma, beta, state["running_mean"], state["running_var"],
                training=training, momentum=self.momentum, eps=self.epsilon,
                data_format=self.data_format)
            y = y.reshape(x.shape)
        else:
            y, new_mean, new_var = norm_ops.batch_norm(
                x, gamma, beta, state["running_mean"], state["running_var"],
                training=training, momentum=self.momentum, eps=self.epsilon,
                data_format=self.data_format)
        return y, {"running_mean": new_mean, "running_var": new_var}

    def forward_complexity(self, input_shape):
        n = 1
        for d in input_shape:
            n *= d
        return 8 * n  # mean/var/normalize/affine passes

    def param_count(self, input_shape):
        c = input_shape[_feature_axis(self.data_format)] if len(input_shape) == 3 else input_shape[0]
        return 2 * c if self.affine else 0

    def get_config(self):
        return {"type": self.type_name, "name": self.name,
                "num_features": self.num_features, "epsilon": self.epsilon,
                "momentum": self.momentum, "affine": self.affine,
                "data_format": self.data_format}


@register_layer("groupnorm")
class GroupNormLayer(ParameterizedLayer):
    """GroupNorm (reference ``groupnorm_layer.tpp``; eps 1e-5)."""

    def __init__(self, num_groups: int, num_channels: Optional[int] = None,
                 epsilon: float = 1e-5, affine: bool = True,
                 data_format: str = "NCHW", name: Optional[str] = None):
        super().__init__(name)
        self.num_groups = int(num_groups)
        self.num_channels = num_channels
        self.epsilon = float(epsilon)
        self.affine = bool(affine)
        self.data_format = data_format

    def init(self, key, input_shape):
        c = input_shape[_feature_axis(self.data_format)]
        if self.num_channels is not None and self.num_channels != c:
            raise ValueError(f"{self.name}: expected {self.num_channels} channels, got {c}")
        self.num_channels = c
        params = {"gamma": init.ones((c,)), "beta": init.zeros((c,))} if self.affine else {}
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        y = norm_ops.group_norm(
            x, params.get("gamma"), params.get("beta"), self.num_groups,
            eps=self.epsilon, data_format=self.data_format)
        return y, state

    def forward_complexity(self, input_shape):
        n = 1
        for d in input_shape:
            n *= d
        return 8 * n

    def param_count(self, input_shape):
        return 2 * input_shape[_feature_axis(self.data_format)] if self.affine else 0

    def get_config(self):
        return {"type": self.type_name, "name": self.name,
                "num_groups": self.num_groups, "num_channels": self.num_channels,
                "epsilon": self.epsilon, "affine": self.affine,
                "data_format": self.data_format}


class _Pool2DLayer(StatelessLayer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format: str = "NCHW", name: Optional[str] = None):
        super().__init__(name)
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride) if stride is not None else self.kernel_size
        self.padding = _pair(padding)
        self.data_format = data_format

    def output_shape(self, input_shape):
        if self.data_format == "NCHW":
            c, h, w = input_shape
            oh, ow = pool_ops.pool_output_shape((h, w), self.kernel_size, self.stride, self.padding)
            return (c, oh, ow)
        h, w, c = input_shape
        oh, ow = pool_ops.pool_output_shape((h, w), self.kernel_size, self.stride, self.padding)
        return (oh, ow, c)

    def forward_complexity(self, input_shape):
        out = self.output_shape(input_shape)
        n = 1
        for d in out:
            n *= d
        return n * self.kernel_size[0] * self.kernel_size[1]

    def get_config(self):
        return {"type": self.type_name, "name": self.name,
                "kernel_size": list(self.kernel_size), "stride": list(self.stride),
                "padding": list(self.padding), "data_format": self.data_format}


@register_layer("maxpool2d")
class MaxPool2DLayer(_Pool2DLayer):
    """Max pooling (reference ``maxpool2d_layer.tpp``; argmax cache replaced
    by the autodiff transpose of ``reduce_window``)."""

    def forward(self, x, *, training=False, rng=None):
        return pool_ops.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                                   data_format=self.data_format)


@register_layer("avgpool2d")
class AvgPool2DLayer(_Pool2DLayer):
    """Average pooling (reference ``avgpool2d_layer.tpp``)."""

    def forward(self, x, *, training=False, rng=None):
        return pool_ops.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                                   data_format=self.data_format)


@register_layer("dropout")
class DropoutLayer(StatelessLayer):
    """Inverted dropout with an explicit PRNG key (reference
    ``dropout_layer.tpp`` uses a seeded mask kernel; explicit keys are the
    functional equivalent)."""

    def __init__(self, rate: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.rate = float(rate)

    def forward(self, x, *, training=False, rng=None):
        if not training or self.rate <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: dropout in training mode needs an rng key")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    def forward_complexity(self, input_shape):
        n = 1
        for d in input_shape:
            n *= d
        return 2 * n

    def get_config(self):
        return {"type": self.type_name, "name": self.name, "rate": self.rate}


@register_layer("flatten")
class FlattenLayer(StatelessLayer):
    """Flatten per-sample dims (reference ``flatten_layer.tpp`` — shape-only)."""

    def forward(self, x, *, training=False, rng=None):
        return x.reshape(x.shape[0], -1)

    def output_shape(self, input_shape):
        n = 1
        for d in input_shape:
            n *= d
        return (n,)


@register_layer("activation")
class ActivationLayer(StatelessLayer):
    """Standalone activation (reference ``activation_layer.tpp`` +
    ``ActivationFactory``)."""

    def __init__(self, activation: str = "relu", negative_slope: float = 0.01,
                 alpha: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.activation = activation.lower()
        self.negative_slope = float(negative_slope)
        self.alpha = float(alpha)
        if self.activation not in act_ops.ACTIVATIONS:
            raise ValueError(f"unknown activation {activation!r}")

    def forward(self, x, *, training=False, rng=None):
        if self.activation == "leaky_relu":
            return act_ops.leaky_relu(x, self.negative_slope)
        if self.activation == "elu":
            return act_ops.elu(x, self.alpha)
        return act_ops.ACTIVATIONS[self.activation](x)

    def forward_complexity(self, input_shape):
        n = 1
        for d in input_shape:
            n *= d
        return n

    def get_config(self):
        return {"type": self.type_name, "name": self.name,
                "activation": self.activation,
                "negative_slope": self.negative_slope, "alpha": self.alpha}


@register_layer("log_softmax")
class LogSoftmaxLayer(StatelessLayer):
    """Log-softmax output layer pairing with ``log_softmax_cross_entropy``
    (reference models end with activation "softmax"/log-softmax before the
    LogSoftmaxCE loss, ``example_models.hpp``)."""

    def forward(self, x, *, training=False, rng=None):
        return jax.nn.log_softmax(x, axis=-1)
