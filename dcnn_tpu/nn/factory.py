"""String-keyed layer registry + JSON config materialization.

Reference equivalent: ``LayerFactory`` (``include/nn/layers.hpp:115-296``) —
the registry that lets a pipeline worker materialize its stage model from a
JSON config message (``pipeline_stage.hpp:231-289``). Same role here: the
pipeline coordinator ships ``Sequential.get_config()`` dicts; workers rebuild
with ``layer_from_config``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Type

from .layer import Layer

_REGISTRY: Dict[str, Type[Layer]] = {}


def register_layer(type_name: str) -> Callable[[Type[Layer]], Type[Layer]]:
    def deco(cls: Type[Layer]) -> Type[Layer]:
        cls.type_name = type_name
        _REGISTRY[type_name] = cls
        return cls
    return deco


def layer_from_config(cfg: Dict[str, Any]) -> Layer:
    ty = cfg.get("type")
    if ty not in _REGISTRY:
        raise ValueError(f"unknown layer type {ty!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[ty].from_config(cfg)


class LayerFactory:
    """Class-style façade over the registry (reference API shape)."""

    @staticmethod
    def create(cfg: Dict[str, Any]) -> Layer:
        return layer_from_config(cfg)

    @staticmethod
    def registered() -> Dict[str, Type[Layer]]:
        return dict(_REGISTRY)
