"""Layer base classes.

Reference equivalent: ``Layer<T>`` (``include/nn/layers_impl/base_layer.hpp:37``)
with virtual ``forward/backward(…, micro_batch_id)``, ``parameters()``/
``gradients()``, FLOP estimators ``forward_complexity``/``backward_complexity``
(consumed by the partitioner), ``compute_output_shape``, clone/serialize, and
the ``ParameterizedLayer``/``StatelessLayer`` split
(``parameterized_layer.hpp:17-29``, ``stateless_layer.hpp``).

TPU-native differences:

- A layer is an immutable spec. ``init(key, input_shape)`` returns
  ``(params, state)`` pytrees; ``apply(params, state, x, training, rng)``
  returns ``(y, new_state)`` and is pure/jittable.
- No ``backward``: ``jax.vjp(apply)`` is the backward. The reference's
  per-microbatch caches (conv col buffers, pool argmax, BN saved stats —
  SURVEY.md §1 "Microbatch-ID plumbing") become vjp residuals owned by the
  pipeline schedule, not the layer.
- Shapes are per-sample (no batch dim): ``(C, H, W)`` for image layers,
  ``(features,)`` after Flatten — same convention the reference's
  SequentialBuilder uses for shape inference.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax

Params = Dict[str, Any]
State = Dict[str, Any]
Shape = Tuple[int, ...]


class Layer:
    """Immutable layer spec; subclasses define init/apply/output_shape."""

    # registry key; subclasses override (reference LayerFactory keys, layers.hpp:115)
    type_name: str = "layer"

    def __init__(self, name: Optional[str] = None):
        self.name = name or self.type_name

    # -- functional interface --
    def init(self, key: jax.Array, input_shape: Shape) -> Tuple[Params, State]:
        del key, input_shape
        return {}, {}

    def apply(
        self,
        params: Params,
        state: State,
        x: jax.Array,
        *,
        training: bool = False,
        rng: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, State]:
        raise NotImplementedError

    # -- shape / cost metadata --
    def output_shape(self, input_shape: Shape) -> Shape:
        return input_shape

    def forward_complexity(self, input_shape: Shape) -> int:
        """Per-sample forward FLOP estimate (reference
        ``base_layer.hpp:60-66``); drives the FLOP-balanced partitioner."""
        del input_shape
        return 0

    def backward_complexity(self, input_shape: Shape) -> int:
        # Backward ≈ 2× forward for conv/dense (two GEMMs vs one); subclasses
        # with a better estimate override.
        return 2 * self.forward_complexity(input_shape)

    def param_count(self, input_shape: Shape) -> int:
        return 0

    # -- config round-trip (reference LayerConfig JSON, layers.hpp:21-113) --
    def get_config(self) -> Dict[str, Any]:
        return {"type": self.type_name, "name": self.name}

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "Layer":
        kwargs = {k: v for k, v in cfg.items() if k != "type"}
        return cls(**kwargs)

    def __repr__(self) -> str:
        cfg = {k: v for k, v in self.get_config().items() if k not in ("type", "name")}
        args = ", ".join(f"{k}={v}" for k, v in cfg.items())
        return f"{type(self).__name__}({args})"


class ParameterizedLayer(Layer):
    """Marker base for layers owning trainable parameters
    (reference ``parameterized_layer.hpp:17``)."""

    has_params = True


class StatelessLayer(Layer):
    """Marker base for layers with neither params nor state
    (reference ``stateless_layer.hpp``)."""

    has_params = False

    def apply(self, params, state, x, *, training=False, rng=None):
        return self.forward(x, training=training, rng=rng), state

    def forward(self, x: jax.Array, *, training: bool = False,
                rng: Optional[jax.Array] = None) -> jax.Array:
        raise NotImplementedError
