"""Inference-time BatchNorm folding.

Reference context: the reference evaluates BN in inference mode as a
per-channel affine using running statistics
(``batchnorm_layer.tpp`` inference path); it never folds that affine into the
preceding convolution. Folding is the standard deployment transform: for a
Conv/Dense layer followed immediately by BatchNorm,

    y = BN(conv(x, W, b)) = conv(x, W * s) + (b - mu) * s + beta,
    s = gamma / sqrt(running_var + eps)

so the BN layer disappears entirely from the inference graph — one fewer
normalize pass per BN layer and a shorter op chain for XLA to schedule.

``fold_batchnorm`` walks a Sequential (recursing into ResidualBlock main and
shortcut paths), folds every (Conv2D|Dense) -> BatchNorm adjacency, and
returns a NEW (model, params, state) triple — the original objects are
untouched. BN layers not preceded by a foldable layer (e.g. after pooling)
are kept as-is. The transform is inference-only: the folded model has no
batch statistics to update, so training it would silently skip BN.
"""

from __future__ import annotations

import copy
from typing import Any, List, Sequence, Tuple

import jax.numpy as jnp

from .factory import layer_from_config
from .layers import BatchNormLayer, Conv2DLayer, DenseLayer
from .residual import ResidualBlock
from .sequential import Sequential


def _bn_scale_shift(bn: BatchNormLayer, bn_params, bn_state):
    rm = jnp.asarray(bn_state["running_mean"], jnp.float32)
    rv = jnp.asarray(bn_state["running_var"], jnp.float32)
    c = rm.shape[0]
    gamma = jnp.asarray(bn_params.get("gamma", jnp.ones((c,))), jnp.float32)
    beta = jnp.asarray(bn_params.get("beta", jnp.zeros((c,))), jnp.float32)
    s = gamma / jnp.sqrt(rv + bn.epsilon)
    return s, beta - rm * s


def _fold_pair(layer, lp, bn: BatchNormLayer, bn_params, bn_state):
    """Fold BN into the preceding conv/dense; returns (new_layer, new_params).
    The folded layer always carries a bias (the BN shift lands there)."""
    s, shift = _bn_scale_shift(bn, bn_params, bn_state)
    w = jnp.asarray(lp["w"], jnp.float32)
    scale = s.reshape((-1,) + (1,) * (w.ndim - 1))  # out axis leads for both
    new_w = (w * scale).astype(lp["w"].dtype)
    b = jnp.asarray(lp["b"], jnp.float32) if "b" in lp else jnp.zeros_like(s)
    new_b = (b * s + shift).astype(new_w.dtype)
    cfg = layer.get_config()
    cfg["use_bias"] = True
    new_layer = layer_from_config(cfg)
    return new_layer, {"w": new_w, "b": new_b}


def _fold_list(layers: Sequence, params: Sequence, state: Sequence
               ) -> Tuple[List, List, List]:
    out_l: List[Any] = []
    out_p: List[Any] = []
    out_s: List[Any] = []
    i = 0
    while i < len(layers):
        layer, lp, ls = layers[i], params[i], state[i]
        nxt = layers[i + 1] if i + 1 < len(layers) else None
        if (isinstance(layer, (Conv2DLayer, DenseLayer))
                and isinstance(nxt, BatchNormLayer)):
            new_layer, new_p = _fold_pair(layer, lp, nxt,
                                          params[i + 1], state[i + 1])
            out_l.append(new_layer)
            out_p.append(new_p)
            out_s.append({})
            i += 2
            continue
        if isinstance(layer, ResidualBlock):
            ml, mp, ms = _fold_list(layer.layers, lp["main"], ls["main"])
            sl, sp, ss = _fold_list(layer.shortcut, lp["shortcut"],
                                    ls["shortcut"])
            out_l.append(ResidualBlock(ml, sl, activation=layer.activation,
                                       name=layer.name))
            out_p.append({"main": tuple(mp), "shortcut": tuple(sp)})
            out_s.append({"main": tuple(ms), "shortcut": tuple(ss)})
            i += 1
            continue
        # unchanged layer: rebuild from config so the folded model shares no
        # (mutable) layer objects with the original
        try:
            out_l.append(layer_from_config(layer.get_config()))
        except ValueError:
            # pass-through custom layer outside the factory registry: a
            # shallow copy keeps the folded graph independent without
            # refusing to fold the rest of the model (ADVICE r5)
            out_l.append(copy.copy(layer))
        out_p.append(lp)
        out_s.append(ls)
        i += 1
    return out_l, out_p, out_s


def fold_batchnorm(model: Sequential, params, state
                   ) -> Tuple[Sequential, Any, Any]:
    """Return (folded_model, folded_params, folded_state) with every
    (Conv2D|Dense)->BatchNorm pair collapsed into the linear layer.
    Inference-only (see module docstring); outputs match the original
    eval-mode model to float tolerance."""
    layers, new_p, new_s = _fold_list(model.layers, params, state)
    folded = Sequential(layers, name=f"{model.name}_folded",
                        input_shape=model.input_shape)
    return folded, tuple(new_p), tuple(new_s)
